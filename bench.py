#!/usr/bin/env python
"""OSU-style collective benchmark suite (BASELINE.md configs #1-#5).

Primary metric (the ONE printed JSON line, BASELINE.json config #3): bus
bandwidth of the framework's MPI_Allreduce path (coll/xla → ``lax.psum``
over the ICI mesh) at 16MB float32 vs raw hand-written ``jax.lax.psum`` —
``vs_baseline`` = framework / raw (north star ≥0.8 at ≥4MB).

Also runs (written to BENCH_SWEEP.json + BENCH_SWEEP.md, not the JSON
line):
  - allreduce latency + bus-bw sweep 8B→256MB (OSU osu_allreduce protocol)
  - bcast / allgather / reduce_scatter spot sizes (configs #4, #5)
  - persistent-collective (MPI_Allreduce_init analog) datapoint
  - 4-rank host-path ring smoke (config #1) when tpurun is runnable

Set OTPU_BENCH_FAST=1 to skip everything but the primary metric.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

# jax imports are DEFERRED into the functions that need them: the axon
# boot hook makes even `import jax` block on the TPU tunnel, and a bench
# that can hang forever is worse than one that reports honestly (see
# backend_available()).

SWEEP_SIZES = (8, 4096, 262144, 4 << 20, 16 << 20, 64 << 20, 256 << 20)
SPOT_SIZES = (4096, 4 << 20, 64 << 20)
PRIMARY = 16 << 20


def _bus_factor(coll: str, ndev: int) -> float:
    # OSU bus-bandwidth conventions per collective
    if ndev <= 1:
        return 1.0
    if coll in ("allreduce",):
        return 2.0 * (ndev - 1) / ndev
    return (ndev - 1) / ndev


def _clamp_iters(iters: int, pilot_s: float) -> int:
    """Adaptive sampling: a healthy chip keeps the full iteration
    count; a degraded tunnel (100ms-10s RTT) still produces a
    bounded-time row instead of an hours-long stall the driver can
    only kill (rounds 3-4 lost ALL device rows that way)."""
    budget = float(os.environ.get("OTPU_BENCH_ROW_BUDGET_S", "45"))
    return max(3, min(iters, int(budget / max(pilot_s, 1e-9))))


def _time_fn(fn, arg, iters=10, warmup=2):
    import jax

    for _ in range(warmup):
        out = fn(arg)
    jax.block_until_ready(out)
    # pilot: bound this measurement's wall time on a degraded tunnel
    t0 = time.perf_counter()
    jax.block_until_ready(fn(arg))
    iters = _clamp_iters(iters, time.perf_counter() - t0)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(arg)
        jax.block_until_ready(out)
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


class DeviceBench:
    def __init__(self):
        import jax
        from ompi_tpu.base.jaxenv import shard_map
        from jax.sharding import PartitionSpec as P

        self.devices = jax.devices()
        self.ndev = len(self.devices)
        self.mesh = jax.sharding.Mesh(np.array(self.devices), ("x",))
        self._P = P
        self._sm = shard_map

        import ompi_tpu
        from ompi_tpu.mca.coll.xla import XlaCollModule

        self.world = ompi_tpu.init()
        self.xla_mod = next(
            (m for m in self.world.coll_modules
             if isinstance(m, XlaCollModule)), None)
        if self.xla_mod is None:
            raise RuntimeError("coll/xla did not select on COMM_WORLD")

    def make(self, nbytes_per_rank: int):
        nelem = max(1, nbytes_per_rank // 4)
        return self.xla_mod.make_world_array(
            np.ones((self.world.size, nelem), np.float32))

    def raw_fn(self, coll: str):
        """Raw-XLA twin of each framework path, pinned to the IDENTICAL
        algorithm/program shape (a different shape makes the ratio
        meaningless as a dispatch-overhead guard — an earlier bcast
        baseline gathered n blocks to deliver one and made the
        framework look 1.5x 'faster')."""
        import jax
        import jax.numpy as jnp

        P, sm = self._P, self._sm
        n = self.ndev

        def bcast_body(t):   # the same two-regime selection as
            me = jax.lax.axis_index("x")     # xla.py bcast_array
            nbytes_payload = int(np.prod(t.shape[1:])) * t.dtype.itemsize
            if nbytes_payload >= (256 << 10):   # scatter+allgather
                contrib = jnp.where(me == 0, t[0], jnp.zeros_like(t[0]))
                flat = contrib.reshape(-1)
                blk = -(-flat.shape[0] // n)
                if blk * n != flat.shape[0]:
                    flat = jnp.pad(flat, (0, blk * n - flat.shape[0]))
                part = jax.lax.psum_scatter(flat.reshape(n, blk), "x",
                                            scatter_dimension=0,
                                            tiled=False)
                full = jax.lax.all_gather(part, "x")
                return full.reshape(-1)[:t[0].size].reshape(t.shape)
            rel = me % n
            cur = t
            k = 1
            while k < n:
                perm = [(i, i + k) for i in range(min(k, n - k))]
                recvd = jax.lax.ppermute(cur, "x", perm)
                newly = (rel >= k) & (rel < 2 * k)
                cur = jnp.where(newly, recvd, cur)
                k *= 2
            return cur

        bodies = {
            "allreduce": lambda t: jax.lax.psum(t[0], "x"),
            "bcast": bcast_body,
            "allgather": lambda t: jax.lax.all_gather(t[0], "x"),
        }
        out_specs = {"allreduce": P(), "bcast": P("x"), "allgather": P()}
        if coll == "reduce_scatter":
            def body(t):  # (1, n*S) -> (1, S)
                return jax.lax.psum_scatter(
                    t[0].reshape(self.ndev, -1), "x",
                    scatter_dimension=0, tiled=False)[None]
            return jax.jit(sm(body, mesh=self.mesh, in_specs=P("x"),
                              out_specs=P("x"), check_vma=False))
        return jax.jit(sm(bodies[coll], mesh=self.mesh, in_specs=P("x"),
                          out_specs=out_specs[coll], check_vma=False))

    def fw_fn(self, coll: str):
        w = self.world
        if coll == "reduce_scatter":
            # framework reduce_scatter wants (n, n, *S)
            return lambda x: w.reduce_scatter_array(x)
        return {
            "allreduce": lambda x: w.allreduce_array(x),
            "bcast": lambda x: w.bcast_array(x),
            "allgather": lambda x: w.allgather_array(x),
        }[coll]

    def _timed_pair(self, coll: str, fw, raw, x, xr, nbytes: int,
                    iters: int) -> dict:
        """ONE measurement protocol for every row: warmup, interleaved
        fw/raw samples (tunnel/clock drift hits both sides of a pair
        equally), medians + median pairwise ratio.  Shared so no row can
        drift onto a skewed protocol again (round 2's 'persistent slower
        than one-shot' artifact was exactly that)."""
        import jax

        out = fw(x)
        out2 = raw(xr)
        jax.block_until_ready((out, out2))   # compile round
        t0 = time.perf_counter()
        out = fw(x)
        out2 = raw(xr)
        jax.block_until_ready((out, out2))   # steady-state warmup pair
        iters = _clamp_iters(iters, time.perf_counter() - t0)
        fw_s, raw_s = [], []
        for i in range(iters):
            # alternate which side goes first: over a tunnel the second
            # call of a pair rides a warm connection, and a fixed order
            # would hand that advantage to one side systematically
            # (suspected in round 2's allgather-4MB 0.609 — fw and raw
            # compile to byte-identical programs there)
            first, second = (fw, raw) if i % 2 == 0 else (raw, fw)
            xa, xb = (x, xr) if i % 2 == 0 else (xr, x)
            t0 = time.perf_counter()
            jax.block_until_ready(first(xa))
            t1 = time.perf_counter()
            jax.block_until_ready(second(xb))
            t2 = time.perf_counter()
            if i % 2 == 0:
                fw_s.append(t1 - t0)
                raw_s.append(t2 - t1)
            else:
                raw_s.append(t1 - t0)
                fw_s.append(t2 - t1)
        fw_t, raw_t = statistics.median(fw_s), statistics.median(raw_s)
        pair_ratio = statistics.median(r / f_ for f_, r in zip(fw_s, raw_s))
        f = _bus_factor(coll.split("_")[0], self.ndev)
        return {
            "coll": coll, "nbytes": nbytes,
            "fw_lat_us": round(fw_t * 1e6, 2),
            "raw_lat_us": round(raw_t * 1e6, 2),
            "fw_bw_gbs": round(f * nbytes / fw_t / 1e9, 3),
            "raw_bw_gbs": round(f * nbytes / raw_t / 1e9, 3),
            "ratio": round(pair_ratio, 4),
        }

    def point(self, coll: str, nbytes: int, iters: int = 10) -> dict:
        if coll == "reduce_scatter":
            # (n, n, S): each rank contributes n blocks of nbytes/n
            nelem = max(self.ndev, nbytes // 4 // self.ndev * self.ndev)
            x = self.xla_mod.make_world_array(np.ones(
                (self.world.size, self.ndev, nelem // self.ndev),
                np.float32))
            xr = self.make(nbytes)
        else:
            x = xr = self.make(nbytes)
        return self._timed_pair(coll, self.fw_fn(coll), self.raw_fn(coll),
                                x, xr, nbytes, iters)

    def persistent_point(self, nbytes: int, iters: int = 40) -> dict:
        """MPI_Allreduce_init analog, measured by the same interleaved
        protocol as every other row."""
        x = self.make(nbytes)
        h = self.world.allreduce_array_init(x)
        return self._timed_pair("allreduce_persistent", h,
                                self.raw_fn("allreduce"), x, x, nbytes,
                                iters)


#: bf16 peak FLOP/s by device_kind substring (public TPU specs); f32
#: runs the MXU at half rate on these generations
_CHIP_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12), ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def _chip_peak_flops(device_kind: str, dtype: str = "bf16"):
    kind = (device_kind or "").lower()
    for pat, bf16 in _CHIP_PEAK_BF16:
        if pat in kind:
            return bf16 if dtype == "bf16" else bf16 / 2.0
    return None


def mfu_rows(sink=None) -> list:
    """Single-chip MFU rows — achieved FLOP/s ÷ chip peak for (a) the
    flagship train step (``__graft_entry__.entry``), (b) the pallas
    flash-attention block kernel vs its jnp twin, (c) the MXU matmul
    the fused GEMM-overlap kernel builds on.  The op/avx discipline
    (``ompi/mca/op/avx/op_avx_functions.c``): keep the math at hardware
    peak, and measure that claim.  Train-step FLOPs come from XLA's
    cost analysis (not hand math); the pallas kernel's inner FLOPs are
    invisible to XLA and use the closed-form attention count.  Off-TPU
    the peak is unknowable: rows carry grade=dryrun and ``mfu: null``.
    """
    from ompi_tpu.base.jaxenv import apply_platform_env

    apply_platform_env()   # JAX_PLATFORMS=cpu must beat any boot hook
    import jax
    import jax.numpy as jnp

    rows = []
    kind = getattr(jax.devices()[0], "device_kind", "?")
    on_tpu = jax.default_backend() == "tpu"
    grade = "device" if on_tpu else "dryrun"

    def row(name, flops, secs, dtype, extra=None):
        peak = _chip_peak_flops(kind, dtype) if on_tpu else None
        achieved = flops / secs
        r = {"metric": name, "grade": grade, "device_kind": kind,
             "tflops": round(achieved / 1e12, 3),
             "model_flops": int(flops),
             "lat_us": round(secs * 1e6, 1),
             "mfu": round(achieved / peak, 4) if peak else None}
        if peak:
            r["peak_tflops_assumed"] = round(peak / 1e12, 1)
        if extra:
            r.update(extra)
        rows.append(r)
        if sink is not None:   # stream: a later-row stall must not
            sink(r)            # lose the rows already measured
        return r

    # (a) flagship train step at bench scale: same program as the
    # driver contract (__graft_entry__.entry -> parallel.dryrun), with
    # OTPU_MODEL_SCALE raising the width/seq dims to MXU-saturating
    # sizes — tracing-scale shapes would measure dispatch, not FLOPs
    old_scale = os.environ.get("OTPU_MODEL_SCALE")
    try:
        os.environ["OTPU_MODEL_SCALE"] = os.environ.get(
            "OTPU_BENCH_MODEL_SCALE", "64" if on_tpu else "4")
        scale = int(os.environ["OTPU_MODEL_SCALE"])
        from ompi_tpu.parallel.dryrun import make_step_and_args

        fn, example_args, _ = make_step_and_args(jax.devices()[:1])
        jfn = jax.jit(fn)
        ca = jfn.lower(*example_args).compile().cost_analysis() or {}
        if isinstance(ca, list):   # pre-0.9 jax: list of per-device dicts
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        t = _time_fn(lambda a: jfn(*a), example_args, iters=10)
        # f32 params, but JAX default matmul precision runs one bf16
        # MXU pass per f32 matmul on TPU — bf16 peak is the roofline
        row("mfu_train_step", flops, t, "bf16",
            extra={"model_scale": scale,
                   "matmul_precision": "default (bf16 MXU passes)"})
        # the bf16 compute-dtype mode (half-width activations, per-block
        # param casts): the achievable-MFU row for production configs
        from ompi_tpu.base.var import registry as _reg

        _cd = _reg.lookup("otpu_parallel_compute_dtype")
        _old_cd = _cd.value
        try:
            _cd.set("bfloat16")
            fnb, args_b, _ = make_step_and_args(jax.devices()[:1])
            jfnb = jax.jit(fnb)
            cab = jfnb.lower(*args_b).compile().cost_analysis() or {}
            if isinstance(cab, list):
                cab = cab[0] if cab else {}
            tb = _time_fn(lambda a: jfnb(*a), args_b, iters=10)
            row("mfu_train_step_bf16", float(cab.get("flops", 0.0)), tb,
                "bf16", extra={"model_scale": scale,
                               "vs_f32_speedup": round(t / tb, 3)})
        finally:
            _cd.set(_old_cd)
    except Exception as exc:
        print(f"mfu: train step failed: {exc}", file=sys.stderr)
    finally:
        if old_scale is None:
            os.environ.pop("OTPU_MODEL_SCALE", None)
        else:
            os.environ["OTPU_MODEL_SCALE"] = old_scale

    # (b) flash-attention block kernel vs the jnp twin it replaces
    try:
        from ompi_tpu.ops import flash_attention as fa

        b_, h, sq, skv, d = (4, 8, 2048, 2048, 128) if on_tpu \
            else (1, 2, 256, 256, 128)   # interpreter is ~1000x slower
        key = jax.random.PRNGKey(0)
        dt = jnp.bfloat16 if on_tpu else jnp.float32
        q = jax.random.normal(key, (b_, h, sq, d), dt)
        k = jax.random.normal(key, (b_, h, skv, d), dt)
        v = jax.random.normal(key, (b_, h, skv, d), dt)
        m0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)
        num0 = jnp.zeros(q.shape, jnp.float32)
        den0 = jnp.zeros(q.shape[:-1], jnp.float32)
        # 2 MXU matmuls (qk^T, pv): 2 * 2*sq*skv*d each, per (b, h)
        flops = 4.0 * b_ * h * sq * skv * d
        flash = jax.jit(lambda a: fa.flash_block_update(*a))
        t_flash = _time_fn(flash, (q, k, v, m0, num0, den0), iters=10)
        jnp_twin = jax.jit(lambda a: fa._update_jnp(*a))
        t_jnp = _time_fn(jnp_twin, (q, k, v, m0, num0, den0), iters=10)
        row("mfu_flash_attention", flops, t_flash,
            "bf16" if on_tpu else "f32",
            extra={"vs_jnp_speedup": round(t_jnp / t_flash, 3)})
        # causal variant: same kernel + fused additive bias; ~half the
        # scores are masked so model FLOPs halve (the MXU still runs
        # the full tiles — mfu reflects achieved useful FLOPs)
        bias = jnp.where(jnp.arange(sq)[:, None] >= jnp.arange(skv)[None, :],
                         0.0, -jnp.inf).astype(jnp.float32)
        flash_c = jax.jit(lambda a: fa.flash_block_update_biased(*a))
        t_c = _time_fn(flash_c, (q, k, v, m0, num0, den0, bias),
                       iters=10)
        row("mfu_flash_attention_causal", flops / 2.0, t_c,
            "bf16" if on_tpu else "f32",
            extra={"vs_dense_flash": round(t_flash / t_c, 3)})
    except Exception as exc:
        print(f"mfu: flash attention failed: {exc}", file=sys.stderr)

    # (c) the MXU phase of the fused GEMM-overlap kernel: a plain bf16
    # matmul at benchmark size is its compute roofline
    try:
        mm = 4096 if on_tpu else 1024
        a = jnp.ones((mm, mm), jnp.bfloat16)
        bmat = jnp.ones((mm, mm), jnp.bfloat16)
        f = jax.jit(lambda ab: ab[0] @ ab[1])
        t = _time_fn(f, (a, bmat), iters=10)
        row("mfu_matmul_bf16", 2.0 * mm ** 3, t, "bf16",
            extra={"dim": mm})
    except Exception as exc:
        print(f"mfu: matmul failed: {exc}", file=sys.stderr)
    return rows


def mfu_rows_subprocess() -> list:
    """Run ``--mfu`` in a fresh CPU-pinned interpreter and parse its
    JSON lines — the tunnel-down-safe path (the parent process must
    never import jax when the accelerator may hang the import)."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mfu"],
            env=env, cwd=here, capture_output=True, text=True,
            timeout=900)
        return [json.loads(ln) for ln in proc.stdout.splitlines()
                if ln.startswith("{")]
    except Exception as exc:
        print(f"mfu subprocess failed: {exc}", file=sys.stderr)
        return []


def host_ring_smoke() -> dict:
    """BASELINE config #1: 4-rank ring over the host path (tpurun)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "4",
         sys.executable, os.path.join(here, "examples", "ring.py")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    dt = time.perf_counter() - t0
    return {"coll": "ring_4rank_host", "ok": proc.returncode == 0,
            "wall_s": round(dt, 2)}


_HOST_OSU = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu

w = ompi_tpu.init()
out = []
for nbytes in (4096, 262144, 4 << 20):
    x = np.ones(nbytes // 4, np.float32)
    for _ in range(3):
        w.allreduce(x)
    lat = []
    iters = 20 if nbytes <= 262144 else 8
    for _ in range(iters):
        w.barrier()
        t0 = time.perf_counter()
        w.allreduce(x)
        lat.append(time.perf_counter() - t0)
    out.append((nbytes, statistics.median(lat)))
if w.rank == 0:
    print("OSU_HOST " + json.dumps(out))
ompi_tpu.finalize()
"""


def host_allreduce_points(n: int = 4) -> list:
    """BASELINE config #2: OSU allreduce over the host path (pml/sm +
    coll/tuned ladder), n CPU ranks under tpurun."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_HOST_OSU)
        script = f.name
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
             sys.executable, script],
            capture_output=True, text=True, timeout=240,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        line = next((ln for ln in proc.stdout.splitlines()
                     if "OSU_HOST" in ln), None)
        if proc.returncode or line is None:
            print(f"host allreduce bench failed (rc={proc.returncode}):\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return [{"coll": "allreduce_host_tuned", "ok": False}]
        pts = _json.loads(line.split("OSU_HOST ", 1)[1])
        f_bus = _bus_factor("allreduce", n)
        return [{"coll": "allreduce_host_tuned", "nbytes": nb,
                 "fw_lat_us": round(t * 1e6, 1),
                 "fw_bw_gbs": round(f_bus * nb / t / 1e9, 4)}
                for nb, t in pts]
    finally:
        os.unlink(script)


_RGET_BW = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu

w = ompi_tpu.init()
out = []
WINDOW = 4
for nbytes in (4 << 20, 16 << 20):
    x = np.ones(nbytes, np.uint8)
    bufs = [np.empty_like(x) for _ in range(WINDOW)]
    ack = np.zeros(1, np.float64)
    def once():
        if w.rank == 0:
            reqs = [w.isend(x, dest=1, tag=9) for _ in range(WINDOW)]
            for r in reqs:
                r.wait()
            w.recv(ack, source=1, tag=10)
        else:
            reqs = [w.irecv(bufs[i], source=0, tag=9)
                    for i in range(WINDOW)]
            for r in reqs:
                r.wait()
            w.send(ack, dest=0, tag=10)
    for _ in range(2):
        once()
    iters = 6 if nbytes <= (4 << 20) else 4
    ts = []
    for _ in range(iters):
        w.barrier()
        t0 = time.perf_counter()
        once()
        ts.append(time.perf_counter() - t0)
    t = statistics.median(ts)
    out.append((nbytes, WINDOW * nbytes / t / 1e9))
if w.rank == 0:
    print("RGET_BW " + json.dumps(out))
ompi_tpu.finalize()
"""


def host_rget_points() -> list:
    """RGET-vs-FRAG isolation rows (pml_ob1_sendreq.h:375-401): 2-rank
    OSU-style pt2pt bandwidth at 4MB/16MB over btl/sm (true one-sided
    segment pull) and btl/tcp via --fake-nodes (pull emulation), each
    measured with the RGET protocol forced ON (rget_limit 512k) and OFF
    (rget_limit 0 -> RNDV FRAG stream).  Striping is disabled so ONE
    transport carries the message and the protocol delta is isolated."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_RGET_BW)
        script = f.name
    rows = []
    try:
        bw = {}   # (transport, proto) -> {nbytes: GB/s}
        for transport in ("sm", "tcp"):
            for proto, limit in (("rget", "512k"), ("frag", "0")):
                cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                       "-n", "2",
                       "--mca", "pml_ob1_rget_limit", limit,
                       "--mca", "pml_ob1_stripe", "0"]
                if transport == "tcp":
                    # emulation is gated off by default (measured slower
                    # than FRAG); force it so the row keeps documenting
                    # the crossover
                    cmd += ["--fake-nodes", "2",
                            "--mca", "pml_ob1_rget_emulate", "1"]
                cmd += [sys.executable, script]
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=300,
                    env=dict(os.environ, JAX_PLATFORMS="cpu"))
                line = next((ln for ln in proc.stdout.splitlines()
                             if "RGET_BW" in ln), None)
                if proc.returncode or line is None:
                    print(f"rget bench ({transport},{proto}) failed "
                          f"(rc={proc.returncode}):\n"
                          f"{proc.stderr[-1500:]}", file=sys.stderr)
                    continue
                pts = _json.loads(line.split("RGET_BW ", 1)[1])
                bw[(transport, proto)] = {nb: g for nb, g in pts}
                rows.extend(
                    {"coll": f"pt2pt_{transport}_{proto}", "nbytes": nb,
                     "fw_bw_gbs": round(g, 4)} for nb, g in pts)
        for transport in ("sm", "tcp"):
            r_on = bw.get((transport, "rget"), {})
            r_off = bw.get((transport, "frag"), {})
            rows.extend(
                {"coll": f"rget_speedup_{transport}", "nbytes": nb,
                 "ratio": round(r_on[nb] / r_off[nb], 3)}
                for nb in r_on if r_off.get(nb))
    finally:
        os.unlink(script)
    return rows


_PART_PP = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu

w = ompi_tpu.init()
out = []
for nbytes, parts in ((65536, 4), (1 << 20, 4), (1 << 20, 16)):
    n = nbytes // 8
    x = np.ones(n, np.float64)
    y = np.empty(n, np.float64)
    if w.rank == 0:
        s = w.psend_init(x, parts, dest=1, tag=5)
        r = w.precv_init(y, parts, source=1, tag=6)
    else:
        r = w.precv_init(y, parts, source=0, tag=5)
        s = w.psend_init(x, parts, dest=0, tag=6)
    def once():
        if w.rank == 0:
            s.start()
            for p in range(parts):
                s.pready(p)
            s.wait()
            r.start(); r.wait()
        else:
            r.start(); r.wait()
            s.start()
            for p in range(parts):
                s.pready(p)
            s.wait()
    for _ in range(3):
        once()
    iters = 20 if nbytes <= 65536 else 8
    lat = []
    for _ in range(iters):
        w.barrier()
        t0 = time.perf_counter()
        once()
        lat.append(time.perf_counter() - t0)
    out.append((nbytes, parts, statistics.median(lat)))
if w.rank == 0:
    print("PART_PP " + json.dumps(out))
ompi_tpu.finalize()
"""


def host_part_points() -> list:
    """MPI-4 partitioned ping-pong (mca/part/persist over pml/sm):
    message size x partition count, full round trip per iteration.  The
    partitions-vs-latency delta is the per-Pready framing cost; the
    same size at 4 vs 16 partitions bounds the aggregation overhead."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_PART_PP)
        script = f.name
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
             sys.executable, script],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        line = next((ln for ln in proc.stdout.splitlines()
                     if "PART_PP" in ln), None)
        if proc.returncode or line is None:
            print(f"partitioned pingpong bench failed "
                  f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}",
                  file=sys.stderr)
            return [{"coll": "part_pingpong", "ok": False}]
        pts = _json.loads(line.split("PART_PP ", 1)[1])
        # round trip moves nbytes each way: bandwidth = 2*nbytes/t
        return [{"coll": f"part_pingpong_{parts}p", "nbytes": nb,
                 "fw_lat_us": round(t * 1e6, 1),
                 "fw_bw_gbs": round(2 * nb / t / 1e9, 4)}
                for nb, parts, t in pts]
    finally:
        os.unlink(script)


_SERVING = """
import json, sys
import ompi_tpu
from ompi_tpu.serving import ContinuousBatchScheduler, Router, ShardWorker
from ompi_tpu.serving.driver import PoissonDriver

mode = sys.argv[1]
w = ompi_tpu.init()
if w.rank == 0:
    sched = ContinuousBatchScheduler(max_batch=8,
                                     max_batch_tokens=1 << 14, slots=8)
    r = Router(w, scheduler=sched, stages=(mode == "stages"),
               decode_chunk=4, kv_elems=256)
    rep = PoissonDriver(rate_rps=300.0, n_requests=96,
                        prompt_lens=(8, 64), decode_lens=(4, 24),
                        seed=5).run(r, max_wall_s=150)
    r.shutdown()
    print("SERVING " + json.dumps(rep), flush=True)
elif mode == "stages" and w.rank == 1:
    ShardWorker(w, router=0, role="prefill", peer=2, slots=8,
                kv_elems=256).serve()
elif mode == "stages" and w.rank == 2:
    ShardWorker(w, router=0, role="decode", peer=1, slots=8,
                kv_elems=256, kv_partitions=16).serve()
else:
    ShardWorker(w, router=0).serve()
ompi_tpu.finalize()
"""


def serving_rows() -> list:
    """The heavy-traffic serving benchmark (ROADMAP item 3): a Poisson
    open-loop driver against the continuous-batching engine — router +
    2 workers, colocated AND disaggregated (KV slabs over partitioned
    requests) — reporting p50/p99 request latency from the otpu-trace
    log2 histograms and decoded tokens/sec.  A queueing benchmark, not
    a ping-pong: latency includes admission waiting, which is why it is
    a new surface next to the OSU-style sweeps."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_SERVING)
        script = f.name
    rows = []
    try:
        for mode in ("colocated", "stages"):
            with tempfile.TemporaryDirectory() as td:
                proc = subprocess.run(
                    [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                     "-n", "3",
                     "--mca", "otpu_trace_enable", "1",
                     "--mca", "otpu_trace_requests", "1",
                     "--mca", "otpu_trace_dir", td,
                     sys.executable, script, mode],
                    capture_output=True, text=True, timeout=300,
                    env=dict(os.environ, JAX_PLATFORMS="cpu"))
                line = next((ln for ln in proc.stdout.splitlines()
                             if "SERVING " in ln), None)
                if proc.returncode or line is None:
                    print(f"serving bench ({mode}) failed "
                          f"(rc={proc.returncode}):\n"
                          f"{proc.stderr[-2000:]}",
                          file=sys.stderr)
                    rows.append({"coll": f"serving_poisson_{mode}",
                                 "ok": False})
                    continue
                rep = _json.loads(line.split("SERVING ", 1)[1])
                row = {
                    "coll": f"serving_poisson_{mode}",
                    "nbytes": rep["requests"],
                    "p50_ms": rep["p50_ms"], "p99_ms": rep["p99_ms"],
                    "p99_exact_ms": rep["p99_exact_ms"],
                    "tokens_per_s": rep["tokens_per_s"],
                    "req_per_s": rep["req_per_s"],
                }
                row.update(_req_stage_medians(td))
                rows.append(row)
    finally:
        os.unlink(script)
    return rows


_FLEET = """
import json, sys
import ompi_tpu
from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                              ShardWorker)

w = ompi_tpu.init()
if w.rank == 0:
    fleet = FleetController(w, tenants={"ten_a": 2, "ten_b": 1})
    drv = MixedPoissonDriver({
        "ten_a": dict(model="m_a", rate_rps=300.0, n_requests=48,
                      prompt_lens=(8, 64), decode_lens=(4, 24),
                      prefixes=3, prefix_len=32),
        "ten_b": dict(model="m_b", rate_rps=200.0, n_requests=32,
                      prompt_lens=(8, 64), decode_lens=(4, 24),
                      prefixes=2, prefix_len=16),
    }, seed=5)
    rep = drv.run(fleet, max_wall_s=150)
    fleet.shutdown()
    print("FLEET " + json.dumps(rep), flush=True)
else:
    ShardWorker(w, router=0).serve()
ompi_tpu.finalize()
"""


def fleet_rows() -> list:
    """``bench.py --serving``'s fleet half: TWO model pools + TWO
    weighted tenants under the mixed-workload driver (shared prompt
    prefixes included, so the per-tenant numbers reflect prefix-aware
    routing).  One row per tenant — the per-tenant p99 IS the fleet's
    contract number (a blended percentile would hide one tenant
    starving) — plus the prefix-cache hit rate on each row."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_FLEET)
        script = f.name
    rows = []
    try:
        with tempfile.TemporaryDirectory() as td:
            proc = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "5",
                 "--pool", "m_a:1,2", "--pool", "m_b:3,4",
                 "--mca", "otpu_trace_enable", "1",
                 "--mca", "otpu_trace_requests", "1",
                 "--mca", "otpu_trace_dir", td,
                 sys.executable, script],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            line = next((ln for ln in proc.stdout.splitlines()
                         if "FLEET " in ln), None)
            if proc.returncode or line is None:
                print(f"fleet bench failed (rc={proc.returncode}):\n"
                      f"{proc.stderr[-2000:]}", file=sys.stderr)
                return [{"coll": "serving_fleet", "ok": False}]
            rep = _json.loads(line.split("FLEET ", 1)[1])
            stages = _req_stage_medians(td)
            for name, tr in sorted(rep["tenants"].items()):
                row = {
                    "coll": f"serving_fleet_{name}",
                    "nbytes": tr["requests"],
                    "p50_ms": tr["p50_ms"], "p99_ms": tr["p99_ms"],
                    "p99_exact_ms": tr["p99_exact_ms"],
                    "tokens_per_s": tr["tokens_per_s"],
                    "req_per_s": round(tr["requests"]
                                       / rep["elapsed_s"], 1),
                    "prefix_hit_rate": rep["prefix_hit_rate"],
                }
                # the fleet trace is one merged timeline over both
                # pools — the stage decomposition is fleet-wide, so
                # every tenant row carries the same medians
                row.update(stages)
                rows.append(row)
    finally:
        os.unlink(script)
    return rows


_SPEC = """
import json, sys, time
import ompi_tpu
from ompi_tpu.serving import Router, ShardWorker

k = int(sys.argv[1])
w = ompi_tpu.init()
if w.rank == 0:
    r = Router(w, workers=[1, 2], decode_chunk=8)
    # closed-loop saturation: every request is in the queue before the
    # first tick, so tokens/sec measures the decode engine, not the
    # arrival process (the open-loop Poisson rows are arrival-limited
    # and would read a multiplier of ~1.0 no matter what decode does)
    for i in range(16):
        r.submit(8, 32, rid=2000 + i, tenant="bench")
    t0 = time.perf_counter()
    done = r.serve_until_drained(max_ticks=200000)
    dt = time.perf_counter() - t0
    toks = sum(len(q.tokens) for q in done)
    assert len(done) == 16, len(done)
    r.shutdown()
    print("SPEC " + json.dumps(
        {"k": k, "tokens": toks, "elapsed_s": round(dt, 4),
         "tokens_per_s": round(toks / dt, 1)}), flush=True)
else:
    ShardWorker(w, router=0, spec_k=k).serve()
ompi_tpu.finalize()
"""

_OVERLOAD = """
import json
import ompi_tpu
from ompi_tpu.base.var import registry
from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                              ShardWorker)

w = ompi_tpu.init()
if w.rank == 0:
    registry.set("otpu_serving_slo_p99_ms", 800.0)
    fleet = FleetController(
        w, tenants={"int": 2, "bat": 1},
        autoscale=dict(poll_ticks=10**9, idle_patience=10**9),
        frontdoor=dict(queue_cap=6, backlog=3, retry_s=0.01,
                       hold_ticks=20, window=16))
    drv = MixedPoissonDriver({
        "int": dict(model="m_a", rate_rps=150, n_requests=28,
                    prompt_lens=(4, 8), decode_lens=(2, 4),
                    slo="interactive"),
        "bat": dict(model="m_a", rate_rps=400, n_requests=36,
                    prompt_lens=(4, 8), decode_lens=(6, 12),
                    slo="batch"),
    }, seed=13)
    rep = drv.run(fleet, max_wall_s=180, check_invariants=True)
    st = fleet.frontdoor.stats()
    fleet.shutdown()
    cls = rep["slo_classes"]
    print("OVERLOAD " + json.dumps(
        {"requests": rep["requests"], "elapsed_s": rep["elapsed_s"],
         "shed": rep["shed"], "retried": rep["retried"],
         "preempts": st["preempts"], "classes": cls}), flush=True)
else:
    ShardWorker(w, router=0).serve()
ompi_tpu.finalize()
"""


def frontdoor_rows() -> list:
    """``bench.py --serving``'s front-door half (ROADMAP item 5):

    * ``serving_spec_k{0,4}``: the speculative-decoding A/B — the SAME
      closed-loop saturated workload on the SAME 2 chips, plain decode
      vs draft-propose/target-verify, plus the derived
      ``serving_spec_multiplier`` row (tokens/sec ratio; the pin says
      it must stay > 1 or speculation is a loss);
    * ``serving_overload_{interactive,batch}``: the sustained-overload
      contract — MixedPoissonDriver above pool capacity through the
      armed door, per-class exact p99 and the shed/retry ledger.

    Every row carries ``fd: True`` so the Poisson table renderer can
    route it to the front-door subsection."""
    import json as _json
    import subprocess
    import tempfile

    rows = []
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_SPEC)
        script = f.name
    reps = {}
    try:
        for k in (0, 4):
            proc = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                 "-n", "3", sys.executable, script, str(k)],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            line = next((ln for ln in proc.stdout.splitlines()
                         if "SPEC " in ln), None)
            if proc.returncode or line is None:
                print(f"spec bench (k={k}) failed "
                      f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}",
                      file=sys.stderr)
                rows.append({"coll": f"serving_spec_k{k}", "fd": True,
                             "ok": False})
                continue
            rep = _json.loads(line.split("SPEC ", 1)[1])
            reps[k] = rep
            rows.append({"coll": f"serving_spec_k{k}", "fd": True,
                         "nbytes": rep["tokens"],
                         "tokens_per_s": rep["tokens_per_s"],
                         "elapsed_s": rep["elapsed_s"]})
    finally:
        os.unlink(script)
    if 0 in reps and 4 in reps:
        mult = reps[4]["tokens_per_s"] / reps[0]["tokens_per_s"]
        rows.append({"coll": "serving_spec_multiplier", "fd": True,
                     "nbytes": reps[4]["tokens"],
                     "multiplier": round(mult, 2)})
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_OVERLOAD)
        script = f.name
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
             "--pool", "m_a:1,2", sys.executable, script],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        line = next((ln for ln in proc.stdout.splitlines()
                     if "OVERLOAD " in ln), None)
        if proc.returncode or line is None:
            print(f"overload bench failed (rc={proc.returncode}):\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            rows.append({"coll": "serving_overload", "fd": True,
                         "ok": False})
            return rows
        rep = _json.loads(line.split("OVERLOAD ", 1)[1])
        total = rep["requests"] + rep["shed"]
        for cls in ("interactive", "batch"):
            c = rep["classes"].get(cls)
            if c is None:
                continue
            rows.append({
                "coll": f"serving_overload_{cls}", "fd": True,
                "nbytes": c["requests"],
                "p50_ms": c["p50_ms"],
                "p99_exact_ms": c["p99_exact_ms"],
                "shed": c["shed"], "retried": c["retried"],
                "shed_rate": round(rep["shed"] / total, 4),
                "preempts": rep["preempts"],
            })
    finally:
        os.unlink(script)
    return rows


def _frontdoor_md_lines(fd_rows) -> list:
    lines = ["", "### Front door (overload shedding + speculative "
             "decode)", "",
             "`serving_spec_k*` is the closed-loop saturation A/B at "
             "matched chips (router + 2 workers, 16 requests queued "
             "up-front): plain decode pays one target pass per token, "
             "speculative decode verifies a k-token draft window per "
             "target pass — `serving_spec_multiplier` is the "
             "tokens/sec ratio and must stay > 1. "
             "`serving_overload_*` rows drive Poisson arrivals above "
             "pool capacity through the armed front door "
             "(`otpu_serving_slo_p99_ms` 800): per-SLO-class exact "
             "p99, requests shed at the door (each re-arrived after "
             "its retry-after), and batch preemptions.", "",
             "| row | n | tokens/s | mult | p50 ms | p99 exact ms | "
             "shed | retried | shed rate | preempts |",
             "|---|---|---|---|---|---|---|---|---|---|"]

    def _c(r, key, fmt="{}"):
        v = r.get(key)
        return fmt.format(v) if v is not None else "-"

    for r in fd_rows:
        if not r.get("ok", True):
            lines.append(f"| {r['coll']} | FAILED | - | - | - | - | - "
                         "| - | - | - |")
            continue
        lines.append(
            f"| {r['coll']} | {r.get('nbytes', '-')} | "
            f"{_c(r, 'tokens_per_s')} | {_c(r, 'multiplier')} | "
            f"{_c(r, 'p50_ms')} | {_c(r, 'p99_exact_ms')} | "
            f"{_c(r, 'shed')} | {_c(r, 'retried')} | "
            f"{_c(r, 'shed_rate')} | {_c(r, 'preempts')} |")
    return lines


def _req_stage_medians(trace_dir: str) -> dict:
    """Per-request stage medians from the per-rank traces a
    request-armed (``otpu_trace_requests``) serving run exported —
    the REAL ``otpu_analyze --requests`` decomposition over the
    merged timeline, not a shadow estimator in the bench script.
    Empty dict when the run produced no decomposable requests (the
    row simply doesn't grow the column; the pin test treats that as
    a regression)."""
    from ompi_tpu.tools import otpu_analyze
    try:
        events = otpu_analyze.load_events([trace_dir])
    except (SystemExit, OSError, ValueError):
        return {}
    rep = otpu_analyze.requests_report(events)
    med = rep.get("stage_median_us") or {}
    if not med:
        return {}
    return {"stage_median_ms": {s: round(v / 1000.0, 3)
                                for s, v in med.items()},
            "req_decomposed": int(rep.get("decomposed", 0))}


def _stage_cell(r: dict) -> str:
    """Compact q/d/p/k/dec/str stage-median cell for the md table
    (absent stages — e.g. prefill/kv on a colocated row whose engine
    prefills inline — render as '-')."""
    from ompi_tpu.tools.otpu_analyze import REQ_STAGES
    med = r.get("stage_median_ms")
    if not med:
        return "-"
    return "/".join(f"{med[s]:g}" if s in med else "-"
                    for s in REQ_STAGES)


def _serving_md_section(rows) -> list:
    # front-door rows (speculative A/B, overload contract) carry a
    # different column set — route them to their own subsection instead
    # of KeyError-ing on p50_ms/p99_ms below
    fd_rows = [r for r in rows if r.get("fd")]
    rows = [r for r in rows if not r.get("fd")]
    lines = ["", "## Serving (Poisson open-loop, router + 2 workers)",
             "",
             "Request latency percentiles come from the otpu-trace "
             "log2 histogram estimator (`p99_exact` is the driver's "
             "own sample check); tokens/sec counts decoded tokens. "
             "Open-loop queueing numbers, not ping-pong latency. "
             "`serving_fleet_*` rows are PER TENANT from the two-pool "
             "/ two-tenant fleet run (weighted fair-share admission, "
             "prefix-aware routing — `pfx%` is the cache hit rate). "
             "`stage med ms` is the otpu-req per-request decomposition "
             "(queue/dispatch/prefill/kv/decode/stream medians from "
             "`otpu_analyze --requests` over the run's merged "
             "timeline; fleet rows share one fleet-wide cell).",
             "",
             "| mode | requests | p50 ms | p99 ms | p99 exact ms | "
             "tokens/s | req/s | pfx% | stage med ms (q/d/p/k/dec/str) |",
             "|---|---|---|---|---|---|---|---|---|"]
    for r in rows:
        if not r.get("ok", True):
            lines.append(f"| {r['coll']} | FAILED | - | - | - | - | "
                         "- | - | - |")
            continue
        pfx = r.get("prefix_hit_rate")
        pfx_s = f"{100.0 * pfx:.0f}%" if pfx is not None else "-"
        lines.append(
            f"| {r['coll']} | {r['nbytes']} | {r['p50_ms']} | "
            f"{r['p99_ms']} | {r['p99_exact_ms']} | "
            f"{r['tokens_per_s']} | {r['req_per_s']} | {pfx_s} | "
            f"{_stage_cell(r)} |")
    if fd_rows:
        lines += _frontdoor_md_lines(fd_rows)
    return lines


def _splice_md_section(md: str, heading_prefix: str,
                       new_lines: list) -> str:
    """Replace ONE '## ' section of the sweep markdown (matched by its
    heading prefix; appended at the end when absent), PRESERVING every
    later section — a plain partition-and-truncate silently deleted
    whatever another refresher had appended after the replaced heading
    (the --serving run ate the committed Recovery/Quant sections)."""
    head, sep, tail = md.partition("\n" + heading_prefix)
    rest = ""
    if sep:
        nxt = tail.find("\n## ")
        if nxt != -1:
            rest = tail[nxt:]
    return (head.rstrip("\n") + "\n" + "\n".join(new_lines) + "\n"
            + ("\n" + rest.strip("\n") + "\n" if rest.strip("\n")
               else ""))


def refresh_serving_tables() -> list:
    """``bench.py --serving``: run the serving rows and fold them into
    the committed sweep tables (replacing any previous serving rows) —
    the device/host rows are left untouched."""
    here = os.path.dirname(os.path.abspath(__file__))
    rows = serving_rows() + fleet_rows() + frontdoor_rows()
    # stage medians double as BENCH_HISTORY points so otpu_perf --diff
    # guards the per-stage numbers run over run (bench-kind rows need a
    # positive lat_us; zero-width stages just don't emit a point)
    hist: dict = {}
    for r in rows:
        if not r.get("ok", True):
            continue
        for s, v in (r.get("stage_median_ms") or {}).items():
            if v > 0:
                hist[f"serving_stage/{r['coll']}/{s}"] = {
                    "key": f"serving_stage/{r['coll']}/{s}",
                    "lat_us": round(1000.0 * v, 1),
                    "k": int(r.get("req_decomposed", 0))}
        # front-door points: us-per-token for the spec A/B legs (so the
        # rolling-min gate catches a decode-throughput regression) and
        # the overload interactive exact p99
        if r.get("fd") and r.get("tokens_per_s", 0) > 0:
            key = f"serving_spec/us_per_token/{r['coll']}"
            hist[key] = {"key": key,
                         "lat_us": round(1e6 / r["tokens_per_s"], 1),
                         "k": int(r.get("nbytes", 0))}
        if (r.get("coll") == "serving_overload_interactive"
                and r.get("p99_exact_ms", 0) > 0):
            key = "serving_overload/interactive_p99"
            hist[key] = {"key": key,
                         "lat_us": round(1000.0 * r["p99_exact_ms"], 1),
                         "k": int(r.get("nbytes", 0))}
    if hist:
        append_history(sorted(hist.values(), key=lambda h: h["key"]),
                       "bench", "host_serving")
    try:
        with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        payload = {"ndev": 0, "results": []}
    payload["results"] = [r for r in payload.get("results", [])
                          if not str(r.get("coll", "")).startswith(
                              "serving_")] + rows
    _atomic_write(os.path.join(here, "BENCH_SWEEP.json"),
                  json.dumps(payload, indent=1))
    # regenerate only the Serving section of the markdown table
    md_path = os.path.join(here, "BENCH_SWEEP.md")
    try:
        with open(md_path) as f:
            md = f.read()
    except OSError:
        md = "# Collective sweep\n"
    _atomic_write(md_path, _splice_md_section(
        md, "## Serving (Poisson open-loop",
        _serving_md_section(rows)))
    return rows


_RECOVERY = """
import json, sys
import ompi_tpu
from ompi_tpu.parallel.elastic import ElasticTrainer

w = ompi_tpu.init()
tr = ElasticTrainer(w, ckpt_dir=sys.argv[1], model_size=32,
                    global_batch=40, ckpt_every=4, respawn=False)
tr.train(20)
if tr.comm.rank == 0:
    print("RECOVERY " + json.dumps(tr.recoveries), flush=True)
ompi_tpu.finalize()
"""


def recovery_rows() -> list:
    """``bench.py --recovery``: detect→resume latency of the elastic
    train-through-failure loop.  One 5-rank job with a chaos kill
    schedule that fells three ranks at different steps — three full
    revoke→agree→shrink→restore recoveries — reporting p50/p99 of the
    end-to-end recovery time plus the median per-phase split.  The
    launcher-detection path (--enable-recovery), not the heartbeat
    ring, so the number is the runtime's recovery cost, not the
    detector timeout."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(_RECOVERY)
        script = f.name
    ckpt = tempfile.mkdtemp(prefix="otpu-recovery-")
    spec = "kill:rank=1,step=6;kill:rank=2,step=11;kill:rank=3,step=16"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "5",
             "--enable-recovery", "--mca", "otpu_chaos_spec", spec,
             sys.executable, script, ckpt],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        line = next((ln for ln in proc.stdout.splitlines()
                     if "RECOVERY " in ln), None)
        if line is None:
            print(f"recovery bench failed (rc={proc.returncode}):\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return [{"coll": "recovery_detect_to_resume", "ok": False}]
        recs = _json.loads(line.split("RECOVERY ", 1)[1])
        totals = sorted(r["total_ms"] for r in recs)
        phases = {}
        for ph in ("revoke", "agree", "shrink", "restore"):
            vals = sorted(r[ph + "_ms"] for r in recs if ph + "_ms" in r)
            if vals:
                phases[ph] = round(vals[len(vals) // 2], 3)
        return [{
            "coll": "recovery_detect_to_resume",
            "nbytes": len(totals),
            "p50_ms": round(totals[len(totals) // 2], 3),
            "p99_ms": round(totals[-1], 3),
            "min_ms": round(totals[0], 3),
            "phase_median_ms": phases,
        }]
    finally:
        import shutil

        os.unlink(script)
        shutil.rmtree(ckpt, ignore_errors=True)


def _recovery_md_section(rows) -> list:
    lines = ["", "## Recovery (elastic train-through-failure)",
             "",
             "Detect→resume latency of the full "
             "revoke→agree→shrink→restore recovery sequence "
             "(`bench.py --recovery`: 5-rank job, 3 chaos-scheduled "
             "rank kills).  Launcher detection; add the detector "
             "timeout for heartbeat-detected hangs.",
             "",
             "| rows | samples | p50 ms | p99 ms | min ms | "
             "phase medians (ms) |", "|---|---|---|---|---|---|"]
    for r in rows:
        if not r.get("ok", True):
            lines.append(f"| {r['coll']} | FAILED | - | - | - | - |")
            continue
        ph = "; ".join(f"{k}={v}" for k, v in
                       r.get("phase_median_ms", {}).items())
        lines.append(
            f"| {r['coll']} | {r['nbytes']} | {r['p50_ms']} | "
            f"{r['p99_ms']} | {r['min_ms']} | {ph} |")
    return lines


def refresh_recovery_tables() -> list:
    """``bench.py --recovery``: run the recovery rows and fold them
    into the committed sweep tables (replacing previous recovery rows);
    everything else is left untouched — the serving-table discipline."""
    here = os.path.dirname(os.path.abspath(__file__))
    rows = recovery_rows()
    try:
        with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        payload = {"ndev": 0, "results": []}
    payload["results"] = [r for r in payload.get("results", [])
                          if not str(r.get("coll", "")).startswith(
                              "recovery_")] + rows
    _atomic_write(os.path.join(here, "BENCH_SWEEP.json"),
                  json.dumps(payload, indent=1))
    md_path = os.path.join(here, "BENCH_SWEEP.md")
    try:
        with open(md_path) as f:
            md = f.read()
    except OSError:
        md = "# Collective sweep\n"
    _atomic_write(md_path, _splice_md_section(
        md, "## Recovery (elastic train-through-failure)",
        _recovery_md_section(rows)))
    return rows


_QUANT_WIRE = """
import json, time
import numpy as np
import ompi_tpu
from ompi_tpu.mca.coll import quant
from ompi_tpu.runtime import spc

w = ompi_tpu.init()
n = (4 << 20) // 4
base = np.stack([np.random.default_rng([7, r]).standard_normal(n)
                 for r in range(w.size)]).astype(np.float32)
mine = base[w.rank]
exact = base.astype(np.float64).sum(0)
w.barrier()
got = np.asarray(w.allreduce(mine))          # warm
reps = 3
t0 = time.perf_counter()
for _ in range(reps):
    got = np.asarray(w.allreduce(mine))
dt = (time.perf_counter() - t0) / reps
rel = float(np.max(np.abs(got - exact)) / max(1e-12,
                                              np.max(np.abs(exact))))
st = quant.wire_stats()
if w.rank == 0:
    print("QUANTWIRE " + json.dumps({
        "lat_us": round(dt * 1e6, 1),
        "eff_gbs": round(n * 4 / dt / 1e9, 4),
        "wire_orig": st["orig"], "wire_enc": st["enc"],
        "wire_saved": spc.read("quant_wire_bytes_saved"),
        "max_rel_err": rel}), flush=True)
ompi_tpu.finalize()
"""


def _quant_wire_rows() -> list:
    """Wire-path evidence: the 4MB host allreduce over loopback tcp
    (the PR 4 fastpath wire) with quantize-on-pack ON vs OFF — latency,
    effective GB/s, measured bytes-on-wire (orig vs encoded out of the
    codec stage's own accounting), and max relative error vs the f64
    exact sum.  rd forced so both runs move the same message pattern."""
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(_QUANT_WIRE)
        script = f.name
    rows = []
    try:
        for name, wire in (("quant_wire_off_4MB", "0"),
                           ("quant_wire_int8_4MB", "1")):
            proc = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                 "-n", "2", "--fake-nodes", "2",
                 "--mca", "otpu_coll_sm_coll_priority", "0",
                 "--mca", "otpu_coll_quant_wire", wire,
                 "--mca", "otpu_coll_tuned_allreduce_algorithm",
                 "recursive_doubling",
                 "--mca", "pml_ob1_stripe", "0",
                 "--mca", "pml_ob1_rget_limit", "0",
                 sys.executable, script],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            line = next((ln for ln in proc.stdout.splitlines()
                         if "QUANTWIRE " in ln), None)
            if proc.returncode or line is None:
                print(f"quant wire bench ({name}) failed "
                      f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}",
                      file=sys.stderr)
                rows.append({"coll": name, "ok": False})
                continue
            rep = json.loads(line.split("QUANTWIRE ", 1)[1])
            row = {"coll": name, "nbytes": 4 << 20}
            row.update(rep)
            if rep.get("wire_enc"):
                row["wire_ratio"] = round(rep["wire_orig"]
                                          / rep["wire_enc"], 2)
            rows.append(row)
    finally:
        os.unlink(script)
    return rows


def _quant_kv_row(codec: str = "int8") -> dict:
    """KV-slab evidence: encode+decode cost per 4096-elem block, the
    capacity multiplier (raw slot bytes / encoded slot bytes — the
    users-per-chip factor), and the codec's measured error."""
    import numpy as np

    from ompi_tpu.mca.coll import quant

    elems, reps = 4096, 64
    rng = np.random.default_rng(11)
    blocks = rng.standard_normal((reps, elems)).astype(np.float32)
    enc0 = quant.encode_f32(blocks[0], codec)
    t0 = time.perf_counter()
    worst = 0.0
    for i in range(reps):
        enc = quant.encode_f32(blocks[i], codec)
        dec = quant.decode_f32(enc, codec, elems)
        worst = max(worst, float(np.max(np.abs(dec - blocks[i]))
                                 / np.max(np.abs(blocks[i]))))
    dt = (time.perf_counter() - t0) / reps
    return {"coll": f"quant_kv_{codec}", "nbytes": elems * 4,
            "lat_us": round(dt * 1e6, 1),
            "enc_bytes": int(enc0.nbytes),
            "capacity_x": round(elems * 4 / enc0.nbytes, 2),
            "max_rel_err": worst}


_QUANT_DEVICE = """
import json, time
import numpy as np
import ompi_tpu

w = ompi_tpu.init()
n = (4 << 20) // 4
host = np.stack([np.random.default_rng([13, r]).standard_normal(n)
                 for r in range(w.size)]).astype(np.float32)
exact = host.astype(np.float64).sum(0)
import jax
xla = next(m for m in w.coll_modules
           if type(m).__name__ == "XlaCollModule")
rows = []
for name, budget in (("quant_device_off_4MB", None),
                     ("quant_device_int8_4MB", "0.02")):
    c = w.dup()
    if budget is not None:
        c.info.set("otpu_quant_budget", budget)
    x = next(m for m in c.coll_modules
             if type(m).__name__ == "XlaCollModule").make_world_array(host)
    out = np.asarray(c.allreduce_array(x))       # compile + warm
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jax.block_until_ready(c.allreduce_array(x))
    dt = (time.perf_counter() - t0) / reps
    rel = float(np.max(np.abs(np.asarray(out) - exact))
                / max(1e-12, np.max(np.abs(exact))))
    rows.append({"coll": name, "nbytes": n * 4,
                 "lat_us": round(dt * 1e6, 1),
                 "eff_gbs": round(n * 4 / dt / 1e9, 3),
                 "max_rel_err": rel})
print("QUANTDEV " + json.dumps(rows), flush=True)
ompi_tpu.finalize()
"""


def _quant_device_rows() -> list:
    """Device-tier rows — run ONLY after the device probe succeeds
    (the carried-forward-honesty rule: a fake-device run must never
    mint device rows; the CPU-side compile coverage lives in
    tests/test_quant.py's AOT gate instead)."""
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(_QUANT_DEVICE)
        script = f.name
    try:
        proc = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            timeout=600, env=dict(os.environ))
        line = next((ln for ln in proc.stdout.splitlines()
                     if "QUANTDEV " in ln), None)
        if proc.returncode or line is None:
            print(f"quant device bench failed (rc={proc.returncode}):"
                  f"\n{proc.stderr[-2000:]}", file=sys.stderr)
            return []
        return json.loads(line.split("QUANTDEV ", 1)[1])
    finally:
        os.unlink(script)


def quant_rows(probe_device: bool = True) -> list:
    """``bench.py --quant``: wire + KV rows always; device rows ONLY
    when the TPU probe answers (the tunnel has been down since round 5
    — emitting quant device rows from a CPU run would launder
    fake-device numbers into the carried-forward table)."""
    rows = _quant_wire_rows() + [_quant_kv_row("int8"),
                                 _quant_kv_row("bf16")]
    if probe_device:
        ok, detail = backend_available()
        if ok:
            rows += _quant_device_rows()
        else:
            print("quant: TPU probe failed — device rows NOT emitted "
                  f"(re-earn on hardware): {detail.splitlines()[0][:120]}",
                  file=sys.stderr)
    return rows


def _quant_md_section(rows) -> list:
    lines = ["", "## Quant (block-scale quantized collectives & KV)",
             "",
             "`bench.py --quant`: the coll/quant codec across its "
             "three datapaths.  Wire rows are the 4MB loopback-tcp "
             "host allreduce with quantize-on-pack off/on (`wire B` "
             "is measured bytes-on-wire out of the codec stage; the "
             "byte win pays on a real DCN wire — loopback moves at "
             "memcpy speed, so latency is codec-dominated there).  "
             "KV rows are per-block encode+decode cost and the slots-"
             "per-worker capacity multiplier.  Device rows appear "
             "ONLY when the TPU probe succeeds.",
             "",
             "| row | bytes | lat us | eff GB/s | wire B (orig→enc) | "
             "ratio/cap x | max rel err |",
             "|---|---|---|---|---|---|---|"]
    for r in rows:
        if not r.get("ok", True):
            lines.append(f"| {r['coll']} | FAILED | - | - | - | - | "
                         "- |")
            continue
        wire = (f"{r['wire_orig']}→{r['wire_enc']}"
                if r.get("wire_orig") else "-")
        factor = r.get("wire_ratio", r.get("capacity_x", "-"))
        lines.append(
            f"| {r['coll']} | {r.get('nbytes', '-')} | "
            f"{r.get('lat_us', '-')} | {r.get('eff_gbs', '-')} | "
            f"{wire} | {factor} | "
            f"{round(r['max_rel_err'], 6) if 'max_rel_err' in r else '-'} |")
    return lines


def refresh_quant_tables() -> list:
    """``bench.py --quant``: run the quant rows, fold them into the
    committed sweep tables (replacing previous quant rows — the
    serving-table discipline), and append the wire-on row as a
    BENCH_HISTORY point so ``otpu_perf --diff`` guards it."""
    here = os.path.dirname(os.path.abspath(__file__))
    rows = quant_rows()
    try:
        with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        payload = {"ndev": 0, "results": []}
    payload["results"] = [r for r in payload.get("results", [])
                          if not str(r.get("coll", "")).startswith(
                              "quant_")] + rows
    _atomic_write(os.path.join(here, "BENCH_SWEEP.json"),
                  json.dumps(payload, indent=1))
    md_path = os.path.join(here, "BENCH_SWEEP.md")
    try:
        with open(md_path) as f:
            md = f.read()
    except OSError:
        md = "# Collective sweep\n"
    _atomic_write(md_path, _splice_md_section(
        md, "## Quant (block-scale quantized collectives & KV)",
        _quant_md_section(rows)))
    hist = [{"key": r["coll"], "lat_us": r["lat_us"], "k": 3}
            for r in rows
            if r.get("ok", True) and r.get("lat_us")
            and str(r["coll"]).startswith("quant_wire_")]
    if hist:
        append_history(hist, "bench", "host_tcp_n2")
    return rows


_MOE_WORKER = """
import json, os, shutil, tempfile, time
import ompi_tpu
from ompi_tpu.parallel.elastic import ElasticTrainer
from ompi_tpu.parallel.moe import MoeTrainer

E, D, T, STEPS, WARM = 8, 32, 256, 24, 4
w = ompi_tpu.init()
# every rank must see the SAME checkpoint tree: derive it from the
# coord address (identical across ranks, unique per live job)
base = os.path.join(tempfile.gettempdir(), "otpu_moebench_"
                    + os.environ["OTPU_COORD"].replace(":", "_")
                    .replace("/", "_"))
if w.rank == 0:
    shutil.rmtree(base, ignore_errors=True)
    os.makedirs(base)
w.barrier()
tr = MoeTrainer(w, base + "/moe", n_experts=E, expert_dim=D,
                tokens_per_step=T, ckpt_every=1 << 30)
tr.train(WARM)
w.barrier(); t0 = time.perf_counter()
tr.train(WARM + STEPS)
w.barrier(); moe_s = time.perf_counter() - t0
rep = tr.report()
dn = ElasticTrainer(w, base + "/dense", model_size=E * D,
                    global_batch=T, ckpt_every=1 << 30)
dn.train(WARM)
w.barrier(); t0 = time.perf_counter()
dn.train(WARM + STEPS)
w.barrier(); dense_s = time.perf_counter() - t0
if w.rank == 0:
    rows = [
        {"coll": "moe_host_n2", "nbytes": T, "ok": True,
         "lat_us": round(moe_s / STEPS * 1e6, 1),
         "tokens_per_s": round(T * STEPS / moe_s, 1),
         "imbalance": rep["imbalance_max"],
         "dropped": rep["dropped"]},
        {"coll": "moe_dense_n2", "nbytes": T, "ok": True,
         "lat_us": round(dense_s / STEPS * 1e6, 1),
         "tokens_per_s": round(T * STEPS / dense_s, 1)},
    ]
    print("MOEBENCH " + json.dumps(rows))
ompi_tpu.finalize()
"""


def moe_rows(n: int = 2) -> list:
    """``bench.py --moe``: expert-parallel training throughput vs the
    dense trainer at MATCHED params (same weight count E*D, same token
    batch, same lr schedule) over one tpurun world — tokens/sec, the
    per-step latency, and the gating load-imbalance factor (a pure
    function of the seeded plan, so the committed value is exact, not
    a noisy measurement)."""
    return _run_history_worker(_MOE_WORKER, "MOEBENCH", n)


def _moe_md_section(rows) -> list:
    lines = ["", "## MoE (expert-parallel host trainer vs dense)",
             "",
             "`bench.py --moe`: the `parallel/moe` expert-parallel "
             "trainer (top-2 gating, capacity-factor dispatch over "
             "the ragged alltoallv/allgatherv tier) against the dense "
             "`parallel/elastic` trainer at matched parameter count "
             "and token batch.  `imbalance` is max-expert-load over "
             "mean — deterministic for the committed seed, so it is "
             "pinned exactly; latency/token rows carry the usual "
             "CI-host noise bands.",
             "",
             "| row | tokens | step us | tokens/s | imbalance | "
             "dropped |",
             "|---|---|---|---|---|---|"]
    for r in rows:
        if not r.get("ok", True):
            lines.append(f"| {r['coll']} | FAILED | - | - | - | - |")
            continue
        lines.append(
            f"| {r['coll']} | {r.get('nbytes', '-')} | "
            f"{r.get('lat_us', '-')} | {r.get('tokens_per_s', '-')} | "
            f"{r.get('imbalance', '-')} | {r.get('dropped', '-')} |")
    return lines


def refresh_moe_tables() -> list:
    """``bench.py --moe``: run the MoE-vs-dense rows, fold them into
    the committed sweep tables (replacing previous moe rows — the
    serving-table discipline), and append them as BENCH_HISTORY points
    so ``otpu_perf --diff`` guards the per-step latency."""
    here = os.path.dirname(os.path.abspath(__file__))
    rows = moe_rows()
    try:
        with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        payload = {"ndev": 0, "results": []}
    payload["results"] = [r for r in payload.get("results", [])
                          if not str(r.get("coll", "")).startswith(
                              "moe_")] + rows
    _atomic_write(os.path.join(here, "BENCH_SWEEP.json"),
                  json.dumps(payload, indent=1))
    md_path = os.path.join(here, "BENCH_SWEEP.md")
    try:
        with open(md_path) as f:
            md = f.read()
    except OSError:
        md = "# Collective sweep\n"
    _atomic_write(md_path, _splice_md_section(
        md, "## MoE (expert-parallel host trainer vs dense)",
        _moe_md_section(rows)))
    hist = [{"key": r["coll"], "lat_us": r["lat_us"], "k": 3}
            for r in rows if r.get("ok", True) and r.get("lat_us")]
    if hist:
        append_history(hist, "bench", "host_sm_n2")
    return rows


_STAGING_OSU = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu
from ompi_tpu.mca.accelerator.jax_acc import staging

w = ompi_tpu.init()
x = np.ones((4 << 20) // 4, np.float32)
for _ in range(3):
    w.allreduce(x)
# min-of-many: the pool's per-call win (one warm 1MB checkout per ring
# call) is percent-scale, far below this 1-core harness's per-call
# scheduling jitter — the latency FLOOR is the comparable statistic
lat = []
for _ in range(24):
    w.barrier()
    t0 = time.perf_counter()
    w.allreduce(x)
    lat.append(time.perf_counter() - t0)
if w.rank == 0:
    print("STAGING " + json.dumps(
        [min(lat), staging.hits, staging.misses]))
ompi_tpu.finalize()
"""


def staging_micro_row() -> dict:
    """Mechanism-level rcache/grdma-reuse row: warmed pool checkout vs
    fresh alloc + page-touch for the ring's per-step 1MB buffer.  This
    is the robust measurement — the end-to-end 4MB rows below sit
    within this 1-core harness's run-to-run noise (the ~30µs/step tax
    is <1% of a 25ms host collective; it matters when the transport is
    fast, i.e. on real hardware)."""
    import numpy as np

    from ompi_tpu.mca.accelerator.jax_acc import _StagingPool

    n, reps = 1 << 20, 50
    t0 = time.perf_counter()
    for _ in range(reps):
        b = np.empty(n, np.float32)
        b[::4096] = 1.0              # touch the fresh pages
    t_fresh = (time.perf_counter() - t0) / reps
    pool = _StagingPool(max_bytes=1 << 30, enabled=True)
    pool.release(pool.acquire(n, np.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        b = pool.acquire(n, np.float32)
        b[::4096] = 1.0
        pool.release(b)
    t_pool = (time.perf_counter() - t0) / reps
    return {"coll": "staging_reuse_micro_1MB", "nbytes": 1 << 20,
            "fresh_us": round(t_fresh * 1e6, 1),
            "pooled_us": round(t_pool * 1e6, 1),
            "ratio": round(t_fresh / max(t_pool, 1e-9), 2)}


def threads_pool_row() -> dict:
    """Mechanism row for the mca/threads substrate: 4MB strided-vector
    pack through a 2-worker native pool vs the single-thread native
    loop.  On a 1-core harness the pool COSTS ~1.6x (cross-thread
    chunking with no second core) — which is exactly why
    ``default_workers`` returns 1 there and the convertor keeps its
    serial path; a many-core TPU-host run shows the fan-out paying
    off.  ``effective_workers`` records what this host actually uses."""
    import numpy as np

    from ompi_tpu.datatype import core as dt_core
    from ompi_tpu.datatype import convertor as conv_mod
    from ompi_tpu.datatype.convertor import Convertor
    from ompi_tpu.base.var import registry
    from ompi_tpu.mca.threads import base as threads_base

    vec = dt_core.vector(2, 1, 2, dt_core.FLOAT32)
    n = (4 << 20) // vec.size
    buf = np.random.default_rng(0).standard_normal(
        n * (vec.extent // 4)).astype(np.float32)
    reps = 10

    def run_pack():
        t0 = time.perf_counter()
        for _ in range(reps):
            Convertor(vec, n, buf).pack()
        return (time.perf_counter() - t0) / reps

    var = registry.lookup("otpu_threads_pool_workers")
    old_var = var.value
    threads_base.shutdown_pool()
    var.set(2)                           # force the pool path for the
    try:                                 # mechanism measurement
        pool = threads_base.get_pool()   # spawn workers OUTSIDE the
        run_pack()                       # timing + one warm-up rep
        pool_ran = bool(getattr(pool, "parallel_pack", False))
        t_pool = run_pack()
    finally:
        var.set(old_var)
        threads_base.shutdown_pool()
    old = conv_mod._POOL_PACK_MIN
    conv_mod._POOL_PACK_MIN = 1 << 62    # force the single-thread loop
    try:
        t_serial = run_pack()
    finally:
        conv_mod._POOL_PACK_MIN = old
    return {"coll": "threads_pool_pack_4MB", "nbytes": 4 << 20,
            "serial_us": round(t_serial * 1e6, 1),
            "pooled_us": round(t_pool * 1e6, 1),
            "effective_workers": threads_base.default_workers(),
            "pool_path_ran": pool_ran,
            "ratio": round(t_serial / max(t_pool, 1e-9), 2),
            "note": ("2-worker pool forced for the measurement; <1.0 "
                     "on a 1-core harness is EXPECTED and is why "
                     "default_workers()==1 keeps the serial path there"
                     if pool_ran else
                     "native substrate unavailable: both columns are "
                     "the serial path (python fallback has no parallel "
                     "pack)")}


def host_staging_points() -> list:
    """rcache/grdma-reuse rows (rcache_grdma.c): the mechanism
    microbenchmark (robust) plus the end-to-end 4MB allreduce pair
    (recorded for completeness; within noise on the 1-core harness)."""
    import json as _json
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_STAGING_OSU)
        script = f.name
    rows = []
    try:
        rows.append(staging_micro_row())
        # ALTERNATE pool/nopool jobs and keep each mode's best run: the
        # two configurations used to run minutes apart, so 1-core host
        # drift (±10%) dwarfed the pool's per-call win and the e2e
        # ratio was pure noise.  Paired best-of-N isolates the
        # mechanism the same way the perf-guard's interleaved reps do.
        lat: dict = {}
        stats: dict = {}
        for _rep in range(3):
            for mode, flag in (("pool", "1"), ("nopool", "0")):
                proc = subprocess.run(
                    [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                     "-n", "4",
                     "--mca", "accelerator_jax_staging_pool", flag,
                     sys.executable, script],
                    capture_output=True, text=True, timeout=240,
                    env=dict(os.environ, JAX_PLATFORMS="cpu"))
                line = next((ln for ln in proc.stdout.splitlines()
                             if "STAGING" in ln), None)
                if proc.returncode or line is None:
                    print(f"staging bench ({mode}) failed "
                          f"(rc={proc.returncode}):"
                          f"\n{proc.stderr[-1500:]}", file=sys.stderr)
                    continue
                t, hits, misses = _json.loads(
                    line.split("STAGING ", 1)[1])
                if mode not in lat or t < lat[mode]:
                    lat[mode] = t
                    stats[mode] = (hits, misses)
        for mode in ("pool", "nopool"):
            if mode in lat:
                rows.append({"coll": f"allreduce_4MB_staging_{mode}",
                             "nbytes": 4 << 20,
                             "fw_lat_us": round(lat[mode] * 1e6, 1),
                             "pool_hits": stats[mode][0],
                             "pool_misses": stats[mode][1]})
        if "pool" in lat and "nopool" in lat:
            rows.append({"coll": "staging_pool_e2e",
                         "nbytes": 4 << 20,
                         "ratio": round(lat["nopool"] / lat["pool"], 3),
                         "note": "paired best-of-3 (alternating jobs); "
                                 "the mechanism micro row is the "
                                 "per-checkout claim"})
    finally:
        os.unlink(script)
    return rows


_FASTPATH_TCP = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu
from ompi_tpu.runtime import spc

w = ompi_tpu.init()
nbytes = 4 << 20
WINDOW = 4
x = np.ones(nbytes, np.uint8)
bufs = [np.empty_like(x) for _ in range(WINDOW)]
ack = np.zeros(1, np.float64)
def once():
    if w.rank == 0:
        reqs = [w.isend(x, dest=1, tag=9) for _ in range(WINDOW)]
        for r in reqs:
            r.wait()
        w.recv(ack, source=1, tag=10)
    else:
        reqs = [w.irecv(bufs[i], source=0, tag=9) for i in range(WINDOW)]
        for r in reqs:
            r.wait()
        w.send(ack, dest=0, tag=10)
for _ in range(2):
    once()
# the 1-core harness is bimodal (scheduler-paced slow windows vs
# memcpy-bound fast windows, in BOTH wire implementations): the best
# window measures the wire MECHANISM, the median measures the host
ts = []
for _ in range(12):
    w.barrier()
    t0 = time.perf_counter()
    once()
    ts.append(time.perf_counter() - t0)
if w.rank == 0:
    c = spc.counters()
    print("FASTPATH_TCP " + json.dumps(
        [WINDOW * nbytes / min(ts) / 1e9,
         WINDOW * nbytes / statistics.median(ts) / 1e9,
         c.get("fastpath_hdr_fast", 0),
         c.get("fastpath_hdr_pickle", 0),
         c.get("fastpath_payload_copies", 0),
         c.get("fastpath_sendmsg", 0)]))
ompi_tpu.finalize()
"""


_FASTPATH_4K = """
import json, statistics, sys, time
import numpy as np
import ompi_tpu
from ompi_tpu.runtime import spc

w = ompi_tpu.init()
x = np.ones(1024, np.float32)          # 4KB
for _ in range(5):
    w.allreduce(x)
lat = []
for _ in range(30):
    w.barrier()
    t0 = time.perf_counter()
    w.allreduce(x)
    lat.append(time.perf_counter() - t0)
if w.rank == 0:
    c = spc.counters()
    print("FASTPATH_4K " + json.dumps(
        [statistics.median(lat),
         c.get("fastpath_eager_lane", 0),
         c.get("fastpath_sched_hits", 0)]))
ompi_tpu.finalize()
"""


# ---------------------------------------------------------------------
# otpu-prof perf-regression history plane (BENCH_HISTORY.jsonl)
# ---------------------------------------------------------------------

_HISTORY_WORKER = """
import json, os, time
import numpy as np
import ompi_tpu
from ompi_tpu.api import op

w = ompi_tpu.init()
K = int(os.environ.get("OTPU_BENCH_HISTORY_REPS", "6"))
BATCH = int(os.environ.get("OTPU_BENCH_HISTORY_BATCH", "30"))
points = os.environ.get(
    "OTPU_BENCH_HISTORY_POINTS",
    "allreduce:4096,allreduce:65536,pingpong:4096")
out = []
for spec in points.split(","):
    kind, nbytes = spec.strip().split(":")
    nbytes = int(nbytes)
    if kind == "allreduce":
        x = np.ones(max(1, nbytes // 4), np.float32)
        def once():
            for _ in range(BATCH):
                w.allreduce(x, op.SUM)
    else:                               # pingpong (2-rank halves)
        x = np.ones(nbytes, np.uint8)
        buf = np.empty_like(x)
        peer = (w.rank + 1) % 2
        def once():
            for _ in range(BATCH):
                if w.rank == 0:
                    w.send(x, dest=1, tag=7)
                    w.recv(buf, source=1, tag=8)
                elif w.rank == 1:
                    w.recv(buf, source=0, tag=7)
                    w.send(x, dest=0, tag=8)
    once()                              # warmup
    best = float("inf")
    for _ in range(K):                  # min-of-k: fast-mode statistic
        w.barrier()
        t0 = time.perf_counter()
        once()
        best = min(best, (time.perf_counter() - t0) / BATCH)
    out.append({"key": f"{kind}_{nbytes}b_n{w.size}",
                "lat_us": round(best * 1e6, 1), "k": K,
                "batch": BATCH, "nbytes": nbytes})
if w.rank == 0:
    print("HISTORY " + json.dumps(out))
ompi_tpu.finalize()
"""

_LADDER_WORKER = """
import json, os, time
import numpy as np
import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.base.var import registry
from ompi_tpu.mca.coll.tuned import _MENUS

w = ompi_tpu.init()
K = int(os.environ.get("OTPU_BENCH_LADDER_REPS", "3"))
colls = os.environ.get("OTPU_BENCH_LADDER_COLLS",
                       "allreduce,bcast").split(",")
sizes = [int(s) for s in os.environ.get(
    "OTPU_BENCH_LADDER_SIZES", "4096,65536,1048576").split(",")]
out = []
for coll in colls:
    force = registry.lookup(f"otpu_coll_tuned_{coll}_algorithm")
    for nbytes in sizes:
        x = np.ones(max(1, nbytes // 4), np.float32)
        for alg in sorted(_MENUS[coll]):
            force.set(alg)              # every rank runs the same loop
            batch = max(3, min(20, (256 << 10) // max(1, nbytes)))
            def once():
                for _ in range(batch):
                    if coll == "allreduce":
                        w.allreduce(x, op.SUM)
                    else:
                        w.bcast(x, root=0)
            try:
                once()
                best = float("inf")
                for _ in range(K):
                    w.barrier()
                    t0 = time.perf_counter()
                    once()
                    best = min(best, (time.perf_counter() - t0) / batch)
                out.append({"coll": coll, "nbytes": nbytes,
                            "algorithm": alg,
                            "lat_us": round(best * 1e6, 1), "k": K})
            except Exception as exc:
                out.append({"coll": coll, "nbytes": nbytes,
                            "algorithm": alg, "error": str(exc)[:120],
                            "lat_us": -1.0, "k": K})
        force.set("")
if w.rank == 0:
    print("LADDER " + json.dumps(out))
ompi_tpu.finalize()
"""


def history_file() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.environ.get("OTPU_BENCH_HISTORY_FILE",
                          os.path.join(here, "BENCH_HISTORY.jsonl"))


def _run_history_worker(body: str, marker: str, n: int,
                        extra_mca=(), extra_argv=(),
                        extra_env=()) -> list:
    """One tpurun job over the PML wire path (coll/sm pushed below
    coll/tuned so the rows measure the datapath the stage clocks cover
    — and so a chaos wire fault actually lands in the numbers)."""
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(body)
        script = f.name
    try:
        argv = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
                "-n", str(n),
                "--mca", "otpu_coll_sm_coll_priority", "0"]
        argv += list(extra_argv)
        for k, v in extra_mca:
            argv += ["--mca", k, v]
        argv += [sys.executable, script]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.update(dict(extra_env))
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=600,
            env=env)
        line = next((ln for ln in proc.stdout.splitlines()
                     if marker in ln), None)
        if proc.returncode or line is None:
            print(f"history bench failed (rc={proc.returncode}):\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return []
        return json.loads(line.split(marker + " ", 1)[1])
    finally:
        os.unlink(script)


def append_history(rows: list, kind: str, topology: str) -> list:
    """Stamp measurement rows into v1 history rows (one run id per
    call) and append them to the history file."""
    run = f"r{int(time.time() * 1000)}"
    t = time.time()
    stamped = []
    for r in rows:
        row = {"v": 1, "kind": kind, "run": run, "t": t,
               "topology": topology, "host": os.uname().nodename}
        row.update(r)
        stamped.append(row)
    path = history_file()
    with open(path, "a") as f:
        for row in stamped:
            f.write(json.dumps(row) + "\n")
    return stamped


def history_rows(n: int = 2) -> list:
    """``--history``: min-of-k host-datapath latency points appended as
    one run to BENCH_HISTORY.jsonl (the otpu_perf --diff input)."""
    rows = _run_history_worker(_HISTORY_WORKER, "HISTORY", n)
    return append_history(rows, "bench", f"host_sm_n{n}")


def reactor_history_rows(n: int = 3, native: bool = True) -> list:
    """``--reactor-history``: the native-reactor acceptance lane — the
    same min-of-k worker forced onto btl/tcp (``--fake-nodes`` so sm
    declines every peer and the eager 4KB allreduce rides the wire the
    epoll reactor drains).  Run once with ``native=False`` (pure-Python
    selector loop, the "before" baseline) and once with the default
    (reactor on, the "after"): both land under the same
    ``host_tcp_n{n}`` topology and identical keys, so ``otpu_perf
    --diff`` compares reactor-on against the reactor-off min — the hard
    4KB-eager latency budget.  Pingpong needs exactly 2 ranks, so the
    default point set here is allreduce-only (override via
    OTPU_BENCH_HISTORY_POINTS)."""
    extra_env = []
    if "OTPU_BENCH_HISTORY_POINTS" not in os.environ:
        extra_env.append(("OTPU_BENCH_HISTORY_POINTS",
                          "allreduce:4096,allreduce:65536"))
    extra_mca = () if native else (("otpu_progress_native", "0"),)
    rows = _run_history_worker(
        _HISTORY_WORKER, "HISTORY", n,
        extra_mca=extra_mca, extra_argv=("--fake-nodes", str(n)),
        extra_env=extra_env)
    return append_history(rows, "bench", f"host_tcp_n{n}")


def ladder_host_rows(n: int = 2) -> list:
    """``--ladder``: the measured per-(topology, coll, size, algorithm)
    sweep the self-tuning rules file (ROADMAP item 3) is derived from.
    Failed (coll, size, alg) cells carry ``error`` and lat_us -1 and
    are excluded from history (otpu_perf rejects non-positive rows)."""
    rows = _run_history_worker(_LADDER_WORKER, "LADDER", n)
    good = [r for r in rows if r.get("lat_us", -1) > 0]
    bad = [r for r in rows if r.get("lat_us", -1) <= 0]
    for r in bad:
        print(f"ladder: {r['coll']}/{r['nbytes']}/{r['algorithm']} "
              f"failed: {r.get('error')}", file=sys.stderr)
    return append_history(good, "ladder", f"host_sm_n{n}") + bad


def fastpath_points() -> list:
    """fastpath rows (BENCH_SWEEP schema): the zero-copy host-datapath
    evidence.  (a) ``fastpath_tcp_loopback``: 2-rank streaming bandwidth
    over btl/tcp's sendmsg-coalesced wire (fake-nodes so tcp carries the
    FRAG stream; acceptance: >=1.5x the pre-fastpath ``pt2pt_tcp_frag``
    figure on the same host), with the SPC copy/header counters in the
    row.  (b) ``fastpath_allreduce_4KB``: the small-message host
    allreduce latency the eager lane + schedule cache attack.  The
    staging e2e evidence is the existing ``staging_pool_e2e`` row."""
    import json as _json
    import subprocess
    import tempfile

    rows = []
    for name, body, cmd_extra in (
            ("fastpath_tcp_loopback", _FASTPATH_TCP,
             ["--fake-nodes", "2", "--mca", "pml_ob1_stripe", "0",
              "--mca", "pml_ob1_rget_limit", "0"]),
            # ^sm_coll isolates coll/tuned (on one host coll/sm owns
            # sub-slot payloads): this row measures the eager lane +
            # schedule cache the fastpath PR added to the tuned ladder
            ("fastpath_allreduce_4KB", _FASTPATH_4K,
             ["--mca", "coll", "^sm_coll"])):
        with tempfile.NamedTemporaryFile("w", suffix=".py",
                                         delete=False) as f:
            f.write(body)
            script = f.name
        try:
            n = "2" if name == "fastpath_tcp_loopback" else "4"
            proc = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", n,
                 *cmd_extra, sys.executable, script],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            tagname = ("FASTPATH_TCP" if name == "fastpath_tcp_loopback"
                       else "FASTPATH_4K")
            line = next((ln for ln in proc.stdout.splitlines()
                         if tagname in ln), None)
            if proc.returncode or line is None:
                print(f"fastpath bench ({name}) failed "
                      f"(rc={proc.returncode}):\n{proc.stderr[-1500:]}",
                      file=sys.stderr)
                continue
            vals = _json.loads(line.split(tagname + " ", 1)[1])
            if name == "fastpath_tcp_loopback":
                bw_best, bw_med, hfast, hpickle, copies, sendmsg = vals
                rows.append({"coll": name, "nbytes": 4 << 20,
                             "fw_bw_gbs": round(bw_best, 4),
                             "fw_bw_med_gbs": round(bw_med, 4),
                             "hdr_fast": int(hfast),
                             "hdr_pickle": int(hpickle),
                             "payload_copies": int(copies),
                             "sendmsg_calls": int(sendmsg),
                             "note": "fw_bw_gbs = best window (wire "
                                     "mechanism); median tracks the "
                                     "bimodal 1-core scheduler"})
            else:
                lat, lane, hits = vals
                rows.append({"coll": name, "nbytes": 4096,
                             "fw_lat_us": round(lat * 1e6, 1),
                             "eager_lane_calls": int(lane),
                             "sched_cache_hits": int(hits)})
        finally:
            os.unlink(script)
    return rows


MULTIDEV_SIZES = (8, 4096, 262144, 4 << 20)
MULTIDEV_SPOT = 262144
#: acceptable fw-vs-raw ratio band for the 8-virtual-device table once
#: raw baselines are pinned to identical program shapes
MULTIDEV_BAND = (0.8, 1.25)


def multidev_child() -> None:
    """Child body: 8-virtual-CPU-device ratio sweep (correctness-grade).

    Ratios here measure framework dispatch + algorithm choice against
    raw shard_map programs on the SAME 8-device CPU mesh — they make
    tuned-ladder and xla-program regressions visible without pod access
    (SURVEY.md §4's "fake backend MPI never had").  They are NOT
    bandwidth numbers: CPU rings move bytes through host memory.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    b = DeviceBench()
    rows = []
    for nbytes in MULTIDEV_SIZES:
        rows.append(b.point("allreduce", nbytes))
    for coll in ("bcast", "allgather", "reduce_scatter"):
        rows.append(b.point(coll, MULTIDEV_SPOT))
    try:
        rows.append(b.persistent_point(MULTIDEV_SPOT, iters=10))
    except Exception as exc:
        # one failing row must not cost the whole 8-device table
        print(f"multidev persistent failed: {exc}", file=sys.stderr)
    # regression-guard contract: with raw baselines pinned to identical
    # program shapes, every ratio must sit in a band around 1.0 —
    # below = dispatch/selection regression, above = the baselines
    # diverged again and the table stopped guarding anything.
    # (Tiny payloads are latency-noise-bound: band-checked only at
    # >=4KB.)  tests/test_bench_table.py fails CI on out-of-band rows.
    for r in rows:
        if r.get("nbytes", 0) >= 4096:
            r["in_band"] = bool(
                MULTIDEV_BAND[0] <= r["ratio"] <= MULTIDEV_BAND[1])
    bad = [r for r in rows if r.get("in_band") is False]
    if bad:
        print("multidev rows OUT OF BAND: "
              + ", ".join(f"{r['coll']}/{r['nbytes']}={r['ratio']}"
                          for r in bad), file=sys.stderr)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_SWEEP_8DEV.json"), "w") as f:
        json.dump({"ndev": b.ndev, "grade": "correctness",
                   "band": list(MULTIDEV_BAND), "results": rows},
                  f, indent=1)
    import ompi_tpu

    ompi_tpu.finalize()


def multidev_sweep(ndev: int = 8) -> list:
    """Run the virtual-multidevice sweep hermetically (fresh interpreter:
    the parent's jax may be pinned to one real TPU chip) and return its
    rows (empty on failure)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multidev-child"],
        env=env, cwd=here, capture_output=True, text=True, timeout=900)
    if proc.returncode:
        print(f"multidev sweep failed (rc={proc.returncode}):\n"
              f"{proc.stderr[-1500:]}", file=sys.stderr)
        return []
    try:
        with open(os.path.join(here, "BENCH_SWEEP_8DEV.json")) as f:
            return json.load(f)["results"]
    except (OSError, KeyError, ValueError):
        return []


def emit_metric(value: float, ratio: float, note: str = None) -> None:
    """The ONE driver-contract JSON line (single emission point)."""
    out = {"metric": "osu_allreduce_bus_bw_16MB_f32",
           "value": value, "unit": "GB/s", "vs_baseline": ratio}
    if note:
        out["note"] = note
    print(json.dumps(out))


_probe_ok = False


def backend_available(timeout: float = 180.0):
    """Probe the accelerator backend in a SUBPROCESS with a hard timeout;
    returns (ok, detail).  A positive result is cached for the process
    (pod_smoke -> main() must not pay the probe twice).

    The axon boot hook can make ``import jax`` / ``jax.devices()`` block
    indefinitely when the TPU tunnel is down; probing out-of-process is
    the only way this bench can refuse to hang.  A nonzero exit is a
    DIFFERENT failure (broken install, devices() crash) and its stderr
    is surfaced, not mislabeled as a tunnel timeout."""
    import subprocess

    global _probe_ok
    if _probe_ok:
        return True, ""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung past {timeout:.0f}s (tunnel down)"
    if proc.returncode:
        return False, ("backend probe failed (rc="
                       f"{proc.returncode}): {proc.stderr[-400:]}")
    _probe_ok = True
    return True, ""


def host_rows() -> list:
    """Configs #1-#2 (host path, JAX_PLATFORMS=cpu subprocesses): these
    need no accelerator at all."""
    rows = []
    try:
        rows.append(host_ring_smoke())
    except Exception as exc:
        print(f"ring smoke failed: {exc}", file=sys.stderr)
    try:
        rows.extend(host_allreduce_points())
    except Exception as exc:
        print(f"host allreduce failed: {exc}", file=sys.stderr)
    try:
        rows.extend(host_rget_points())
    except Exception as exc:
        print(f"rget bench failed: {exc}", file=sys.stderr)
    try:
        rows.extend(host_part_points())
    except Exception as exc:
        print(f"partitioned pingpong bench failed: {exc}", file=sys.stderr)
    try:
        rows.extend(host_staging_points())
    except Exception as exc:
        print(f"staging bench failed: {exc}", file=sys.stderr)
    try:
        rows.append(threads_pool_row())
    except Exception as exc:
        print(f"threads pool bench failed: {exc}", file=sys.stderr)
    try:
        rows.extend(fastpath_points())
    except Exception as exc:
        print(f"fastpath bench failed: {exc}", file=sys.stderr)
    return rows


def _table(rows) -> list:
    out = ["| coll | bytes | fw lat us | raw lat us | fw GB/s | "
           "raw GB/s | ratio |",
           "|---|---|---|---|---|---|---|"]
    for r in rows:
        out.append(
            f"| {r['coll']} | {r.get('nbytes', '-')} | "
            f"{r.get('fw_lat_us', '-')} | "
            f"{r.get('raw_lat_us', '-')} | "
            f"{r.get('fw_bw_gbs', '-')} | "
            f"{r.get('raw_bw_gbs', '-')} | "
            f"{r.get('ratio', '-')} |")
    return out


def _atomic_write(path: str, text: str) -> None:
    """Write-then-replace: a mid-write failure must never leave a
    truncated file (the carried-forward device rows live here)."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def write_sweep(ndev, results, multidev_rows, header_note="",
                stale_device_rows=None, stale_rounds=0,
                mfu=None) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    # serving/recovery/quant rows are refreshed by `bench.py --serving`
    # / `--recovery` / `--quant`, not by the sweep: carry the committed
    # ones forward so a sweep refresh cannot erase them (the
    # carried-device-rows discipline)
    for prefix in ("serving_", "recovery_", "quant_"):
        if not any(str(r.get("coll", "")).startswith(prefix)
                   for r in results):
            try:
                with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
                    results = results + [
                        r for r in json.load(f).get("results", [])
                        if str(r.get("coll", "")).startswith(prefix)]
            except (OSError, ValueError):
                pass
    payload = {"ndev": ndev, "results": results}
    if mfu:
        payload["mfu"] = mfu
    if stale_device_rows:
        payload["stale_device_rows"] = stale_device_rows
        payload["stale_rounds"] = stale_rounds
    _atomic_write(os.path.join(here, "BENCH_SWEEP.json"),
                  json.dumps(payload, indent=1))
    lines = ["# Collective sweep (OSU protocol, BASELINE.md configs "
             "#1-#5)", ""]
    if header_note:
        lines += [header_note, ""]
    lines += [f"Devices: {ndev}", ""] + _table(
        [r for r in results
         if not str(r.get("coll", "")).startswith(("serving_",
                                                   "recovery_",
                                                   "quant_"))])
    if mfu:
        lines += ["", "## Single-chip MFU", ""]
        for r in mfu:
            mfu_s = (f"{r['mfu'] * 100:.1f}% of "
                     f"{r.get('peak_tflops_assumed', '?')} TF peak"
                     if r.get("mfu") is not None
                     else "mfu n/a (non-TPU backend)")
            extra = (f", {r['vs_jnp_speedup']}x vs jnp"
                     if "vs_jnp_speedup" in r else "")
            lines.append(f"- `{r['metric']}` [{r['grade']}]: "
                         f"{r['tflops']} TFLOP/s ({mfu_s}){extra}")
    if stale_device_rows:
        age = (f"at least {stale_rounds} fallback round(s) old"
               if stale_rounds else "previous round")
        lines += ["", f"## Carried-forward DEVICE rows ({age}; the "
                  "tunnel was unreachable this round)", ""] \
                 + _table(stale_device_rows)
    if multidev_rows:
        lines += ["", "## 8 virtual CPU devices (correctness-grade)",
                  "",
                  "Framework-vs-raw ratios on an 8-device CPU mesh: "
                  "dispatch + algorithm-choice regressions show up "
                  "here without pod access.  NOT bandwidth numbers.",
                  ""] + _table(multidev_rows)
    serving_now = [r for r in results
                   if str(r.get("coll", "")).startswith("serving_")]
    if serving_now:
        lines += _serving_md_section(serving_now)
    recovery_now = [r for r in results
                    if str(r.get("coll", "")).startswith("recovery_")]
    if recovery_now:
        lines += _recovery_md_section(recovery_now)
    quant_now = [r for r in results
                 if str(r.get("coll", "")).startswith("quant_")]
    if quant_now:
        lines += _quant_md_section(quant_now)
    _atomic_write(os.path.join(here, "BENCH_SWEEP.md"),
                  "\n".join(lines) + "\n")


def _previous_device_rows():
    """(device rows, stale_rounds) from the last committed sweep —
    carried forward when the tunnel is unreachable so a fallback run
    cannot erase them.  Device rows are classified STRUCTURALLY (they
    carry a fw-vs-raw ratio; host rows never do), not by name list."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, "BENCH_SWEEP.json")) as f:
            old = json.load(f)
    except (OSError, ValueError):
        return [], 0
    rows = [r for r in old.get("results", []) if "ratio" in r
            or r.get("coll") == "allreduce_persistent"]
    if rows:
        return rows, 1
    return (old.get("stale_device_rows", []),
            int(old.get("stale_rounds", 0)) + 1)


def unreachable_fallback(detail: str, fast: bool) -> None:
    """The TPU never answered: emit an honest zero line (the framework's
    TPU path did NOT run), plus — outside fast mode — everything that
    needs NO accelerator: the host-path OSU rows and the 8-virtual-CPU
    correctness-grade sweep, so the round still records transport and
    dispatch health.  (The CPU children run with JAX_PLATFORMS=cpu
    pinned pre-import, which the boot hook honors — verified working
    with the tunnel dead — and each subprocess timeout bounds the worst
    case.)"""
    print(f"TPU backend unavailable: {detail}; vs_baseline=0",
          file=sys.stderr)
    rows, mrows = [], []
    recorded = False
    if not fast:
        try:
            stale, stale_rounds = _previous_device_rows()
            rows = host_rows()
            mrows = multidev_sweep()
            mfu = mfu_rows_subprocess()  # dryrun grade (hermetic: the
            # parent must never import jax while the tunnel is down)
            write_sweep(0, rows, mrows, header_note=(
                "**TPU tunnel unreachable this round**: fresh device "
                "rows absent; host-path rows + the virtual-CPU section "
                "ran, and older device rows are carried below for "
                "reference."), stale_device_rows=stale,
                stale_rounds=stale_rounds, mfu=mfu)
            recorded = True
        except Exception as exc:
            # the honest-zero metric line below must print regardless
            print(f"fallback sweep recording failed: {exc}",
                  file=sys.stderr)
    state = (f"host rows + 8-virtual-CPU correctness ratios recorded "
             f"({len(rows)}+{len(mrows)} rows)" if recorded
             else "sweep recording FAILED (see stderr)")
    emit_metric(0.0, 0.0, note=(
        f"TPU backend unavailable ({detail.splitlines()[0][:120]}); "
        f"framework TPU path did not run.  {state}."))


def _pallas_first_run(devs, mesh, interp: bool) -> dict:
    """coll/pallas validation: every ring-kernel variant executes on
    THIS mesh (compiled on real TPU, interpreter elsewhere) and matches
    numpy."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n = len(devs)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 256)).astype(np.float32)
    x2 = rng.standard_normal((n, n, 16)).astype(np.float32)
    put = jax.device_put
    checks = {}

    def chk(name, got, want, tol=1e-4):
        checks[name] = bool(np.allclose(np.asarray(got), want, atol=tol,
                                        rtol=tol))

    chk("allreduce_fused",
        pc.all_reduce(put(x), mesh, "x", "sum", interpret=interp),
        x.sum(0))
    chk("allreduce_seg",
        pc.all_reduce(put(x), mesh, "x", "sum", interpret=interp,
                      variant="seg", seg_elems=64), x.sum(0))
    chk("allreduce_bidi",
        pc.all_reduce(put(x), mesh, "x", "sum", interpret=interp,
                      variant="bidi"), x.sum(0))
    chk("allreduce_seg_bidi",
        pc.all_reduce(put(x), mesh, "x", "sum", interpret=interp,
                      variant="seg_bidi", seg_elems=32), x.sum(0))
    chk("allreduce_max",
        pc.all_reduce(put(x), mesh, "x", "max", interpret=interp),
        x.max(0), tol=1e-6)
    chk("allreduce_wire16",
        pc.all_reduce(put(x), mesh, "x", "sum", interpret=interp,
                      variant="wire16"), x.sum(0), tol=0.25)
    chk("reduce_scatter",
        pc.reduce_scatter(put(x2), mesh, "x", "sum", interpret=interp),
        x2.sum(0))
    chk("allgather",
        pc.all_gather(put(x), mesh, "x", interpret=interp), x, tol=1e-6)
    chk("allgather_bidi",
        pc.all_gather(put(x), mesh, "x", interpret=interp,
                      variant="bidi"), x, tol=1e-6)
    chk("bcast",
        pc.bcast(put(x), mesh, "x", root=1, interpret=interp),
        np.broadcast_to(x[1], x.shape), tol=1e-6)
    chk("alltoall",
        pc.all_to_all(put(x2), mesh, "x", interpret=interp),
        np.swapaxes(x2, 0, 1), tol=1e-6)
    xv = rng.standard_normal((n, n, 8, 128)).astype(np.float32)
    cnt = rng.integers(1, 9, (n, n)).astype(np.int32)
    a2av = np.asarray(pc.all_to_all_v(put(xv), cnt, mesh, "x",
                                      interpret=interp))
    checks["alltoallv_ragged"] = all(
        np.array_equal(a2av[j, i, :cnt[i, j]], xv[i, j, :cnt[i, j]])
        for i in range(n) for j in range(n))
    if n % 2 == 0 and n >= 4:
        from jax.sharding import Mesh

        mesh2 = Mesh(np.asarray(devs).reshape(2, n // 2), ("x", "y"))
        chk("allreduce_torus",
            pc.all_reduce_torus(put(x.reshape(2, n // 2, -1)), mesh2,
                                ("x", "y"), interpret=interp),
            x.sum(0))
        chk("reduce_scatter_torus",
            pc.reduce_scatter_torus(put(x2), mesh2, ("x", "y"),
                                    interpret=interp), x2.sum(0))
        chk("allgather_torus",
            pc.all_gather_torus(put(x), mesh2, ("x", "y"),
                                interpret=interp), x, tol=1e-6)

    # the fused compute+communicate kernels are part of the evidence
    # set too (pallas_overlap: new collective_ids, real RDMA semantics
    # on hardware)
    from ompi_tpu.ops import pallas_overlap as po

    m, k_loc, n_out = 2 * n, 16, 8
    a = rng.standard_normal((n, m, k_loc)).astype(np.float32)
    bb = rng.standard_normal((n, k_loc, n_out)).astype(np.float32)
    want = sum(a[i] @ bb[i] for i in range(n))
    chk("matmul_allreduce",
        po.matmul_allreduce(put(a), put(bb), mesh, "x",
                            interpret=interp), want, tol=1e-3)
    chk("matmul_reduce_scatter",
        po.matmul_reduce_scatter(put(a), put(bb), mesh, "x",
                                 interpret=interp),
        want.reshape(n, m // n, n_out), tol=1e-3)
    return checks


def _ladder_row(coll: str, variant: str, nbytes: int, xla_us: float,
                pallas_us: float, interp: bool) -> dict:
    """One LADDER_PROBE row.  Interpreter-grade timings misrepresent
    the pallas/xla crossover by 10-25x (the interpreter serializes what
    hardware overlaps), so dryrun rows carry ``binding: false`` and NO
    winner — a decision ladder seeded from them would permanently gate
    pallas off.  Only device-grade rows declare one."""
    row = {"coll": coll, "variant": variant, "nbytes": nbytes,
           "xla_us": xla_us, "pallas_us": pallas_us,
           "binding": not interp}
    row["winner"] = (None if interp
                     else ("pallas" if pallas_us < xla_us else "xla"))
    return row


def _ladder_probe(b: "DeviceBench", interp: bool, sizes) -> list:
    """Tuned-ladder re-derivation scaffold: per (size, variant), the
    compiler-scheduled coll/xla path vs the explicit coll/pallas ring —
    the measurement the device ladder's crossovers are derived from on
    a real pod.  Both the fused and segmented variants are probed (the
    fused/seg crossover is itself a ladder input).  Timings use the
    shared interleaved ``_timed_pair`` protocol (drift hits both sides
    of a pair equally); interpreter-mode runs are dryrun-grade.
    """
    from ompi_tpu.ops import pallas_collectives as pc
    from ompi_tpu.ops import pallas_overlap as po

    rows = []
    for nbytes in sizes:
        x = b.make(nbytes)
        variants = ["fused"] if nbytes < (64 << 10) else ["fused", "seg"]
        for variant in variants:
            def pallas_fn(t, variant=variant):
                return pc.all_reduce(t, b.mesh, "x", "sum",
                                     interpret=interp, variant=variant)

            pair = b._timed_pair(f"ladder_{variant}", b.fw_fn("allreduce"),
                                 pallas_fn, x, x, nbytes, iters=6)
            rows.append(_ladder_row("allreduce", variant, nbytes,
                                    pair["fw_lat_us"],
                                    pair["raw_lat_us"], interp))

    # bcast + alltoall crossovers: the other slots coll/pallas can own
    for coll in ("bcast", "alltoall"):
        nbytes = 262144
        if coll == "bcast":
            x = b.make(nbytes)

            def pallas_coll_fn(t):
                return pc.bcast(t, b.mesh, "x", root=0,
                                interpret=interp)
        else:
            nelem = max(b.ndev, nbytes // 4 // b.ndev * b.ndev)
            x = b.xla_mod.make_world_array(np.ones(
                (b.world.size, b.ndev, nelem // b.ndev), np.float32))

            def pallas_coll_fn(t):
                return pc.all_to_all(t, b.mesh, "x", interpret=interp)

        try:
            pair = b._timed_pair(f"ladder_{coll}", b.fw_fn(coll)
                                 if coll == "bcast"
                                 else (lambda t: b.world
                                       .alltoall_array(t)),
                                 pallas_coll_fn, x, x, nbytes, iters=6)
            rows.append(_ladder_row(coll, "ring", nbytes,
                                    pair["fw_lat_us"],
                                    pair["raw_lat_us"], interp))
        except Exception as exc:
            print(f"ladder {coll} failed: {exc}", file=sys.stderr)

    # fused collective matmul vs XLA's matmul-then-psum: the overlap row
    # the explicit transport exists for (ops/pallas_overlap.py)
    import jax
    import jax.numpy as jnp
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = b.ndev
    M = K = 256
    N = 128
    key_a = jnp.ones((n, M, K // n), jnp.float32)
    key_b = jnp.ones((n, K // n, N), jnp.float32)

    def fused(args):
        return po.matmul_allreduce(args[0], args[1], b.mesh, "x",
                                   interpret=interp)

    unfused = jax.jit(shard_map(
        lambda a, bb: jax.lax.psum(a[0] @ bb[0], "x"),
        mesh=b.mesh, in_specs=(P("x"), P("x")), out_specs=P(),
        check_vma=False))

    pair = b._timed_pair(
        "ladder_matmul", fused, lambda args: unfused(*args),
        (key_a, key_b), (key_a, key_b), M * K * 4, iters=6)
    rows.append(_ladder_row("matmul_allreduce", "overlap", M * K * 4,
                            pair["raw_lat_us"], pair["fw_lat_us"],
                            interp))
    return rows


def _pallas_aot_gate(here: str) -> dict:
    """Pre-gate: AOT-compile every coll/pallas kernel for a real TPU
    topology (no hardware needed — libtpu's Mosaic compiler runs
    offline).  Runs in a subprocess with a scrubbed env so a site boot
    hook pinning an accelerator tunnel can't hang the compile-only
    path.  Writes PALLAS_AOT.json; a kernel failing here would fail on
    a live pod, so the device sweep shouldn't bother until this is
    green."""
    import importlib.util

    if importlib.util.find_spec("libtpu") is None:
        # no offline Mosaic compiler on this machine: the gate cannot
        # run, which is NOT a compile failure (the CI test skips on the
        # same condition) — report skipped, don't fail pod-smoke
        print("pod-smoke: pallas AOT gate skipped (no libtpu)",
              file=sys.stderr)
        return {"skipped": True, "reason": "libtpu unavailable"}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or here
    out = os.path.join(here, "PALLAS_AOT.json")
    try:
        # a crashed run must not report green off a previous run's file
        try:
            os.remove(out)
        except FileNotFoundError:
            pass
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.pallas_aot",
             "--out", out],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=900)
        if proc.returncode not in (0, 1) or not os.path.exists(out):
            # rc 1 = compiled-with-failures (the file says which); any
            # other rc means the gate itself crashed
            raise RuntimeError(
                f"pallas_aot rc={proc.returncode}: "
                f"{proc.stderr[-400:]}")
        res = json.loads(open(out).read())
        summary = {"ok": res.get("ok", False),
                   "n_compiled": res.get("n_compiled", 0),
                   "n_kernels": res.get("n_kernels", 0),
                   "topology": res.get("topology")}
        print(f"pod-smoke: pallas AOT {summary['n_compiled']}/"
              f"{summary['n_kernels']} kernels compiled for "
              f"{summary['topology']}")
        return summary
    except Exception as exc:
        print(f"pod-smoke: pallas AOT gate failed: {exc}",
              file=sys.stderr)
        return {"ok": False, "error": str(exc)[:300]}


def pod_smoke(dry_run: bool = False) -> int:
    """One-command pod readiness (SURVEY §6 measurement protocol): the
    first hour of real multi-chip access runs THIS to produce the full
    round's evidence set instead of ad-hoc commands.

    Phases: (1) capability probe, (2) coll/pallas first-run validation
    of every ring-kernel variant, (3) the canonical full sweep +
    persistent row via main() (real hardware) or a mini-sweep (dry
    run), (4) tuned-ladder re-derivation probe -> LADDER_PROBE.json.
    ``--dry-run`` forces the 8-virtual-CPU mesh + interpreter kernels
    so CI can validate the script itself.
    """
    here = os.path.dirname(os.path.abspath(__file__))
    report = {"dry_run": dry_run, "phases": {}}
    report["phases"]["pallas_aot"] = _pallas_aot_gate(here)
    if dry_run:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
    else:
        ok, detail = backend_available()
        if not ok:
            report["phases"]["probe"] = {"ok": False, "detail": detail}
            _atomic_write(os.path.join(here, "POD_SMOKE.json"),
                          json.dumps(report, indent=1))
            print(f"pod-smoke: backend unreachable: {detail}",
                  file=sys.stderr)
            return 1
    import jax

    if dry_run:
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    platform = getattr(devs[0], "platform", "?")
    report["phases"]["probe"] = {"ok": True, "ndev": len(devs),
                                 "platform": platform}
    print(f"pod-smoke: {len(devs)} {platform} device(s)")

    from jax.sharding import Mesh

    mesh = Mesh(np.array(devs), ("x",))
    interp = dry_run or platform != "tpu"
    checks = _pallas_first_run(devs, mesh, interp)
    report["phases"]["pallas_first_run"] = {
        "interpret": interp, **checks}
    print("pod-smoke: pallas kernels "
          + ("ALL OK" if all(checks.values()) else f"FAILED: {checks}"))

    b = DeviceBench()
    if dry_run or platform != "tpu":
        rows = [b.point("allreduce", nb, iters=6)
                for nb in MULTIDEV_SIZES]
        try:
            rows.append(b.persistent_point(MULTIDEV_SPOT, iters=10))
        except Exception as exc:   # one row must not cost the report
            print(f"pod-smoke persistent failed: {exc}", file=sys.stderr)
        report["phases"]["sweep"] = {"grade": "dryrun", "rows": rows}
    ladder = _ladder_probe(b, interp, sizes=(4096, 262144, 4 << 20))
    grade = "dryrun" if interp else "device"
    _atomic_write(os.path.join(here, "LADDER_PROBE.json"),
                  json.dumps({"grade": grade, "rows": ladder}, indent=1))
    report["phases"]["ladder_probe"] = {"grade": grade,
                                        "rows": len(ladder)}
    aot = report["phases"]["pallas_aot"]
    ok_all = (all(checks.values())
              and (aot.get("ok", False) or aot.get("skipped", False)))
    if not dry_run and platform == "tpu":
        # the canonical sweep + driver metric line (init is idempotent;
        # main() finalizes).  The report records what actually happened
        # and is written AFTER, so a failed sweep can't leave a report
        # claiming device-grade evidence that was never produced.
        try:
            main()
            report["phases"]["sweep"] = {"grade": "device", "ok": True,
                                         "via": "main() full sweep"}
        except Exception as exc:
            report["phases"]["sweep"] = {"grade": "device", "ok": False,
                                         "error": str(exc)}
            ok_all = False
    _atomic_write(os.path.join(here, "POD_SMOKE.json"),
                  json.dumps(report, indent=1, default=str))
    if dry_run or platform != "tpu":
        import ompi_tpu

        ompi_tpu.finalize()    # main() finalizes on the device path
    print(f"pod-smoke: {'READY' if ok_all else 'NOT READY'} "
          f"(report: POD_SMOKE.json, ladder: LADDER_PROBE.json)")
    return 0 if ok_all else 2


def device_child() -> None:
    """Run the TPU device phase, streaming each completed row as one
    flushed JSON line — the parent harvests rows incrementally and a
    mid-run tunnel stall (round-5 failure mode: the probe succeeds,
    then the data plane freezes and the process sleeps forever inside
    the client's retry loop) costs only the rows not yet produced,
    never the whole run.  Row order is chosen for salvage value: the
    contract size first, then small→large (small rows survive the
    slowest tunnels), MFU before the long tail."""
    budget = float(os.environ.get("OTPU_BENCH_DEVICE_BUDGET_S", "1500"))
    t_start = time.monotonic()

    def left() -> float:
        return budget - (time.monotonic() - t_start)

    def put(kind, obj) -> None:
        print(json.dumps({kind: obj}), flush=True)

    from ompi_tpu.base.jaxenv import apply_platform_env

    apply_platform_env()   # explicit JAX_PLATFORMS beats the boot hook
    import jax

    def raw_psum_fallback(why: str) -> None:
        # the honest framework-breakage row: a reachable TPU whose
        # FRAMEWORK path is broken must stay distinguishable from a
        # dead tunnel — time raw psum and report it with vs_baseline=0
        print(f"framework path unavailable ({why}); reporting raw psum "
              "with vs_baseline=0", file=sys.stderr, flush=True)
        import jax.numpy as jnp
        from ompi_tpu.base.jaxenv import shard_map
        from jax.sharding import PartitionSpec as P

        ndev = len(jax.devices())
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("x",))
        fn = jax.jit(shard_map(lambda t: jax.lax.psum(t[0], "x"),
                               mesh=mesh, in_specs=P("x"), out_specs=P(),
                               check_vma=False))
        x = jnp.ones((ndev, PRIMARY // 4), jnp.float32)
        t = _time_fn(fn, x)
        put("raw_only", {
            "raw_bw_gbs": round(_bus_factor("allreduce", ndev)
                                * PRIMARY / t / 1e9, 3),
            "why": str(why)[:200]})

    def bank_mfu() -> None:
        try:
            mfu_rows(sink=lambda r: put("mfu", r))
        except Exception as exc:
            print(f"mfu rows failed: {exc}", file=sys.stderr, flush=True)

    try:
        b = DeviceBench()
    except Exception as exc:
        raw_psum_fallback(exc)
        put("done", True)
        return
    put("meta", {"ndev": b.ndev,
                 "device_kind": getattr(b.devices[0], "device_kind",
                                        "?"),
                 "platform": jax.default_backend()})
    fast = os.environ.get("OTPU_BENCH_FAST", "") not in ("", "0")
    plan = [("allreduce", PRIMARY, 40)]
    if not fast:
        plan += [("allreduce", nb, 10) for nb in sorted(SWEEP_SIZES)
                 if nb != PRIMARY]
        for coll in ("bcast", "allgather", "reduce_scatter"):
            plan += [(coll, nb, 10) for nb in sorted(SPOT_SIZES)]
    mfu_done = fast   # fast mode: the contract row only
    emitted = 0
    for i, (coll, nbytes, iters) in enumerate(plan):
        if left() < 30:
            print(f"device child: budget exhausted at {coll}@{nbytes}",
                  file=sys.stderr, flush=True)
            break
        if not mfu_done and i >= len(SWEEP_SIZES):
            # allreduce sweep done: bank the MFU rows before the spot
            # tail (the driver judges single-chip MFU)
            mfu_done = True
            bank_mfu()
        try:
            put("row", b.point(coll, nbytes, iters=iters))
            emitted += 1
        except Exception as exc:
            print(f"{coll}@{nbytes} failed: {exc}", file=sys.stderr,
                  flush=True)
    if not mfu_done and left() >= 30:
        bank_mfu()
    if not fast and emitted and left() >= 30:
        try:
            put("row", b.persistent_point(PRIMARY))
        except Exception as exc:
            print(f"persistent failed: {exc}", file=sys.stderr,
                  flush=True)
    if not emitted and left() >= 30:
        # every framework point failed with the device reachable
        raw_psum_fallback("all framework points raised")
    put("done", True)


def device_rows_parent(fast: bool):
    """Harvest the device child's row stream under a hard deadline.

    Returns (meta, rows, mfu, stalled: bool).  The parent NEVER imports
    jax (a stalled tunnel would hang it too) — it only reads lines."""
    import select
    import subprocess

    budget = float(os.environ.get("OTPU_BENCH_DEVICE_BUDGET_S",
                                  "300" if fast else "1500"))
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, OTPU_BENCH_DEVICE_BUDGET_S=str(budget))
    if fast:
        env.setdefault("OTPU_BENCH_ROW_BUDGET_S", "20")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--device-child"],
        stdout=subprocess.PIPE, env=env, cwd=here)
    meta, rows, mfu = {}, [], []
    raw_only = None
    # the child polices its own budget; the grace covers one stalled
    # RPC sitting between its budget checks (env knob so the CI
    # stall-salvage test doesn't wait two real minutes)
    grace = float(os.environ.get("OTPU_BENCH_PARENT_GRACE_S", "120"))
    deadline = time.monotonic() + budget + grace
    stalled = True
    done = False
    eof = False
    fd = proc.stdout.fileno()
    buf = b""
    # select() on the RAW fd and read with os.read: buffered readline
    # would swallow a whole burst of lines into the Python-side buffer
    # where select cannot see them, stranding already-delivered rows
    # when the child later stalls
    while not done and not eof:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            print("device phase: parent deadline hit, killing child",
                  file=sys.stderr)
            break
        ready, _, _ = select.select([fd], [], [], min(remaining, 15.0))
        if not ready:
            if proc.poll() is not None:
                break
            continue
        chunk = os.read(fd, 1 << 16)
        if not chunk:
            eof = True
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "meta" in obj:
                meta = obj["meta"]
            elif "row" in obj:
                rows.append(obj["row"])
            elif "mfu" in obj:
                mfu.append(obj["mfu"])
            elif "raw_only" in obj:
                raw_only = obj["raw_only"]
            elif obj.get("done"):
                stalled = False
                done = True
                break
    if proc.poll() is None:
        proc.kill()
    proc.stdout.close()
    return meta, rows, mfu, stalled, raw_only


def main() -> None:
    fast = os.environ.get("OTPU_BENCH_FAST", "") not in ("", "0")
    ok, detail = backend_available()
    if not ok:
        unreachable_fallback(detail, fast)
        return
    meta, rows, mfu, stalled, raw_only = device_rows_parent(fast)
    primary = next((r for r in rows if r["coll"] == "allreduce"
                    and r["nbytes"] == PRIMARY), None)
    note = None
    if primary is None:
        # salvage: the largest completed allreduce row still proves the
        # device path ran — but it is NOT the contract size, say so
        cands = [r for r in rows if r["coll"] == "allreduce"]
        if not cands and raw_only is not None:
            # device reachable, FRAMEWORK path broken: report raw psum
            # with vs_baseline=0 — honest and distinguishable from a
            # dead tunnel
            emit_metric(raw_only["raw_bw_gbs"], 0.0, note=(
                "framework TPU path unavailable "
                f"({raw_only.get('why', '?')}); raw psum only"))
            return
        if not cands:
            unreachable_fallback(
                "device phase produced no rows (tunnel answered the "
                "probe, then stalled)", fast)
            return
        primary = max(cands, key=lambda r: r["nbytes"])
        note = (f"PARTIAL: tunnel degraded mid-run; largest completed "
                f"allreduce row is {primary['nbytes']} bytes, not "
                f"{PRIMARY} (stalled={stalled})")
    elif stalled:
        note = ("PARTIAL: contract row measured, but the sweep was cut "
                "short by a tunnel stall")
    if not fast:
        # nothing after the TPU measurements may lose them: the sweep
        # files and the contract metric line must survive any CPU-side
        # failure (hung multidev child, unwritable bench dir, ...)
        try:
            results = rows + host_rows()
            multidev_rows = multidev_sweep()
            header = ""
            if stalled:
                header = ("**Tunnel degraded this round**: device rows "
                          "below are the completed prefix of the sweep.")
            write_sweep(meta.get("ndev", 0), results, multidev_rows,
                        header_note=header, mfu=mfu)
        except Exception as exc:
            print(f"post-TPU sweep recording failed: {exc}",
                  file=sys.stderr)
    emit_metric(primary["fw_bw_gbs"], primary["ratio"], note=note)


if __name__ == "__main__":
    if "--multidev-child" in sys.argv:
        multidev_child()
    elif "--device-child" in sys.argv:
        device_child()
    elif "--multidev" in sys.argv:
        for row in multidev_sweep():
            print(row)
    elif "--reactor-history" in sys.argv:
        for row in reactor_history_rows(
                native="--baseline" not in sys.argv):
            print(json.dumps(row))
    elif "--history" in sys.argv:
        for row in history_rows():
            print(json.dumps(row))
    elif "--ladder" in sys.argv:
        for row in ladder_host_rows():
            print(json.dumps(row))
    elif "--serving" in sys.argv:
        for row in refresh_serving_tables():
            print(json.dumps(row))
    elif "--recovery" in sys.argv:
        for row in refresh_recovery_tables():
            print(json.dumps(row))
    elif "--quant" in sys.argv:
        for row in refresh_quant_tables():
            print(json.dumps(row))
    elif "--moe" in sys.argv:
        for row in refresh_moe_tables():
            print(json.dumps(row))
    elif "--pod-smoke" in sys.argv:
        sys.exit(pod_smoke(dry_run="--dry-run" in sys.argv))
    elif "--mfu" in sys.argv:
        for row in mfu_rows():
            print(json.dumps(row))
    else:
        main()
