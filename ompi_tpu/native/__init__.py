"""ompi_tpu.native — C++ twins of the hot host-path loops.

Lazy ctypes binding over ``otpu_native.cc`` (datatype pack/unpack element
loops + the btl/sm SPSC ring).  The library is compiled on first use with
the in-image g++ into a per-source-hash cache path; if the toolchain or
compile is unavailable every caller silently stays on its numpy fallback —
``available()`` reports which world you are in.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "otpu_native.cc")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_I64P = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_has_reactor = False


def _build_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get("OTPU_NATIVE_CACHE",
                           os.path.join(tempfile.gettempdir(),
                                        "otpu_native_cache"))
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"libotpu_native_{tag}.so")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("OTPU_NATIVE_DISABLE"):
            # explicit fallback-lane switch: behave exactly as if the
            # toolchain were absent (CI runs the whole suite this way
            # to prove the pure-Python lanes carry the job alone)
            return None
        try:
            so = _build_path()
            if not os.path.exists(so):
                tmp = so + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", _SRC, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except Exception:
            return None
        lib.otpu_pack_elems.restype = ctypes.c_int64
        lib.otpu_pack_elems.argtypes = [
            _U8P, _U8P, _I64P, _I64P,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        lib.otpu_unpack_elems.restype = ctypes.c_int64
        lib.otpu_unpack_elems.argtypes = [
            _U8P, _U8P, _I64P, _I64P,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        lib.otpu_ring_push.restype = ctypes.c_int
        lib.otpu_ring_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, _U8P, ctypes.c_uint64]
        lib.otpu_ring_push2.restype = ctypes.c_int
        lib.otpu_ring_push2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, _U8P, ctypes.c_uint64,
            _U8P, ctypes.c_uint64]
        lib.otpu_ring_peek_len.restype = ctypes.c_int64
        lib.otpu_ring_peek_len.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.otpu_ring_pop.restype = ctypes.c_int64
        lib.otpu_ring_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, _U8P, ctypes.c_uint64]
        # osc/rdma window atomics
        for name in ("otpu_lock_excl_try", "otpu_lock_shared_try"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p]
        for name in ("otpu_lock_excl_release", "otpu_lock_shared_release"):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p]
        lib.otpu_atomic_add_i64.restype = ctypes.c_int64
        lib.otpu_atomic_add_i64.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.otpu_atomic_cas_i64.restype = ctypes.c_int64
        lib.otpu_atomic_cas_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]
        lib.otpu_atomic_load_u64.restype = ctypes.c_uint64
        lib.otpu_atomic_load_u64.argtypes = [ctypes.c_void_p]
        lib.otpu_atomic_store_u64.restype = None
        lib.otpu_atomic_store_u64.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64]
        # worker pool (mca/threads native substrate)
        lib.otpu_pool_create.restype = ctypes.c_int64
        lib.otpu_pool_create.argtypes = [ctypes.c_int32]
        lib.otpu_pool_destroy.restype = None
        lib.otpu_pool_destroy.argtypes = [ctypes.c_int64]
        lib.otpu_pool_size.restype = ctypes.c_int32
        lib.otpu_pool_size.argtypes = [ctypes.c_int64]
        lib.otpu_pool_memcpy.restype = ctypes.c_int64
        lib.otpu_pool_memcpy.argtypes = [
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64]
        lib.otpu_pool_reduce.restype = ctypes.c_int64
        lib.otpu_pool_reduce.argtypes = [
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        for name in ("otpu_pool_pack", "otpu_pool_unpack"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int64
            fn.argtypes = [
                ctypes.c_int64, _U8P, _U8P, _I64P, _I64P,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64]
        lib.otpu_pool_test.restype = ctypes.c_int32
        lib.otpu_pool_test.argtypes = [ctypes.c_int64]
        lib.otpu_pool_wait.restype = None
        lib.otpu_pool_wait.argtypes = [ctypes.c_int64]
        # progress reactor (runtime/reactor.py front-end)
        try:
            lib.otpu_reactor_create.restype = ctypes.c_int64
            lib.otpu_reactor_create.argtypes = [ctypes.c_int64,
                                                ctypes.c_int64]
            lib.otpu_reactor_destroy.restype = None
            lib.otpu_reactor_destroy.argtypes = [ctypes.c_int64]
            lib.otpu_reactor_notify_fd.restype = ctypes.c_int
            lib.otpu_reactor_notify_fd.argtypes = [ctypes.c_int64]
            lib.otpu_reactor_wait_fd.restype = ctypes.c_int
            lib.otpu_reactor_wait_fd.argtypes = [ctypes.c_int64]
            lib.otpu_reactor_add.restype = ctypes.c_int
            lib.otpu_reactor_add.argtypes = [
                ctypes.c_int64, ctypes.c_int, ctypes.c_int]
            lib.otpu_reactor_del.restype = ctypes.c_int
            lib.otpu_reactor_del.argtypes = [ctypes.c_int64, ctypes.c_int]
            lib.otpu_reactor_rearm.restype = ctypes.c_int
            lib.otpu_reactor_rearm.argtypes = [ctypes.c_int64,
                                               ctypes.c_int]
            lib.otpu_reactor_want_write.restype = ctypes.c_int
            lib.otpu_reactor_want_write.argtypes = [
                ctypes.c_int64, ctypes.c_int, ctypes.c_int]
            # raw void* out-buffer (not an ndpointer): the per-tick
            # caller passes a cached buffer ADDRESS, skipping numpy's
            # from_param validation on the hottest ctypes call
            lib.otpu_reactor_drain.restype = ctypes.c_int64
            lib.otpu_reactor_drain.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_uint64]
            lib.otpu_reactor_take_oversize.restype = ctypes.c_int64
            lib.otpu_reactor_take_oversize.argtypes = [
                ctypes.c_int64, ctypes.c_int, _U8P, ctypes.c_uint64]
            lib.otpu_reactor_stats.restype = ctypes.c_int
            lib.otpu_reactor_stats.argtypes = [
                ctypes.c_int64, _I64P, ctypes.c_int]
            _reactor_ok = True
        except AttributeError:
            # stale cached .so from an older source (hash collision is
            # impossible, but a hand-copied cache is not): the pack/
            # ring/pool substrate still works, only the reactor is off
            _reactor_ok = False
        global _has_reactor
        _has_reactor = _reactor_ok
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def reactor_supported() -> bool:
    """The library is loaded AND exports the progress-reactor entry
    points (a non-Linux build stubs them; ``reactor_create`` then
    returns 0 and the runtime stays on the pure-Python lane)."""
    return _load() is not None and _has_reactor


# -- progress reactor entry points ----------------------------------------

def reactor_create(ring_cap: int = 8 << 20,
                   oversize_limit: int = 4 << 20) -> int:
    """Start the epoll reactor thread; returns a handle (0: failed)."""
    if not reactor_supported():
        return 0
    return int(_load().otpu_reactor_create(ring_cap, oversize_limit))


def reactor_destroy(handle: int) -> None:
    _load().otpu_reactor_destroy(handle)


def reactor_notify_fd(handle: int) -> int:
    """The eventfd the reactor pokes when completed records land
    (drain clears it)."""
    return int(_load().otpu_reactor_notify_fd(handle))


def reactor_wait_fd(handle: int) -> int:
    """The consumer waiter fd: readable when the reactor's epoll set
    has ready events OR completed records are queued.  Register THIS
    as the progress waiter — an idle consumer then wakes on raw socket
    readiness and picks the frame up inline via the drain-time pump,
    without waiting for the (idle-priority) reactor thread to be
    scheduled on a saturated host."""
    return int(_load().otpu_reactor_wait_fd(handle))


def reactor_add(handle: int, fd: int, mode: int) -> bool:
    """Register ``fd``: mode 0 = byte stream (framing + parse), 1 =
    notify-only oneshot (listener), 2 = drain-dgram (doorbell)."""
    return int(_load().otpu_reactor_add(handle, fd, mode)) == 0


def reactor_del(handle: int, fd: int) -> bool:
    return int(_load().otpu_reactor_del(handle, fd)) == 0


def reactor_rearm(handle: int, fd: int) -> bool:
    """Re-arm a notify-mode fd after servicing its ACCEPT record."""
    return int(_load().otpu_reactor_rearm(handle, fd)) == 0


def reactor_want_write(handle: int, fd: int, on: bool) -> bool:
    """(De)register EPOLLOUT interest for a backpressured stream fd."""
    return int(_load().otpu_reactor_want_write(
        handle, fd, 1 if on else 0)) == 0


def reactor_drain(handle: int, out: np.ndarray) -> int:
    """Copy completed records into ``out``; returns bytes copied, or a
    NEGATIVE needed-size when the next record does not fit (grow and
    retry).  The one ctypes call on the per-tick hot path."""
    return int(_load().otpu_reactor_drain(
        handle, out.ctypes.data, len(out)))


def reactor_drain_fn():
    """The bound ctypes drain entry point itself, for the per-tick
    caller (runtime/reactor.drain) to cache: calling it directly with
    (handle, buffer_address, capacity) ints skips the module lookup
    and wrapper frame on every progress tick.  Releases the GIL for
    the duration like any CDLL call — the inline pump's recv/parse
    runs GIL-free on the consumer thread too."""
    lib = _load()
    return None if lib is None else lib.otpu_reactor_drain


def reactor_take_oversize(handle: int, fd: int, out: np.ndarray) -> int:
    """Fetch a parked oversize frame (resumes the stream); returns its
    length, a negative needed-size, or -1 when nothing is parked."""
    return int(_load().otpu_reactor_take_oversize(handle, fd, out,
                                                  len(out)))


def reactor_stats(handle: int) -> dict:
    """Reactor counters for telemetry/otpu_info (racy reads)."""
    out = np.zeros(7, np.int64)
    n = int(_load().otpu_reactor_stats(handle, out, len(out)))
    keys = ("fds", "records", "frames_fast", "frames_raw",
            "overflow", "wakeups", "pumps")
    return {k: int(out[i]) for i, k in enumerate(keys[:n])}


# -- datatype engine entry points ----------------------------------------

def pack_elems(mem: np.ndarray, out: np.ndarray, seg_off: np.ndarray,
               seg_len: np.ndarray, extent: int, base_offset: int,
               first_elem: int, nelem: int) -> int:
    """Gather ``nelem`` whole elements into ``out``; returns bytes."""
    lib = _load()
    return int(lib.otpu_pack_elems(
        mem, out, seg_off, seg_len, len(seg_off), extent, base_offset,
        first_elem, nelem))


def unpack_elems(mem: np.ndarray, chunk: np.ndarray, seg_off: np.ndarray,
                 seg_len: np.ndarray, extent: int, base_offset: int,
                 first_elem: int, nelem: int) -> int:
    lib = _load()
    return int(lib.otpu_unpack_elems(
        mem, chunk, seg_off, seg_len, len(seg_off), extent, base_offset,
        first_elem, nelem))


# -- osc/rdma window atomics ---------------------------------------------

def lock_excl_try(addr: int) -> bool:
    return bool(_load().otpu_lock_excl_try(addr))


def lock_excl_release(addr: int) -> None:
    _load().otpu_lock_excl_release(addr)


def lock_shared_try(addr: int) -> bool:
    return bool(_load().otpu_lock_shared_try(addr))


def lock_shared_release(addr: int) -> None:
    _load().otpu_lock_shared_release(addr)


def atomic_add_i64(addr: int, delta: int) -> int:
    """Fetch-and-add on a mapped int64; returns the old value."""
    return int(_load().otpu_atomic_add_i64(addr, delta))


def atomic_cas_i64(addr: int, expected: int, desired: int) -> tuple:
    """(old_value, swapped) CAS on a mapped int64."""
    ok = ctypes.c_int32(0)
    old = _load().otpu_atomic_cas_i64(addr, expected, desired,
                                      ctypes.byref(ok))
    return int(old), bool(ok.value)


def atomic_load_u64(addr: int) -> int:
    return int(_load().otpu_atomic_load_u64(addr))


def atomic_store_u64(addr: int, v: int) -> None:
    _load().otpu_atomic_store_u64(addr, v)


# -- worker pool (mca/threads native substrate) ---------------------------

#: reduce op codes shared with otpu_pool_reduce
POOL_OPS = {"sum": 0, "prod": 1, "max": 2, "min": 3}
#: dtype codes shared with otpu_pool_reduce
POOL_DTYPES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3}


def pool_create(nthreads: int) -> int:
    return int(_load().otpu_pool_create(nthreads))


def pool_destroy(handle: int) -> None:
    _load().otpu_pool_destroy(handle)


def pool_size(handle: int) -> int:
    return int(_load().otpu_pool_size(handle))


def pool_memcpy(handle: int, dst_addr: int, src_addr: int,
                nbytes: int) -> int:
    """Parallel memcpy; returns a ticket for pool_wait/pool_test."""
    return int(_load().otpu_pool_memcpy(handle, dst_addr, src_addr, nbytes))


def pool_reduce(handle: int, op: str, dtype: str, acc_addr: int,
                src_addr: int, count: int) -> int:
    """Parallel elementwise ``acc = acc <op> src``; returns a ticket."""
    return int(_load().otpu_pool_reduce(
        handle, POOL_OPS[op], POOL_DTYPES[dtype], acc_addr, src_addr,
        count))


def pool_pack(handle: int, mem: np.ndarray, out: np.ndarray,
              seg_off: np.ndarray, seg_len: np.ndarray, extent: int,
              base_offset: int, first_elem: int, nelem: int) -> int:
    """Parallel whole-element gather (pack_elems split over workers)."""
    return int(_load().otpu_pool_pack(
        handle, mem, out, seg_off, seg_len, len(seg_off), extent,
        base_offset, first_elem, nelem))


def pool_unpack(handle: int, mem: np.ndarray, chunk: np.ndarray,
                seg_off: np.ndarray, seg_len: np.ndarray, extent: int,
                base_offset: int, first_elem: int, nelem: int) -> int:
    return int(_load().otpu_pool_unpack(
        handle, mem, chunk, seg_off, seg_len, len(seg_off), extent,
        base_offset, first_elem, nelem))


def pool_test(ticket: int) -> bool:
    return bool(_load().otpu_pool_test(ticket))


def pool_wait(ticket: int) -> None:
    """Block until done and free the ticket (call exactly once)."""
    _load().otpu_pool_wait(ticket)


# -- sm ring entry points -------------------------------------------------

def ring_push(buf_addr: int, cap: int, payload: np.ndarray) -> bool:
    lib = _load()
    return bool(lib.otpu_ring_push(buf_addr, cap, payload, len(payload)))


def ring_push2(buf_addr: int, cap: int, a: np.ndarray,
               b: np.ndarray) -> bool:
    """Gather-push one frame from two buffers (header + payload)."""
    lib = _load()
    return bool(lib.otpu_ring_push2(buf_addr, cap, a, len(a), b, len(b)))


def ring_peek_len(buf_addr: int, cap: int) -> int:
    """Next complete frame's length, or -1 when none is ready."""
    lib = _load()
    return int(lib.otpu_ring_peek_len(buf_addr, cap))


def ring_pop(buf_addr: int, cap: int, out: np.ndarray) -> int:
    """Returns payload length, -1 if empty/incomplete, -2 if out too small."""
    lib = _load()
    return int(lib.otpu_ring_pop(buf_addr, cap, out, len(out)))
