// otpu_native — C++ twins of the hot host-path loops.
//
// The reference implements these in C for the same reason (the datatype
// pack engine `opal/datatype/opal_datatype_pack.c` and the sm fifo
// `opal/class/opal_fifo.h`): per-element gather/scatter and ring ops are
// tight loops the interpreter cannot keep up with.  The Python layers
// (ompi_tpu/datatype/convertor.py, ompi_tpu/mca/btl/sm.py) call these
// through ctypes when the shared library is available and fall back to
// their numpy implementations otherwise.
//
// Build: g++ -O3 -shared -fPIC otpu_native.cc -o libotpu_native.so
// (driven lazily by ompi_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

extern "C" {

// ---- datatype engine: whole-element gather/scatter ---------------------
//
// Stream layout: element e of the datatype contributes its segments in
// type-map order; segment j lives at base_offset + e*extent + seg_off[j]
// in memory and occupies seg_len[j] bytes of the packed stream.

int64_t otpu_pack_elems(const uint8_t *base, uint8_t *out,
                        const int64_t *seg_off, const int64_t *seg_len,
                        int64_t nseg, int64_t extent, int64_t base_offset,
                        int64_t first_elem, int64_t nelem) {
    uint8_t *dst = out;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        const uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(dst, ebase + seg_off[j], (size_t)seg_len[j]);
            dst += seg_len[j];
        }
    }
    return dst - out;
}

int64_t otpu_unpack_elems(uint8_t *base, const uint8_t *in,
                          const int64_t *seg_off, const int64_t *seg_len,
                          int64_t nseg, int64_t extent, int64_t base_offset,
                          int64_t first_elem, int64_t nelem) {
    const uint8_t *src = in;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(ebase + seg_off[j], src, (size_t)seg_len[j]);
            src += seg_len[j];
        }
    }
    return src - in;
}

// ---- btl/sm: SPSC byte ring -------------------------------------------
//
// Layout (matches ompi_tpu/mca/btl/sm.py `_Ring`):
//   [ head u64 | tail u64 | data[cap] ]
// frames are <u32 length><payload>, wrapping modulo cap.  Single producer
// advances tail, single consumer advances head (acquire/release pairs —
// the property the reference's opal_fifo gets from its atomics).

static inline uint64_t load_acq(const uint8_t *p) {
    return __atomic_load_n((const uint64_t *)p, __ATOMIC_ACQUIRE);
}
static inline void store_rel(uint8_t *p, uint64_t v) {
    __atomic_store_n((uint64_t *)p, v, __ATOMIC_RELEASE);
}

static void ring_write(uint8_t *data, uint64_t cap, uint64_t pos,
                       const uint8_t *src, uint64_t n) {
    uint64_t p = pos % cap;
    uint64_t first = n < cap - p ? n : cap - p;
    std::memcpy(data + p, src, (size_t)first);
    if (first < n)
        std::memcpy(data, src + first, (size_t)(n - first));
}

int otpu_ring_push(uint8_t *buf, uint64_t cap, const uint8_t *payload,
                   uint64_t n) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t need = 4 + n;
    if (need > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, payload, n);
    store_rel(buf + 8, tail + need);
    return 1;
}

// Gather-push: one frame from two source buffers (header + payload),
// written back-to-back so the caller never has to concatenate them in
// Python (the concatenation would copy the payload an extra time).
int otpu_ring_push2(uint8_t *buf, uint64_t cap,
                    const uint8_t *a, uint64_t alen,
                    const uint8_t *b, uint64_t blen) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t n = alen + blen;
    if (4 + n > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, a, alen);
    ring_write(data, cap, tail + 4 + alen, b, blen);
    store_rel(buf + 8, tail + 4 + n);
    return 1;
}

// Length of the next complete frame, or -1 when none is ready — lets the
// consumer allocate an exact-size owned buffer before popping (so frame
// payloads can be delivered as zero-copy views of that buffer).
int64_t otpu_ring_peek_len(const uint8_t *buf, uint64_t cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    uint64_t p = head % cap;
    uint8_t tmp[4];
    uint64_t first = 4 < cap - p ? 4 : cap - p;
    std::memcpy(tmp, data + p, (size_t)first);
    if (first < 4)
        std::memcpy(tmp + first, data, (size_t)(4 - first));
    std::memcpy(&len32, tmp, 4);
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    return (int64_t)n;
}

int64_t otpu_ring_pop(uint8_t *buf, uint64_t cap, uint8_t *out,
                      uint64_t out_cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    {   // read the length header (may wrap)
        uint64_t p = head % cap;
        uint8_t tmp[4];
        uint64_t first = 4 < cap - p ? 4 : cap - p;
        std::memcpy(tmp, data + p, (size_t)first);
        if (first < 4)
            std::memcpy(tmp + first, data, (size_t)(4 - first));
        std::memcpy(&len32, tmp, 4);
    }
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    if (n > out_cap)
        return -2;          // caller buffer too small
    {   // read the payload (may wrap)
        uint64_t p = (head + 4) % cap;
        uint64_t first = n < cap - p ? n : cap - p;
        std::memcpy(out, data + p, (size_t)first);
        if (first < n)
            std::memcpy(out + first, data, (size_t)(n - first));
    }
    store_rel(buf, head + 4 + n);
    return (int64_t)n;
}

// ---- osc/rdma: cross-process atomics on mapped windows ------------------
//
// The reference's osc/rdma implements locks and accumulates via remote
// atomic CAS over the BTL (`osc_rdma_accumulate.c:26-71`).  On a same-host
// mapped window the "remote" atomic is a plain shared-memory atomic; the
// lock word lives in the window segment header.  Layout of the lock word:
// bit 63 = exclusive held, bits 0..62 = shared-reader count.

static const uint64_t EXCL_BIT = 1ull << 63;

int otpu_lock_excl_try(uint8_t *word) {
    uint64_t expected = 0;
    return __atomic_compare_exchange_n(
        (uint64_t *)word, &expected, EXCL_BIT, false,
        __ATOMIC_ACQUIRE, __ATOMIC_RELAXED) ? 1 : 0;
}

void otpu_lock_excl_release(uint8_t *word) {
    __atomic_store_n((uint64_t *)word, 0, __ATOMIC_RELEASE);
}

int otpu_lock_shared_try(uint8_t *word) {
    uint64_t cur = __atomic_load_n((uint64_t *)word, __ATOMIC_RELAXED);
    while (!(cur & EXCL_BIT)) {
        if (__atomic_compare_exchange_n(
                (uint64_t *)word, &cur, cur + 1, false,
                __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
            return 1;
        // cur reloaded by the failed CAS; loop unless exclusive appeared
    }
    return 0;
}

void otpu_lock_shared_release(uint8_t *word) {
    __atomic_fetch_sub((uint64_t *)word, 1, __ATOMIC_RELEASE);
}

int64_t otpu_atomic_add_i64(uint8_t *ptr, int64_t delta) {
    return __atomic_fetch_add((int64_t *)ptr, delta, __ATOMIC_ACQ_REL);
}

// returns the OLD value; *ok set to 1 when the swap happened
int64_t otpu_atomic_cas_i64(uint8_t *ptr, int64_t expected, int64_t desired,
                            int32_t *ok) {
    int64_t exp = expected;
    int swapped = __atomic_compare_exchange_n(
        (int64_t *)ptr, &exp, desired, false,
        __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE);
    *ok = swapped ? 1 : 0;
    return exp;  // old value on failure, `expected` (== old) on success
}

uint64_t otpu_atomic_load_u64(const uint8_t *ptr) {
    return __atomic_load_n((const uint64_t *)ptr, __ATOMIC_ACQUIRE);
}

void otpu_atomic_store_u64(uint8_t *ptr, uint64_t v) {
    __atomic_store_n((uint64_t *)ptr, v, __ATOMIC_RELEASE);
}

}  // extern "C"
