// otpu_native — C++ twins of the hot host-path loops.
//
// The reference implements these in C for the same reason (the datatype
// pack engine `opal/datatype/opal_datatype_pack.c` and the sm fifo
// `opal/class/opal_fifo.h`): per-element gather/scatter and ring ops are
// tight loops the interpreter cannot keep up with.  The Python layers
// (ompi_tpu/datatype/convertor.py, ompi_tpu/mca/btl/sm.py) call these
// through ctypes when the shared library is available and fall back to
// their numpy implementations otherwise.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread otpu_native.cc
//        -o libotpu_native.so
// (driven lazily by ompi_tpu/native/__init__.py; -pthread is required
// by the worker pool's std::thread).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#ifdef __linux__
#include <cerrno>
#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>
#endif

extern "C" {

// ---- datatype engine: whole-element gather/scatter ---------------------
//
// Stream layout: element e of the datatype contributes its segments in
// type-map order; segment j lives at base_offset + e*extent + seg_off[j]
// in memory and occupies seg_len[j] bytes of the packed stream.

int64_t otpu_pack_elems(const uint8_t *base, uint8_t *out,
                        const int64_t *seg_off, const int64_t *seg_len,
                        int64_t nseg, int64_t extent, int64_t base_offset,
                        int64_t first_elem, int64_t nelem) {
    uint8_t *dst = out;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        const uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(dst, ebase + seg_off[j], (size_t)seg_len[j]);
            dst += seg_len[j];
        }
    }
    return dst - out;
}

int64_t otpu_unpack_elems(uint8_t *base, const uint8_t *in,
                          const int64_t *seg_off, const int64_t *seg_len,
                          int64_t nseg, int64_t extent, int64_t base_offset,
                          int64_t first_elem, int64_t nelem) {
    const uint8_t *src = in;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(ebase + seg_off[j], src, (size_t)seg_len[j]);
            src += seg_len[j];
        }
    }
    return src - in;
}

// ---- btl/sm: SPSC byte ring -------------------------------------------
//
// Layout (matches ompi_tpu/mca/btl/sm.py `_Ring`):
//   [ head u64 | tail u64 | data[cap] ]
// frames are <u32 length><payload>, wrapping modulo cap.  Single producer
// advances tail, single consumer advances head (acquire/release pairs —
// the property the reference's opal_fifo gets from its atomics).

static inline uint64_t load_acq(const uint8_t *p) {
    return __atomic_load_n((const uint64_t *)p, __ATOMIC_ACQUIRE);
}
static inline void store_rel(uint8_t *p, uint64_t v) {
    __atomic_store_n((uint64_t *)p, v, __ATOMIC_RELEASE);
}

static void ring_write(uint8_t *data, uint64_t cap, uint64_t pos,
                       const uint8_t *src, uint64_t n) {
    uint64_t p = pos % cap;
    uint64_t first = n < cap - p ? n : cap - p;
    std::memcpy(data + p, src, (size_t)first);
    if (first < n)
        std::memcpy(data, src + first, (size_t)(n - first));
}

int otpu_ring_push(uint8_t *buf, uint64_t cap, const uint8_t *payload,
                   uint64_t n) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t need = 4 + n;
    if (need > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, payload, n);
    store_rel(buf + 8, tail + need);
    return 1;
}

// Gather-push: one frame from two source buffers (header + payload),
// written back-to-back so the caller never has to concatenate them in
// Python (the concatenation would copy the payload an extra time).
int otpu_ring_push2(uint8_t *buf, uint64_t cap,
                    const uint8_t *a, uint64_t alen,
                    const uint8_t *b, uint64_t blen) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t n = alen + blen;
    if (4 + n > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, a, alen);
    ring_write(data, cap, tail + 4 + alen, b, blen);
    store_rel(buf + 8, tail + 4 + n);
    return 1;
}

// Length of the next complete frame, or -1 when none is ready — lets the
// consumer allocate an exact-size owned buffer before popping (so frame
// payloads can be delivered as zero-copy views of that buffer).
int64_t otpu_ring_peek_len(const uint8_t *buf, uint64_t cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    uint64_t p = head % cap;
    uint8_t tmp[4];
    uint64_t first = 4 < cap - p ? 4 : cap - p;
    std::memcpy(tmp, data + p, (size_t)first);
    if (first < 4)
        std::memcpy(tmp + first, data, (size_t)(4 - first));
    std::memcpy(&len32, tmp, 4);
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    return (int64_t)n;
}

int64_t otpu_ring_pop(uint8_t *buf, uint64_t cap, uint8_t *out,
                      uint64_t out_cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    {   // read the length header (may wrap)
        uint64_t p = head % cap;
        uint8_t tmp[4];
        uint64_t first = 4 < cap - p ? 4 : cap - p;
        std::memcpy(tmp, data + p, (size_t)first);
        if (first < 4)
            std::memcpy(tmp + first, data, (size_t)(4 - first));
        std::memcpy(&len32, tmp, 4);
    }
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    if (n > out_cap)
        return -2;          // caller buffer too small
    {   // read the payload (may wrap)
        uint64_t p = (head + 4) % cap;
        uint64_t first = n < cap - p ? n : cap - p;
        std::memcpy(out, data + p, (size_t)first);
        if (first < n)
            std::memcpy(out + first, data, (size_t)(n - first));
    }
    store_rel(buf, head + 4 + n);
    return (int64_t)n;
}

// ---- osc/rdma: cross-process atomics on mapped windows ------------------
//
// The reference's osc/rdma implements locks and accumulates via remote
// atomic CAS over the BTL (`osc_rdma_accumulate.c:26-71`).  On a same-host
// mapped window the "remote" atomic is a plain shared-memory atomic; the
// lock word lives in the window segment header.  Layout of the lock word:
// bit 63 = exclusive held, bits 0..62 = shared-reader count.

static const uint64_t EXCL_BIT = 1ull << 63;

int otpu_lock_excl_try(uint8_t *word) {
    uint64_t expected = 0;
    return __atomic_compare_exchange_n(
        (uint64_t *)word, &expected, EXCL_BIT, false,
        __ATOMIC_ACQUIRE, __ATOMIC_RELAXED) ? 1 : 0;
}

void otpu_lock_excl_release(uint8_t *word) {
    __atomic_store_n((uint64_t *)word, 0, __ATOMIC_RELEASE);
}

int otpu_lock_shared_try(uint8_t *word) {
    uint64_t cur = __atomic_load_n((uint64_t *)word, __ATOMIC_RELAXED);
    while (!(cur & EXCL_BIT)) {
        if (__atomic_compare_exchange_n(
                (uint64_t *)word, &cur, cur + 1, false,
                __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
            return 1;
        // cur reloaded by the failed CAS; loop unless exclusive appeared
    }
    return 0;
}

void otpu_lock_shared_release(uint8_t *word) {
    __atomic_fetch_sub((uint64_t *)word, 1, __ATOMIC_RELEASE);
}

int64_t otpu_atomic_add_i64(uint8_t *ptr, int64_t delta) {
    return __atomic_fetch_add((int64_t *)ptr, delta, __ATOMIC_ACQ_REL);
}

// returns the OLD value; *ok set to 1 when the swap happened
int64_t otpu_atomic_cas_i64(uint8_t *ptr, int64_t expected, int64_t desired,
                            int32_t *ok) {
    int64_t exp = expected;
    int swapped = __atomic_compare_exchange_n(
        (int64_t *)ptr, &exp, desired, false,
        __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE);
    *ok = swapped ? 1 : 0;
    return exp;  // old value on failure, `expected` (== old) on success
}

uint64_t otpu_atomic_load_u64(const uint8_t *ptr) {
    return __atomic_load_n((const uint64_t *)ptr, __ATOMIC_ACQUIRE);
}

void otpu_atomic_store_u64(uint8_t *ptr, uint64_t v) {
    __atomic_store_n((uint64_t *)ptr, v, __ATOMIC_RELEASE);
}

// ---- threads: native worker pool ---------------------------------------
//
// The reference's threading substrate (`opal/mca/threads/threads.h`) gives
// the host data path real OS threads — progress, packing, and reduction
// math run concurrently with the application.  A Python framework cannot
// get that from `threading` (the GIL serialises it), so the pool lives
// here: jobs are split into per-worker chunks of pure C++ (memcpy, the
// datatype element loops above, elementwise reduction math), ctypes drops
// the GIL for the submitting call, and the workers never touch Python.
// One job -> one ticket; a ticket completes when every chunk ran.

}  // extern "C" (the pool internals below are C++; the API re-opens it)

namespace {

struct OtpuTicket {
    std::atomic<int64_t> remaining;
    std::mutex m;
    std::condition_variable cv;
    explicit OtpuTicket(int64_t n) : remaining(n) {}
};

struct OtpuChunk {
    int32_t kind;            // 0 memcpy, 1 pack, 2 unpack, 3 reduce
    OtpuTicket *ticket;
    uint8_t *dst;
    const uint8_t *src;
    int64_t n;
    int32_t op, dtype;       // reduce: op 0 sum 1 prod 2 max 3 min;
                             // dtype 0 f32 1 f64 2 i32 3 i64
    const int64_t *seg_off, *seg_len;
    int64_t nseg, extent, base_offset, first_elem, nelem;
};

template <typename T>
static void reduce_span(T *acc, const T *src, int64_t count, int32_t op) {
    // max/min match np.maximum/np.minimum exactly, including NaN
    // propagation from EITHER operand (src!=src catches a NaN src; a
    // NaN acc keeps itself because 'acc < NaN' is false) — the
    // sub-threshold numpy path and the python substrate must be
    // bit-interchangeable with this one.  For integers x!=x is
    // constant-false and folds away.
    switch (op) {
    case 0: for (int64_t i = 0; i < count; ++i) acc[i] += src[i]; break;
    case 1: for (int64_t i = 0; i < count; ++i) acc[i] *= src[i]; break;
    case 2: for (int64_t i = 0; i < count; ++i)
                acc[i] = (src[i] != src[i] || acc[i] < src[i])
                             ? src[i] : acc[i];
            break;
    default: for (int64_t i = 0; i < count; ++i)
                acc[i] = (src[i] != src[i] || src[i] < acc[i])
                             ? src[i] : acc[i];
    }
}

static void run_chunk(const OtpuChunk &c) {
    switch (c.kind) {
    case 0:
        std::memcpy(c.dst, c.src, (size_t)c.n);
        break;
    case 1:
        otpu_pack_elems(c.src, c.dst, c.seg_off, c.seg_len, c.nseg,
                        c.extent, c.base_offset, c.first_elem, c.nelem);
        break;
    case 2:
        otpu_unpack_elems(c.dst, c.src, c.seg_off, c.seg_len, c.nseg,
                          c.extent, c.base_offset, c.first_elem, c.nelem);
        break;
    default:
        switch (c.dtype) {
        case 0: reduce_span((float *)c.dst, (const float *)c.src,
                            c.n, c.op); break;
        case 1: reduce_span((double *)c.dst, (const double *)c.src,
                            c.n, c.op); break;
        case 2: reduce_span((int32_t *)c.dst, (const int32_t *)c.src,
                            c.n, c.op); break;
        default: reduce_span((int64_t *)c.dst, (const int64_t *)c.src,
                             c.n, c.op);
        }
    }
}

struct OtpuPool {
    std::vector<std::thread> workers;
    std::deque<OtpuChunk> queue;
    std::mutex m;
    std::condition_variable cv;
    bool stop = false;

    explicit OtpuPool(int32_t n) {
        for (int32_t i = 0; i < n; ++i)
            workers.emplace_back([this] { loop(); });
    }

    void loop() {
        for (;;) {
            OtpuChunk c;
            {
                std::unique_lock<std::mutex> lk(m);
                cv.wait(lk, [this] { return stop || !queue.empty(); });
                if (queue.empty())
                    return;            // stop && drained
                c = queue.front();
                queue.pop_front();
            }
            run_chunk(c);
            {
                // decrement under the ticket mutex: a waiter holding it
                // cannot observe remaining==0 and free the ticket while
                // this worker is still about to touch it
                std::lock_guard<std::mutex> lk(c.ticket->m);
                if (c.ticket->remaining.fetch_sub(
                        1, std::memory_order_acq_rel) == 1)
                    c.ticket->cv.notify_all();
            }
        }
    }

    OtpuTicket *submit(std::vector<OtpuChunk> &chunks) {
        OtpuTicket *t = new OtpuTicket((int64_t)chunks.size());
        {
            std::lock_guard<std::mutex> lk(m);
            for (auto &c : chunks) {
                c.ticket = t;
                queue.push_back(c);
            }
        }
        cv.notify_all();
        return t;
    }
};

}  // namespace

extern "C" {

int64_t otpu_pool_create(int32_t nthreads) {
    if (nthreads < 1)
        nthreads = 1;
    return (int64_t)(intptr_t) new OtpuPool(nthreads);
}

void otpu_pool_destroy(int64_t pool) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    {
        std::lock_guard<std::mutex> lk(p->m);
        p->stop = true;
    }
    p->cv.notify_all();
    for (auto &w : p->workers)
        w.join();
    delete p;
}

int32_t otpu_pool_size(int64_t pool) {
    return (int32_t)((OtpuPool *)(intptr_t)pool)->workers.size();
}

// Split [0, n) into per-worker spans of at least `grain` units.
static std::vector<std::pair<int64_t, int64_t>> spans(
        int64_t n, int64_t nworkers, int64_t grain) {
    int64_t pieces = n / grain;
    if (pieces > nworkers) pieces = nworkers;
    if (pieces < 1) pieces = 1;
    std::vector<std::pair<int64_t, int64_t>> out;
    int64_t per = n / pieces, rem = n % pieces, at = 0;
    for (int64_t i = 0; i < pieces; ++i) {
        int64_t len = per + (i < rem ? 1 : 0);
        out.emplace_back(at, len);
        at += len;
    }
    return out;
}

int64_t otpu_pool_memcpy(int64_t pool, uint8_t *dst, const uint8_t *src,
                         int64_t n) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(n, (int64_t)p->workers.size(), 1 << 16)) {
        OtpuChunk c{};
        c.kind = 0;
        c.dst = dst + sp.first;
        c.src = src + sp.first;
        c.n = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

int64_t otpu_pool_reduce(int64_t pool, int32_t op, int32_t dtype,
                         uint8_t *acc, const uint8_t *src, int64_t count) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    int64_t esz = (dtype == 0 || dtype == 2) ? 4 : 8;
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(count, (int64_t)p->workers.size(), 1 << 14)) {
        OtpuChunk c{};
        c.kind = 3;
        c.op = op;
        c.dtype = dtype;
        c.dst = acc + sp.first * esz;
        c.src = src + sp.first * esz;
        c.n = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

static int64_t pool_packish(int64_t pool, int32_t kind, uint8_t *mem,
                            uint8_t *stream, const int64_t *seg_off,
                            const int64_t *seg_len, int64_t nseg,
                            int64_t extent, int64_t base_offset,
                            int64_t first_elem, int64_t nelem) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    int64_t elem_packed = 0;
    for (int64_t j = 0; j < nseg; ++j)
        elem_packed += seg_len[j];
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(nelem, (int64_t)p->workers.size(), 64)) {
        OtpuChunk c{};
        c.kind = kind;
        uint8_t *schunk = stream + sp.first * elem_packed;
        if (kind == 1) {               // pack: mem -> stream
            c.src = mem;
            c.dst = schunk;
        } else {                       // unpack: stream -> mem
            c.dst = mem;
            c.src = schunk;
        }
        c.seg_off = seg_off;
        c.seg_len = seg_len;
        c.nseg = nseg;
        c.extent = extent;
        c.base_offset = base_offset;
        c.first_elem = first_elem + sp.first;
        c.nelem = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

int64_t otpu_pool_pack(int64_t pool, uint8_t *mem, uint8_t *out,
                       const int64_t *seg_off, const int64_t *seg_len,
                       int64_t nseg, int64_t extent, int64_t base_offset,
                       int64_t first_elem, int64_t nelem) {
    return pool_packish(pool, 1, mem, out, seg_off, seg_len, nseg, extent,
                        base_offset, first_elem, nelem);
}

int64_t otpu_pool_unpack(int64_t pool, uint8_t *mem, uint8_t *in,
                         const int64_t *seg_off, const int64_t *seg_len,
                         int64_t nseg, int64_t extent, int64_t base_offset,
                         int64_t first_elem, int64_t nelem) {
    return pool_packish(pool, 2, mem, in, seg_off, seg_len, nseg, extent,
                        base_offset, first_elem, nelem);
}

int32_t otpu_pool_test(int64_t ticket) {
    OtpuTicket *t = (OtpuTicket *)(intptr_t)ticket;
    return t->remaining.load(std::memory_order_acquire) == 0 ? 1 : 0;
}

// Blocks until done, then frees the ticket (call exactly once).
void otpu_pool_wait(int64_t ticket) {
    OtpuTicket *t = (OtpuTicket *)(intptr_t)ticket;
    {
        std::unique_lock<std::mutex> lk(t->m);
        t->cv.wait(lk, [t] {
            return t->remaining.load(std::memory_order_acquire) == 0;
        });
    }
    delete t;
}

}  // extern "C"

// ---- runtime/progress: the native reactor -------------------------------
//
// An epoll loop over the btl fds that runs the tcp hot path — socket
// drain (recv into scratch), wire framing ([u32 frame_len][frame]),
// split-tail reassembly, and header-type lane routing — on a dedicated
// OS thread with no GIL anywhere near it.  Completed frames land in a
// lock-free SPSC record queue the Python side empties with ONE ctypes
// call per progress() tick (otpu_reactor_drain).  The reference analog
// is opal_progress driving libevent: the event loop lives below the
// language runtime and the upper layer only sees completed work.
//
// Record stream layout (little-endian, matches runtime/reactor.py):
//   record  := [u32 payload_len][i32 fd][u8 etype][payload]
//   etype 0 := RAW      whole frame (htype byte onward) — the Python
//                       slow lane (_parse_frame): pickle headers,
//                       crc-armed frames, quantized frames, handshakes
//   etype 1 := FAST     frame bytes after the htype byte: the 49-byte
//                       big-endian !IIIiqBqqq header + payload, ready
//                       for the preallocated struct unpack
//   etype 2 := EOF      peer closed / hard error (fd already out of
//                       the epoll set; Python closes + drops the conn)
//   etype 3 := ACCEPT   notify-mode fd readable (listener; ONESHOT —
//                       Python accepts, then otpu_reactor_rearm)
//   etype 4 := WRITABLE backpressured fd turned writable (EPOLLOUT
//                       interest auto-cleared; Python flushes and
//                       re-arms while its queue is non-empty)
//   etype 5 := DOORBELL drain-mode dgram fd rang (datagrams consumed
//                       here; the ring frames carry the data)
//   etype 6 := OVERSIZE payload = u64 frame_len: a frame too large for
//                       the record queue is parked in the stream, the
//                       fd leaves the epoll set, and Python fetches it
//                       with otpu_reactor_take_oversize (which resumes
//                       the stream)
//   etype 7 := DESYNC   payload = u64 bad frame_len: framing desync
//                       (zero-length frame) — Python fails loudly
//
// The queue is the SPSC ring above (single producer: the reactor
// thread; single consumer: whichever Python thread runs progress(),
// serialised by the drain lock on that side).  When the ring is
// momentarily full the producer NEVER blocks — it appends to a small
// mutex-guarded overflow list instead (and keeps appending there until
// the consumer empties it, which preserves global record order).
// Blocking with the stream-map mutex held would deadlock against a
// Python thread doing fd bookkeeping while it drains.

#ifdef __linux__

namespace {

enum {
    REC_RAW = 0, REC_FAST = 1, REC_EOF = 2, REC_ACCEPT = 3,
    REC_WRITABLE = 4, REC_DOORBELL = 5, REC_OVERSIZE = 6, REC_DESYNC = 7,
};

constexpr size_t REC_HDR = 9;          // u32 len + i32 fd + u8 etype
constexpr size_t RX_SCRATCH = 1 << 18; // one recv's worth, like _Conn

static inline uint32_t load_be32(const uint8_t *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

struct RStream {
    int fd = -1;
    int mode = 0;          // 0 stream, 1 notify (oneshot), 2 drain-dgram
    bool dead = false;     // EOF/desync emitted; ignore further events
    bool parked = false;   // oversize frame held; fd out of the epoll set
    bool want_write = false;
    std::vector<uint8_t> pend;     // partial tail: [u32 len][bytes so far]
    std::vector<uint8_t> carry;    // unparsed input arriving while parked
    std::vector<uint8_t> oversize; // the parked frame (htype onward)
};

struct Reactor {
    int epfd = -1;
    int wakefd = -1;       // reactor-thread pokes (stop / resume)
    int notifyfd = -1;     // consumer wakeups (drain clears it)
    int waitfd = -1;       // selectable OR of {epfd, notifyfd}: the fd
                           // Python registers as the progress waiter —
                           // an idle consumer wakes on RAW socket
                           // readiness (then pumps inline) instead of
                           // waiting out a reactor-thread scheduling
                           // hop on an oversubscribed host
    uint64_t ring_cap;
    uint64_t oversize_limit;
    std::vector<uint8_t> ring;     // [head u64 | tail u64 | data] layout
    std::mutex ov_m;
    std::deque<std::vector<uint8_t>> overflow;
    std::atomic<bool> has_overflow{false};  // mirror of !overflow.empty()
    std::mutex m;                  // stream map + cross-thread fd flags
    std::unordered_map<int, RStream *> streams;
    std::vector<int> resume_fds;   // taken by the reactor thread under m
    std::atomic<bool> stop{false};
    std::thread thr;
    uint8_t scratch[RX_SCRATCH];
    // counters (written under R->m, racy reads are fine)
    uint64_t n_frames_fast = 0, n_frames_raw = 0, n_records = 0;
    uint64_t n_overflow = 0, n_wakeups = 0, n_pumps = 0;
};

static void reactor_loop(Reactor *R);

static inline uint8_t *rq_base(Reactor *R) { return R->ring.data(); }

static void notify_consumer(Reactor *R) {
    uint64_t one = 1;
    ssize_t r = ::write(R->notifyfd, &one, 8);
    (void)r;               // EAGAIN: counter already non-zero, still wakes
    R->n_wakeups++;
}

// Append one record (header + up to two payload parts) to the queue.
// Producer side is whichever thread holds R->m (the reactor thread, or
// the consumer thread inside pump()) — serialisation by R->m keeps the
// ring single-producer.  Never blocks: ring when it fits, overflow
// otherwise — and always overflow while overflow is non-empty, so the
// consumer's ring-then-overflow drain order preserves arrival order.
static void emit(Reactor *R, int fd, uint8_t etype,
                 const uint8_t *a, uint64_t alen,
                 const uint8_t *b, uint64_t blen) {
    uint8_t *buf = rq_base(R);
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    bool was_empty;
    uint64_t plen = alen + blen;
    uint8_t hdr[REC_HDR];
    uint32_t plen32 = (uint32_t)plen;
    int32_t fd32 = (int32_t)fd;
    std::memcpy(hdr, &plen32, 4);
    std::memcpy(hdr + 4, &fd32, 4);
    hdr[8] = etype;
    {
        std::lock_guard<std::mutex> lk(R->ov_m);
        was_empty = (head == tail) && R->overflow.empty();
        if (!R->overflow.empty() ||
            REC_HDR + plen > R->ring_cap - (tail - head)) {
            std::vector<uint8_t> rec;
            rec.reserve(REC_HDR + plen);
            rec.insert(rec.end(), hdr, hdr + REC_HDR);
            if (alen) rec.insert(rec.end(), a, a + alen);
            if (blen) rec.insert(rec.end(), b, b + blen);
            R->overflow.push_back(std::move(rec));
            R->has_overflow.store(true, std::memory_order_release);
            R->n_overflow++;
        } else {
            uint8_t *data = buf + 16;
            ring_write(data, R->ring_cap, tail, hdr, REC_HDR);
            if (alen)
                ring_write(data, R->ring_cap, tail + REC_HDR, a, alen);
            if (blen)
                ring_write(data, R->ring_cap, tail + REC_HDR + alen,
                           b, blen);
            store_rel(buf + 8, tail + REC_HDR + plen);
        }
    }
    R->n_records++;
    if (was_empty)
        notify_consumer(R);
}

static void epoll_del_quiet(Reactor *R, RStream *s) {
    struct epoll_event ev {};
    ::epoll_ctl(R->epfd, EPOLL_CTL_DEL, s->fd, &ev);
}

static void stream_eof(Reactor *R, RStream *s) {
    if (s->dead)
        return;
    s->dead = true;
    if (!s->parked)
        epoll_del_quiet(R, s);
    emit(R, s->fd, REC_EOF, nullptr, 0, nullptr, 0);
}

// Route one complete frame (htype byte onward).  Returns false when the
// frame was parked (oversize) and parsing of this stream must pause.
static bool handle_frame(Reactor *R, RStream *s, const uint8_t *f,
                         uint64_t fl) {
    if (REC_HDR + fl + 64 > R->oversize_limit) {
        s->oversize.assign(f, f + fl);
        s->parked = true;
        epoll_del_quiet(R, s);
        uint64_t n = fl;
        emit(R, s->fd, REC_OVERSIZE, (const uint8_t *)&n, 8, nullptr, 0);
        return false;
    }
    // lane routing by header-type byte: ONLY the plain fast header
    // (htype == 1, no crc/quant bits) with a sane kind code takes the
    // native lane; everything else goes to Python whole so the slow
    // lane (crc verify, quant decode, pickle, handshake) sees the
    // exact bytes the pure-Python parser would have
    if (f[0] == 1 && fl >= 50 && f[25] <= 5) {
        emit(R, s->fd, REC_FAST, f + 1, fl - 1, nullptr, 0);
        R->n_frames_fast++;
    } else {
        emit(R, s->fd, REC_RAW, f, fl, nullptr, 0);
        R->n_frames_raw++;
    }
    return true;
}

// Bytes still missing before the parked partial frame completes
// (the Python twin is TcpBtl._need).
static uint64_t pend_need(const RStream *s) {
    if (s->pend.size() < 4)
        return 4 - s->pend.size();
    uint64_t fl = load_be32(s->pend.data());
    uint64_t have = s->pend.size();
    return have >= 4 + fl ? 0 : 4 + fl - have;
}

// The framing/reassembly twin of TcpBtl._on_bytes: finish the parked
// split tail first, then parse complete frames straight from the
// chunk, then park whatever partial tail remains.
static void stream_feed(Reactor *R, RStream *s, const uint8_t *p,
                        uint64_t n) {
    uint64_t pos = 0;
    while (!s->pend.empty() && !s->parked && !s->dead) {
        uint64_t need = pend_need(s);
        uint64_t take = need < n - pos ? need : n - pos;
        if (take) {
            s->pend.insert(s->pend.end(), p + pos, p + pos + take);
            pos += take;
        }
        if (pend_need(s) == 0) {
            uint64_t fl = load_be32(s->pend.data());
            if (fl == 0) {
                uint64_t bad = 0;
                emit(R, s->fd, REC_DESYNC,
                     (const uint8_t *)&bad, 8, nullptr, 0);
                s->dead = true;
                epoll_del_quiet(R, s);
                return;
            }
            bool go = handle_frame(R, s, s->pend.data() + 4, fl);
            s->pend.clear();
            if (!go)
                break;          // parked: rest of the chunk -> carry
        } else if (pos >= n) {
            return;             // chunk exhausted mid-frame
        }
    }
    while (!s->parked && !s->dead && n - pos >= 4) {
        uint64_t fl = load_be32(p + pos);
        if (fl == 0) {
            uint64_t bad = 0;
            emit(R, s->fd, REC_DESYNC,
                 (const uint8_t *)&bad, 8, nullptr, 0);
            s->dead = true;
            epoll_del_quiet(R, s);
            return;
        }
        if (n - pos < 4 + fl)
            break;
        if (!handle_frame(R, s, p + pos + 4, fl)) {
            pos += 4 + fl;
            break;              // parked mid-chunk
        }
        pos += 4 + fl;
    }
    if (pos < n && !s->dead) {
        std::vector<uint8_t> &dst = s->parked ? s->carry : s->pend;
        dst.insert(dst.end(), p + pos, p + n);
    }
}

static void stream_readable(Reactor *R, RStream *s) {
    for (;;) {
        ssize_t r = ::recv(s->fd, R->scratch, RX_SCRATCH, 0);
        if (r > 0) {
            stream_feed(R, s, R->scratch, (uint64_t)r);
            if (s->dead || s->parked)
                return;
            if ((size_t)r < RX_SCRATCH)
                return;         // drained (level-triggered: safe anyway)
        } else if (r == 0) {
            stream_eof(R, s);
            return;
        } else {
            if (errno == EINTR)
                continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK)
                return;
            stream_eof(R, s);
            return;
        }
    }
}

static void drain_dgrams(Reactor *, RStream *s) {
    uint8_t sink[512];
    for (;;) {
        ssize_t r = ::recv(s->fd, sink, sizeof(sink), 0);
        if (r < 0) {
            if (errno == EINTR)
                continue;
            return;             // EAGAIN or hard error: edge consumed
        }
        if (r == 0)
            return;
    }
}

// Resume a stream parked on an oversize frame, after Python took it:
// replay the carried bytes (may park again) and re-arm the epoll
// registration.  Reactor thread, under R->m.
static void resume_stream(Reactor *R, RStream *s) {
    if (s->dead || !s->parked)
        return;
    s->parked = false;
    if (!s->carry.empty()) {
        std::vector<uint8_t> buf;
        buf.swap(s->carry);
        stream_feed(R, s, buf.data(), buf.size());
    }
    if (s->dead || s->parked)
        return;                 // desynced or parked again
    struct epoll_event ev {};
    ev.events = EPOLLIN | (s->want_write ? (uint32_t)EPOLLOUT : 0u);
    ev.data.fd = s->fd;
    ::epoll_ctl(R->epfd, EPOLL_CTL_ADD, s->fd, &ev);
}

// Process one epoll_wait batch.  Caller holds R->m (ALL event
// processing — reactor thread and consumer-thread pump alike — is
// serialised by it, so R->scratch and the stream states stay
// single-writer).  `consume_wake` is false on the pump path: the wake
// eventfd belongs to the reactor thread (stop/resume pokes) and the
// pump must not eat it out from under a blocked epoll_wait.
static void process_events(Reactor *R, struct epoll_event *evs, int n,
                           bool consume_wake) {
    if (!R->resume_fds.empty()) {
        std::vector<int> todo;
        todo.swap(R->resume_fds);
        for (int fd : todo) {
            auto it = R->streams.find(fd);
            if (it != R->streams.end())
                resume_stream(R, it->second);
        }
    }
    for (int i = 0; i < n; ++i) {
        int fd = evs[i].data.fd;
        if (fd == R->wakefd) {
            if (consume_wake) {
                uint64_t junk;
                ssize_t r = ::read(R->wakefd, &junk, 8);
                (void)r;
            }
            continue;
        }
        auto it = R->streams.find(fd);
        if (it == R->streams.end())
            continue;
        RStream *s = it->second;
        if (s->dead)
            continue;
        uint32_t ev = evs[i].events;
        if (s->mode == 1) {
            // notify (oneshot): Python accepts, then rearms
            emit(R, fd, REC_ACCEPT, nullptr, 0, nullptr, 0);
            continue;
        }
        if (s->mode == 2) {
            drain_dgrams(R, s);
            emit(R, fd, REC_DOORBELL, nullptr, 0, nullptr, 0);
            continue;
        }
        if (ev & (EPOLLIN | EPOLLHUP | EPOLLERR))
            stream_readable(R, s);
        if ((ev & EPOLLOUT) && !s->dead && !s->parked &&
            s->want_write) {
            // one-shot writable edge: interest is cleared here,
            // Python re-arms (want_write) while its queue has bytes.
            // (want_write check: both epoll waiters can see the same
            // level-triggered edge — only the first emits.)
            s->want_write = false;
            struct epoll_event mod {};
            mod.events = EPOLLIN;
            mod.data.fd = fd;
            ::epoll_ctl(R->epfd, EPOLL_CTL_MOD, fd, &mod);
            emit(R, fd, REC_WRITABLE, nullptr, 0, nullptr, 0);
        }
    }
}

// Consumer-thread inline pump (called from otpu_reactor_drain when the
// record queue is empty, GIL already released by ctypes): poll the
// SAME epoll set with a zero timeout and process whatever is ready on
// the calling thread.  On a single-core / oversubscribed host this is
// the difference between picking a frame up on the very next progress
// tick and waiting a scheduler quantum for the reactor thread to run —
// the reactor thread still provides the overlap win when cores are
// free.  try_lock: if the reactor thread is mid-batch, records are
// already on their way and the pump has nothing useful to add.
static int pump(Reactor *R) {
    std::unique_lock<std::mutex> lk(R->m, std::try_to_lock);
    if (!lk.owns_lock())
        return 0;
    struct epoll_event evs[64];
    int n = ::epoll_wait(R->epfd, evs, 64, 0);
    if (n <= 0 && R->resume_fds.empty())
        return 0;
    process_events(R, evs, n < 0 ? 0 : n, /*consume_wake=*/false);
    R->n_pumps++;
    return n;
}

static void reactor_loop(Reactor *R) {
    // Idle scheduling policy: the background thread is an OVERLAP
    // optimisation — when cores are free it drains/parses while the
    // consumer computes, but on a saturated (single-core) host it must
    // never steal the quantum from a rank that would have pumped the
    // same event inline on its next progress tick.  Unprivileged
    // one-way switch; failure is fine (normal priority).
    struct sched_param sp {};
    ::pthread_setschedparam(::pthread_self(), SCHED_IDLE, &sp);
    struct epoll_event evs[64];
    while (!R->stop.load(std::memory_order_acquire)) {
        int n = ::epoll_wait(R->epfd, evs, 64, -1);
        if (n < 0) {
            if (errno == EINTR)
                continue;
            return;
        }
        std::lock_guard<std::mutex> lk(R->m);
        process_events(R, evs, n, /*consume_wake=*/true);
    }
}

}  // namespace

extern "C" {

int64_t otpu_reactor_create(int64_t ring_cap, int64_t oversize_limit) {
    if (ring_cap < (1 << 16))
        ring_cap = 1 << 16;
    Reactor *R = new Reactor();
    R->ring_cap = (uint64_t)ring_cap;
    R->oversize_limit = oversize_limit > 4096
        ? (uint64_t)oversize_limit : 4096;
    if (R->oversize_limit > R->ring_cap / 2)
        R->oversize_limit = R->ring_cap / 2;
    R->ring.assign(16 + (size_t)ring_cap, 0);
    R->epfd = ::epoll_create1(EPOLL_CLOEXEC);
    R->wakefd = ::eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    R->notifyfd = ::eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    R->waitfd = ::epoll_create1(EPOLL_CLOEXEC);
    if (R->epfd < 0 || R->wakefd < 0 || R->notifyfd < 0 ||
        R->waitfd < 0) {
        if (R->epfd >= 0) ::close(R->epfd);
        if (R->wakefd >= 0) ::close(R->wakefd);
        if (R->notifyfd >= 0) ::close(R->notifyfd);
        if (R->waitfd >= 0) ::close(R->waitfd);
        delete R;
        return 0;
    }
    struct epoll_event ev {};
    ev.events = EPOLLIN;
    ev.data.fd = R->wakefd;
    ::epoll_ctl(R->epfd, EPOLL_CTL_ADD, R->wakefd, &ev);
    // the consumer waiter fd: readable when the inner epoll set has
    // ready events (a nested epoll fd is itself pollable) OR when
    // completed records are queued (notifyfd)
    ev.events = EPOLLIN;
    ev.data.fd = R->epfd;
    ::epoll_ctl(R->waitfd, EPOLL_CTL_ADD, R->epfd, &ev);
    ev.events = EPOLLIN;
    ev.data.fd = R->notifyfd;
    ::epoll_ctl(R->waitfd, EPOLL_CTL_ADD, R->notifyfd, &ev);
    R->thr = std::thread([R] { reactor_loop(R); });
    return (int64_t)(intptr_t)R;
}

void otpu_reactor_destroy(int64_t h) {
    Reactor *R = (Reactor *)(intptr_t)h;
    R->stop.store(true, std::memory_order_release);
    uint64_t one = 1;
    ssize_t r = ::write(R->wakefd, &one, 8);
    (void)r;
    R->thr.join();
    for (auto &kv : R->streams)
        delete kv.second;
    ::close(R->epfd);
    ::close(R->wakefd);
    ::close(R->notifyfd);
    ::close(R->waitfd);
    delete R;
}

int otpu_reactor_notify_fd(int64_t h) {
    return ((Reactor *)(intptr_t)h)->notifyfd;
}

int otpu_reactor_wait_fd(int64_t h) {
    return ((Reactor *)(intptr_t)h)->waitfd;
}

int otpu_reactor_add(int64_t h, int fd, int mode) {
    Reactor *R = (Reactor *)(intptr_t)h;
    std::lock_guard<std::mutex> lk(R->m);
    if (R->streams.count(fd))
        return -1;
    RStream *s = new RStream();
    s->fd = fd;
    s->mode = mode;
    struct epoll_event ev {};
    ev.events = EPOLLIN | (mode == 1 ? (uint32_t)EPOLLONESHOT : 0u);
    ev.data.fd = fd;
    if (::epoll_ctl(R->epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
        delete s;
        return -1;
    }
    R->streams[fd] = s;
    return 0;
}

int otpu_reactor_del(int64_t h, int fd) {
    Reactor *R = (Reactor *)(intptr_t)h;
    std::lock_guard<std::mutex> lk(R->m);
    auto it = R->streams.find(fd);
    if (it == R->streams.end())
        return -1;
    RStream *s = it->second;
    if (!s->dead && !s->parked)
        epoll_del_quiet(R, s);
    R->streams.erase(it);
    delete s;
    return 0;
}

int otpu_reactor_rearm(int64_t h, int fd) {
    Reactor *R = (Reactor *)(intptr_t)h;
    std::lock_guard<std::mutex> lk(R->m);
    auto it = R->streams.find(fd);
    if (it == R->streams.end() || it->second->mode != 1)
        return -1;
    struct epoll_event ev {};
    ev.events = EPOLLIN | EPOLLONESHOT;
    ev.data.fd = fd;
    return ::epoll_ctl(R->epfd, EPOLL_CTL_MOD, fd, &ev);
}

int otpu_reactor_want_write(int64_t h, int fd, int on) {
    Reactor *R = (Reactor *)(intptr_t)h;
    std::lock_guard<std::mutex> lk(R->m);
    auto it = R->streams.find(fd);
    if (it == R->streams.end())
        return -1;
    RStream *s = it->second;
    s->want_write = on != 0;
    if (s->dead || s->parked)
        return 0;               // resume_stream re-applies the interest
    struct epoll_event ev {};
    ev.events = EPOLLIN | (on ? (uint32_t)EPOLLOUT : 0u);
    ev.data.fd = fd;
    return ::epoll_ctl(R->epfd, EPOLL_CTL_MOD, fd, &ev);
}

// Copy completed records into `out`; returns bytes copied (0: empty).
// Returns the NEGATED size of the next record when it does not fit an
// empty `out` — the caller grows its buffer and retries.  Single
// consumer (the Python side serialises itself).
int64_t otpu_reactor_drain(int64_t h, uint8_t *out, uint64_t cap) {
    Reactor *R = (Reactor *)(intptr_t)h;
    uint64_t junk;
    ssize_t rd = ::read(R->notifyfd, &junk, 8);
    (void)rd;
    uint8_t *buf = rq_base(R);
    const uint8_t *data = buf + 16;
    // empty queue: poll the epoll set inline before giving up —
    // completed frames land this very tick instead of after a
    // reactor-thread scheduling gap (see pump()).  Lock-free check:
    // two acquire loads + an atomic flag, nothing heavier on the
    // every-tick path.
    if (load_acq(buf) == load_acq(buf + 8) &&
        !R->has_overflow.load(std::memory_order_acquire))
        pump(R);
    uint64_t copied = 0;
    for (;;) {
        uint64_t head = load_acq(buf);
        uint64_t tail = load_acq(buf + 8);
        if (head == tail)
            break;
        uint8_t hdr[REC_HDR];
        uint64_t p = head % R->ring_cap;
        uint64_t first = REC_HDR < R->ring_cap - p
            ? REC_HDR : R->ring_cap - p;
        std::memcpy(hdr, data + p, (size_t)first);
        if (first < REC_HDR)
            std::memcpy(hdr + first, data, REC_HDR - first);
        uint32_t plen;
        std::memcpy(&plen, hdr, 4);
        uint64_t total = REC_HDR + plen;
        if (total > cap - copied) {
            if (copied == 0)
                return -(int64_t)total;
            break;
        }
        uint64_t q = head % R->ring_cap;
        uint64_t f2 = total < R->ring_cap - q ? total : R->ring_cap - q;
        std::memcpy(out + copied, data + q, (size_t)f2);
        if (f2 < total)
            std::memcpy(out + copied + f2, data, (size_t)(total - f2));
        copied += total;
        store_rel(buf, head + total);
    }
    // overflow (engaged only while the ring was full): strictly older
    // than nothing — every overflow record postdates every ring record
    {
        std::lock_guard<std::mutex> lk(R->ov_m);
        while (!R->overflow.empty()) {
            std::vector<uint8_t> &rec = R->overflow.front();
            if (rec.size() > cap - copied) {
                if (copied == 0)
                    return -(int64_t)rec.size();
                break;
            }
            std::memcpy(out + copied, rec.data(), rec.size());
            copied += rec.size();
            R->overflow.pop_front();
        }
        if (R->overflow.empty())
            R->has_overflow.store(false, std::memory_order_release);
        uint64_t head = load_acq(buf);
        uint64_t tail = load_acq(buf + 8);
        if (head != tail || !R->overflow.empty())
            notify_consumer(R);   // leftovers: keep waiters awake
    }
    return (int64_t)copied;
}

// Fetch (and clear) a stream's parked oversize frame; schedules the
// stream's resume on the reactor thread.  Returns the frame length,
// the negated length when `cap` is too small, or -1 when nothing is
// parked for `fd`.
int64_t otpu_reactor_take_oversize(int64_t h, int fd, uint8_t *out,
                                   uint64_t cap) {
    Reactor *R = (Reactor *)(intptr_t)h;
    std::lock_guard<std::mutex> lk(R->m);
    auto it = R->streams.find(fd);
    if (it == R->streams.end())
        return -1;
    RStream *s = it->second;
    if (!s->parked || s->oversize.empty())
        return -1;
    if (s->oversize.size() > cap)
        return -(int64_t)s->oversize.size();
    std::memcpy(out, s->oversize.data(), s->oversize.size());
    int64_t n = (int64_t)s->oversize.size();
    s->oversize.clear();
    s->oversize.shrink_to_fit();
    R->resume_fds.push_back(fd);
    uint64_t one = 1;
    ssize_t r = ::write(R->wakefd, &one, 8);
    (void)r;
    return n;
}

// stats: [n_fds, n_records, n_frames_fast, n_frames_raw, n_overflow,
//         n_wakeups, n_pumps] — racy reads, telemetry only.
int otpu_reactor_stats(int64_t h, int64_t *out, int n) {
    Reactor *R = (Reactor *)(intptr_t)h;
    int64_t vals[7];
    {
        std::lock_guard<std::mutex> lk(R->m);
        vals[0] = (int64_t)R->streams.size();
    }
    vals[1] = (int64_t)R->n_records;
    vals[2] = (int64_t)R->n_frames_fast;
    vals[3] = (int64_t)R->n_frames_raw;
    vals[4] = (int64_t)R->n_overflow;
    vals[5] = (int64_t)R->n_wakeups;
    vals[6] = (int64_t)R->n_pumps;
    int k = n < 7 ? n : 7;
    for (int i = 0; i < k; ++i)
        out[i] = vals[i];
    return k;
}

}  // extern "C"

#else  // !__linux__: the reactor needs epoll/eventfd; stub the API so
       // the library still builds and available() stays true for the
       // pack/ring/pool substrate — Python's reactor_supported() gates
       // on otpu_reactor_create returning a handle.

extern "C" {

int64_t otpu_reactor_create(int64_t, int64_t) { return 0; }
void otpu_reactor_destroy(int64_t) {}
int otpu_reactor_notify_fd(int64_t) { return -1; }
int otpu_reactor_wait_fd(int64_t) { return -1; }
int otpu_reactor_add(int64_t, int, int) { return -1; }
int otpu_reactor_del(int64_t, int) { return -1; }
int otpu_reactor_rearm(int64_t, int) { return -1; }
int otpu_reactor_want_write(int64_t, int, int) { return -1; }
int64_t otpu_reactor_drain(int64_t, uint8_t *, uint64_t) { return 0; }
int64_t otpu_reactor_take_oversize(int64_t, int, uint8_t *, uint64_t) {
    return -1;
}
int otpu_reactor_stats(int64_t, int64_t *, int) { return 0; }

}  // extern "C"

#endif  // __linux__
