// otpu_native — C++ twins of the hot host-path loops.
//
// The reference implements these in C for the same reason (the datatype
// pack engine `opal/datatype/opal_datatype_pack.c` and the sm fifo
// `opal/class/opal_fifo.h`): per-element gather/scatter and ring ops are
// tight loops the interpreter cannot keep up with.  The Python layers
// (ompi_tpu/datatype/convertor.py, ompi_tpu/mca/btl/sm.py) call these
// through ctypes when the shared library is available and fall back to
// their numpy implementations otherwise.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread otpu_native.cc
//        -o libotpu_native.so
// (driven lazily by ompi_tpu/native/__init__.py; -pthread is required
// by the worker pool's std::thread).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---- datatype engine: whole-element gather/scatter ---------------------
//
// Stream layout: element e of the datatype contributes its segments in
// type-map order; segment j lives at base_offset + e*extent + seg_off[j]
// in memory and occupies seg_len[j] bytes of the packed stream.

int64_t otpu_pack_elems(const uint8_t *base, uint8_t *out,
                        const int64_t *seg_off, const int64_t *seg_len,
                        int64_t nseg, int64_t extent, int64_t base_offset,
                        int64_t first_elem, int64_t nelem) {
    uint8_t *dst = out;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        const uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(dst, ebase + seg_off[j], (size_t)seg_len[j]);
            dst += seg_len[j];
        }
    }
    return dst - out;
}

int64_t otpu_unpack_elems(uint8_t *base, const uint8_t *in,
                          const int64_t *seg_off, const int64_t *seg_len,
                          int64_t nseg, int64_t extent, int64_t base_offset,
                          int64_t first_elem, int64_t nelem) {
    const uint8_t *src = in;
    for (int64_t e = first_elem; e < first_elem + nelem; ++e) {
        uint8_t *ebase = base + base_offset + e * extent;
        for (int64_t j = 0; j < nseg; ++j) {
            std::memcpy(ebase + seg_off[j], src, (size_t)seg_len[j]);
            src += seg_len[j];
        }
    }
    return src - in;
}

// ---- btl/sm: SPSC byte ring -------------------------------------------
//
// Layout (matches ompi_tpu/mca/btl/sm.py `_Ring`):
//   [ head u64 | tail u64 | data[cap] ]
// frames are <u32 length><payload>, wrapping modulo cap.  Single producer
// advances tail, single consumer advances head (acquire/release pairs —
// the property the reference's opal_fifo gets from its atomics).

static inline uint64_t load_acq(const uint8_t *p) {
    return __atomic_load_n((const uint64_t *)p, __ATOMIC_ACQUIRE);
}
static inline void store_rel(uint8_t *p, uint64_t v) {
    __atomic_store_n((uint64_t *)p, v, __ATOMIC_RELEASE);
}

static void ring_write(uint8_t *data, uint64_t cap, uint64_t pos,
                       const uint8_t *src, uint64_t n) {
    uint64_t p = pos % cap;
    uint64_t first = n < cap - p ? n : cap - p;
    std::memcpy(data + p, src, (size_t)first);
    if (first < n)
        std::memcpy(data, src + first, (size_t)(n - first));
}

int otpu_ring_push(uint8_t *buf, uint64_t cap, const uint8_t *payload,
                   uint64_t n) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t need = 4 + n;
    if (need > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, payload, n);
    store_rel(buf + 8, tail + need);
    return 1;
}

// Gather-push: one frame from two source buffers (header + payload),
// written back-to-back so the caller never has to concatenate them in
// Python (the concatenation would copy the payload an extra time).
int otpu_ring_push2(uint8_t *buf, uint64_t cap,
                    const uint8_t *a, uint64_t alen,
                    const uint8_t *b, uint64_t blen) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    uint64_t n = alen + blen;
    if (4 + n > cap - (tail - head))
        return 0;
    uint8_t *data = buf + 16;
    uint32_t len32 = (uint32_t)n;
    ring_write(data, cap, tail, (const uint8_t *)&len32, 4);
    ring_write(data, cap, tail + 4, a, alen);
    ring_write(data, cap, tail + 4 + alen, b, blen);
    store_rel(buf + 8, tail + 4 + n);
    return 1;
}

// Length of the next complete frame, or -1 when none is ready — lets the
// consumer allocate an exact-size owned buffer before popping (so frame
// payloads can be delivered as zero-copy views of that buffer).
int64_t otpu_ring_peek_len(const uint8_t *buf, uint64_t cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    uint64_t p = head % cap;
    uint8_t tmp[4];
    uint64_t first = 4 < cap - p ? 4 : cap - p;
    std::memcpy(tmp, data + p, (size_t)first);
    if (first < 4)
        std::memcpy(tmp + first, data, (size_t)(4 - first));
    std::memcpy(&len32, tmp, 4);
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    return (int64_t)n;
}

int64_t otpu_ring_pop(uint8_t *buf, uint64_t cap, uint8_t *out,
                      uint64_t out_cap) {
    uint64_t head = load_acq(buf);
    uint64_t tail = load_acq(buf + 8);
    if (tail - head < 4)
        return -1;
    const uint8_t *data = buf + 16;
    uint32_t len32;
    {   // read the length header (may wrap)
        uint64_t p = head % cap;
        uint8_t tmp[4];
        uint64_t first = 4 < cap - p ? 4 : cap - p;
        std::memcpy(tmp, data + p, (size_t)first);
        if (first < 4)
            std::memcpy(tmp + first, data, (size_t)(4 - first));
        std::memcpy(&len32, tmp, 4);
    }
    uint64_t n = len32;
    if (tail - head < 4 + n)
        return -1;          // producer mid-frame
    if (n > out_cap)
        return -2;          // caller buffer too small
    {   // read the payload (may wrap)
        uint64_t p = (head + 4) % cap;
        uint64_t first = n < cap - p ? n : cap - p;
        std::memcpy(out, data + p, (size_t)first);
        if (first < n)
            std::memcpy(out + first, data, (size_t)(n - first));
    }
    store_rel(buf, head + 4 + n);
    return (int64_t)n;
}

// ---- osc/rdma: cross-process atomics on mapped windows ------------------
//
// The reference's osc/rdma implements locks and accumulates via remote
// atomic CAS over the BTL (`osc_rdma_accumulate.c:26-71`).  On a same-host
// mapped window the "remote" atomic is a plain shared-memory atomic; the
// lock word lives in the window segment header.  Layout of the lock word:
// bit 63 = exclusive held, bits 0..62 = shared-reader count.

static const uint64_t EXCL_BIT = 1ull << 63;

int otpu_lock_excl_try(uint8_t *word) {
    uint64_t expected = 0;
    return __atomic_compare_exchange_n(
        (uint64_t *)word, &expected, EXCL_BIT, false,
        __ATOMIC_ACQUIRE, __ATOMIC_RELAXED) ? 1 : 0;
}

void otpu_lock_excl_release(uint8_t *word) {
    __atomic_store_n((uint64_t *)word, 0, __ATOMIC_RELEASE);
}

int otpu_lock_shared_try(uint8_t *word) {
    uint64_t cur = __atomic_load_n((uint64_t *)word, __ATOMIC_RELAXED);
    while (!(cur & EXCL_BIT)) {
        if (__atomic_compare_exchange_n(
                (uint64_t *)word, &cur, cur + 1, false,
                __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
            return 1;
        // cur reloaded by the failed CAS; loop unless exclusive appeared
    }
    return 0;
}

void otpu_lock_shared_release(uint8_t *word) {
    __atomic_fetch_sub((uint64_t *)word, 1, __ATOMIC_RELEASE);
}

int64_t otpu_atomic_add_i64(uint8_t *ptr, int64_t delta) {
    return __atomic_fetch_add((int64_t *)ptr, delta, __ATOMIC_ACQ_REL);
}

// returns the OLD value; *ok set to 1 when the swap happened
int64_t otpu_atomic_cas_i64(uint8_t *ptr, int64_t expected, int64_t desired,
                            int32_t *ok) {
    int64_t exp = expected;
    int swapped = __atomic_compare_exchange_n(
        (int64_t *)ptr, &exp, desired, false,
        __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE);
    *ok = swapped ? 1 : 0;
    return exp;  // old value on failure, `expected` (== old) on success
}

uint64_t otpu_atomic_load_u64(const uint8_t *ptr) {
    return __atomic_load_n((const uint64_t *)ptr, __ATOMIC_ACQUIRE);
}

void otpu_atomic_store_u64(uint8_t *ptr, uint64_t v) {
    __atomic_store_n((uint64_t *)ptr, v, __ATOMIC_RELEASE);
}

// ---- threads: native worker pool ---------------------------------------
//
// The reference's threading substrate (`opal/mca/threads/threads.h`) gives
// the host data path real OS threads — progress, packing, and reduction
// math run concurrently with the application.  A Python framework cannot
// get that from `threading` (the GIL serialises it), so the pool lives
// here: jobs are split into per-worker chunks of pure C++ (memcpy, the
// datatype element loops above, elementwise reduction math), ctypes drops
// the GIL for the submitting call, and the workers never touch Python.
// One job -> one ticket; a ticket completes when every chunk ran.

}  // extern "C" (the pool internals below are C++; the API re-opens it)

namespace {

struct OtpuTicket {
    std::atomic<int64_t> remaining;
    std::mutex m;
    std::condition_variable cv;
    explicit OtpuTicket(int64_t n) : remaining(n) {}
};

struct OtpuChunk {
    int32_t kind;            // 0 memcpy, 1 pack, 2 unpack, 3 reduce
    OtpuTicket *ticket;
    uint8_t *dst;
    const uint8_t *src;
    int64_t n;
    int32_t op, dtype;       // reduce: op 0 sum 1 prod 2 max 3 min;
                             // dtype 0 f32 1 f64 2 i32 3 i64
    const int64_t *seg_off, *seg_len;
    int64_t nseg, extent, base_offset, first_elem, nelem;
};

template <typename T>
static void reduce_span(T *acc, const T *src, int64_t count, int32_t op) {
    // max/min match np.maximum/np.minimum exactly, including NaN
    // propagation from EITHER operand (src!=src catches a NaN src; a
    // NaN acc keeps itself because 'acc < NaN' is false) — the
    // sub-threshold numpy path and the python substrate must be
    // bit-interchangeable with this one.  For integers x!=x is
    // constant-false and folds away.
    switch (op) {
    case 0: for (int64_t i = 0; i < count; ++i) acc[i] += src[i]; break;
    case 1: for (int64_t i = 0; i < count; ++i) acc[i] *= src[i]; break;
    case 2: for (int64_t i = 0; i < count; ++i)
                acc[i] = (src[i] != src[i] || acc[i] < src[i])
                             ? src[i] : acc[i];
            break;
    default: for (int64_t i = 0; i < count; ++i)
                acc[i] = (src[i] != src[i] || src[i] < acc[i])
                             ? src[i] : acc[i];
    }
}

static void run_chunk(const OtpuChunk &c) {
    switch (c.kind) {
    case 0:
        std::memcpy(c.dst, c.src, (size_t)c.n);
        break;
    case 1:
        otpu_pack_elems(c.src, c.dst, c.seg_off, c.seg_len, c.nseg,
                        c.extent, c.base_offset, c.first_elem, c.nelem);
        break;
    case 2:
        otpu_unpack_elems(c.dst, c.src, c.seg_off, c.seg_len, c.nseg,
                          c.extent, c.base_offset, c.first_elem, c.nelem);
        break;
    default:
        switch (c.dtype) {
        case 0: reduce_span((float *)c.dst, (const float *)c.src,
                            c.n, c.op); break;
        case 1: reduce_span((double *)c.dst, (const double *)c.src,
                            c.n, c.op); break;
        case 2: reduce_span((int32_t *)c.dst, (const int32_t *)c.src,
                            c.n, c.op); break;
        default: reduce_span((int64_t *)c.dst, (const int64_t *)c.src,
                             c.n, c.op);
        }
    }
}

struct OtpuPool {
    std::vector<std::thread> workers;
    std::deque<OtpuChunk> queue;
    std::mutex m;
    std::condition_variable cv;
    bool stop = false;

    explicit OtpuPool(int32_t n) {
        for (int32_t i = 0; i < n; ++i)
            workers.emplace_back([this] { loop(); });
    }

    void loop() {
        for (;;) {
            OtpuChunk c;
            {
                std::unique_lock<std::mutex> lk(m);
                cv.wait(lk, [this] { return stop || !queue.empty(); });
                if (queue.empty())
                    return;            // stop && drained
                c = queue.front();
                queue.pop_front();
            }
            run_chunk(c);
            {
                // decrement under the ticket mutex: a waiter holding it
                // cannot observe remaining==0 and free the ticket while
                // this worker is still about to touch it
                std::lock_guard<std::mutex> lk(c.ticket->m);
                if (c.ticket->remaining.fetch_sub(
                        1, std::memory_order_acq_rel) == 1)
                    c.ticket->cv.notify_all();
            }
        }
    }

    OtpuTicket *submit(std::vector<OtpuChunk> &chunks) {
        OtpuTicket *t = new OtpuTicket((int64_t)chunks.size());
        {
            std::lock_guard<std::mutex> lk(m);
            for (auto &c : chunks) {
                c.ticket = t;
                queue.push_back(c);
            }
        }
        cv.notify_all();
        return t;
    }
};

}  // namespace

extern "C" {

int64_t otpu_pool_create(int32_t nthreads) {
    if (nthreads < 1)
        nthreads = 1;
    return (int64_t)(intptr_t) new OtpuPool(nthreads);
}

void otpu_pool_destroy(int64_t pool) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    {
        std::lock_guard<std::mutex> lk(p->m);
        p->stop = true;
    }
    p->cv.notify_all();
    for (auto &w : p->workers)
        w.join();
    delete p;
}

int32_t otpu_pool_size(int64_t pool) {
    return (int32_t)((OtpuPool *)(intptr_t)pool)->workers.size();
}

// Split [0, n) into per-worker spans of at least `grain` units.
static std::vector<std::pair<int64_t, int64_t>> spans(
        int64_t n, int64_t nworkers, int64_t grain) {
    int64_t pieces = n / grain;
    if (pieces > nworkers) pieces = nworkers;
    if (pieces < 1) pieces = 1;
    std::vector<std::pair<int64_t, int64_t>> out;
    int64_t per = n / pieces, rem = n % pieces, at = 0;
    for (int64_t i = 0; i < pieces; ++i) {
        int64_t len = per + (i < rem ? 1 : 0);
        out.emplace_back(at, len);
        at += len;
    }
    return out;
}

int64_t otpu_pool_memcpy(int64_t pool, uint8_t *dst, const uint8_t *src,
                         int64_t n) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(n, (int64_t)p->workers.size(), 1 << 16)) {
        OtpuChunk c{};
        c.kind = 0;
        c.dst = dst + sp.first;
        c.src = src + sp.first;
        c.n = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

int64_t otpu_pool_reduce(int64_t pool, int32_t op, int32_t dtype,
                         uint8_t *acc, const uint8_t *src, int64_t count) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    int64_t esz = (dtype == 0 || dtype == 2) ? 4 : 8;
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(count, (int64_t)p->workers.size(), 1 << 14)) {
        OtpuChunk c{};
        c.kind = 3;
        c.op = op;
        c.dtype = dtype;
        c.dst = acc + sp.first * esz;
        c.src = src + sp.first * esz;
        c.n = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

static int64_t pool_packish(int64_t pool, int32_t kind, uint8_t *mem,
                            uint8_t *stream, const int64_t *seg_off,
                            const int64_t *seg_len, int64_t nseg,
                            int64_t extent, int64_t base_offset,
                            int64_t first_elem, int64_t nelem) {
    OtpuPool *p = (OtpuPool *)(intptr_t)pool;
    int64_t elem_packed = 0;
    for (int64_t j = 0; j < nseg; ++j)
        elem_packed += seg_len[j];
    std::vector<OtpuChunk> cs;
    for (auto &sp : spans(nelem, (int64_t)p->workers.size(), 64)) {
        OtpuChunk c{};
        c.kind = kind;
        uint8_t *schunk = stream + sp.first * elem_packed;
        if (kind == 1) {               // pack: mem -> stream
            c.src = mem;
            c.dst = schunk;
        } else {                       // unpack: stream -> mem
            c.dst = mem;
            c.src = schunk;
        }
        c.seg_off = seg_off;
        c.seg_len = seg_len;
        c.nseg = nseg;
        c.extent = extent;
        c.base_offset = base_offset;
        c.first_elem = first_elem + sp.first;
        c.nelem = sp.second;
        cs.push_back(c);
    }
    return (int64_t)(intptr_t)p->submit(cs);
}

int64_t otpu_pool_pack(int64_t pool, uint8_t *mem, uint8_t *out,
                       const int64_t *seg_off, const int64_t *seg_len,
                       int64_t nseg, int64_t extent, int64_t base_offset,
                       int64_t first_elem, int64_t nelem) {
    return pool_packish(pool, 1, mem, out, seg_off, seg_len, nseg, extent,
                        base_offset, first_elem, nelem);
}

int64_t otpu_pool_unpack(int64_t pool, uint8_t *mem, uint8_t *in,
                         const int64_t *seg_off, const int64_t *seg_len,
                         int64_t nseg, int64_t extent, int64_t base_offset,
                         int64_t first_elem, int64_t nelem) {
    return pool_packish(pool, 2, mem, in, seg_off, seg_len, nseg, extent,
                        base_offset, first_elem, nelem);
}

int32_t otpu_pool_test(int64_t ticket) {
    OtpuTicket *t = (OtpuTicket *)(intptr_t)ticket;
    return t->remaining.load(std::memory_order_acquire) == 0 ? 1 : 0;
}

// Blocks until done, then frees the ticket (call exactly once).
void otpu_pool_wait(int64_t ticket) {
    OtpuTicket *t = (OtpuTicket *)(intptr_t)ticket;
    {
        std::unique_lock<std::mutex> lk(t->m);
        t->cv.wait(lk, [t] {
            return t->remaining.load(std::memory_order_acquire) == 0;
        });
    }
    delete t;
}

}  // extern "C"
