"""dpm — dynamic process management (spawn / connect / accept / merge).

Re-design of ``/root/reference/ompi/dpm/dpm.c:1-2152``: the reference spawns
via ``PMIx_Spawn`` (the launcher execs children, children PMIx_Init back,
both sides build an intercommunicator over agreed CIDs).  Here the
coordination service plays PMIx: ``spawn`` allocates fresh *global* world
ranks and the launcher (tpurun) execs the children as their own job with
their own COMM_WORLD; parent and children meet through the coord KV and an
intercommunicator is built from the published groups.

Cross-job CIDs come from the coord's atomic counter in a reserved high
range (``comm_cid.c``'s agreement cannot run before the bridge exists; the
reference solves this with its next_cid exchange over the bridge — the
counter is the same decision made central).

This also completes the ULFM recovery loop: shrink (degrade) → spawn
(replace) → merge (re-form a full-size world) — the forward-recovery story
``README.FT.ULFM.md`` leaves to the application.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ompi_tpu.api.comm import Comm
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.group import Group
from ompi_tpu.base.var import VarType, registry

# cross-job CIDs live far above any locally-agreed CID
_DPM_CID_BASE = 1 << 20

_spawn_timeout_var = registry.register(
    "dpm", None, "spawn_timeout", vtype=VarType.FLOAT, default=60.0,
    help="Seconds MPI_Comm_spawn waits for every child rank to join the "
         "runtime (the __spawn_join__ handshake) before releasing the "
         "allocated CID and raising ERR_SPAWN — a child that dies during "
         "boot must produce a loud error, not a half-built "
         "intercommunicator")


def _client(comm) -> object:
    client = getattr(comm.rte, "client", None)
    if client is None:
        raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                       "dynamic process management needs the coordination "
                       "service (run under tpurun)")
    return client


def _new_bridge_cid(client) -> int:
    return _DPM_CID_BASE + client.fetch_add(-1, "__dpm_cid__", 1)


def _make_intercomm(comm, cid: int, remote_ranks: Sequence[int],
                    name: str) -> Comm:
    from ompi_tpu.runtime import init as rt

    # bridge comms pin epoch 0: the two sides' local epochs can differ
    # (e.g. spawn from a shrunk comm), and the revocation key
    # (scope, cid, epoch) must match across jobs — bridge CIDs are
    # globally unique so the epoch carries no extra information
    inter = Comm(comm.group, cid, comm.rte, name=name, epoch=0,
                 parent=comm, remote_group=Group(list(remote_ranks)))
    inter.local_comm = comm       # local-side collective channel (merge)
    rt.reserve_cid(cid)
    comm._finish_create(inter)
    return inter


def _await_spawn_join(client, ranks: Sequence[int], job: str,
                      timeout: float) -> None:
    """Block until every spawned rank published its ``__spawn_join__``
    marker (done by ``ProcRte.__init__`` as soon as the child's coord
    connection is up).  A child that died during boot (the launcher's
    proc_failed report lands in the local ft state) or never joined
    within ``timeout`` raises a loud ERR_SPAWN — the half-built-
    intercommunicator hang this replaces."""
    import time as _time

    from ompi_tpu.ft import state as ft_state

    deadline = _time.monotonic() + timeout
    for r in ranks:
        while True:
            if ft_state.is_failed(r):
                raise MpiError(
                    ErrorClass.ERR_SPAWN,
                    f"spawned rank {r} (job {job}) died during join — "
                    "the child process exited before reaching the "
                    "runtime")
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise MpiError(
                    ErrorClass.ERR_SPAWN,
                    f"spawned rank {r} (job {job}) did not join within "
                    f"{timeout:g}s (otpu_dpm_spawn_timeout); aborting "
                    "the spawn instead of leaving a half-built "
                    "intercommunicator")
            # short sub-waits keep the died-during-join check live while
            # a slow child is still booting
            got = client.get(r, f"__spawn_join__:{job}", wait=True,
                             timeout=min(1.0, remaining))
            if got is not None:
                break


def _spawn_at_root(comm, cmd, total: int):
    """Root-side spawn: allocate the bridge CID, launch, and run the
    join handshake.  On ANY failure the reserved-but-never-used CID is
    released again (the children can only adopt it after completing the
    join, so no peer can hold a communicator on it)."""
    from ompi_tpu.runtime import init as rt

    client = _client(comm)
    cid = _new_bridge_cid(client)
    # hold the cid locally from allocation on: a concurrent local
    # create must not collide with it while the children are joining
    rt.reserve_cid(cid)
    try:
        parent_ranks = ",".join(str(w) for w in comm.group.world_ranks)
        ranks, job = client.spawn(
            cmd, total,
            env={"OTPU_PARENT_RANKS": parent_ranks,
                 "OTPU_PARENT_CID": str(cid)})
        if len(ranks) != total:
            raise MpiError(
                ErrorClass.ERR_SPAWN,
                f"launcher allocated {len(ranks)} of {total} requested "
                "ranks — aborting the spawn instead of building a "
                "short-sized intercommunicator")
        _await_spawn_join(client, ranks, job,
                          float(_spawn_timeout_var.value or 60.0))
        return cid, ranks, job
    except BaseException:
        rt.release_cid(cid)
        raise


def _job_seq(job: str) -> int:
    """Numeric tail of a coord job id ('job3' -> 3; -1 if unparsable)."""
    tail = str(job).removeprefix("job")
    return int(tail) if tail.isdigit() else -1


def _spawn_common(comm, cmd, total: int, root: int, name: str) -> Comm:
    """Shared body of spawn / spawn_multiple: root launches + joins,
    the sentinel bcast tells non-roots success or failure, and the
    intercommunicator carries ``spawn_job`` (the coord job id, whose
    ``mpi://job/<id>`` pset names the children)."""
    comm._check_state()
    info = np.zeros(3 + total, np.int64)
    err = None
    if comm.rank == root:
        try:
            cid, ranks, job = _spawn_at_root(comm, cmd, total)
            info[0] = cid
            info[1] = total
            info[2] = _job_seq(job)
            info[3:3 + total] = ranks
        except Exception as exc:
            # error sentinel: non-roots are already blocked in the bcast
            # and must learn the spawn failed rather than hang
            err = exc
            info[0] = -1
    info = np.asarray(comm.bcast(info, root=root))
    if int(info[0]) < 0:
        if err is not None:
            raise err
        raise MpiError(ErrorClass.ERR_SPAWN, f"{name} failed at root")
    children = [int(r) for r in info[3:3 + int(info[1])]]
    inter = _make_intercomm(comm, int(info[0]), children,
                            name=f"{comm.name}~{name}")
    seq = int(info[2])
    inter.spawn_job = f"job{seq}" if seq >= 0 else None
    return inter


def spawn(comm, command: Sequence[str], maxprocs: int,
          root: int = 0) -> Comm:
    """``MPI_Comm_spawn``: launch ``maxprocs`` new ranks running
    ``command``; returns the parent↔children intercommunicator.

    Collective over ``comm``.  Children find their side via
    ``get_parent()``.  The root waits for every child's join handshake
    before the intercomm exists anywhere; a child dying during boot (or
    a short rank allocation) releases the bridge CID and raises
    ERR_SPAWN on all ranks.
    """
    return _spawn_common(comm, list(command), int(maxprocs), root, "spawn")


def spawn_multiple(comm, commands: Sequence[Sequence[str]],
                   maxprocs: Sequence[int], root: int = 0) -> Comm:
    """``MPI_Comm_spawn_multiple``: one child WORLD running several
    executables — child ranks [0, maxprocs[0]) run commands[0], the next
    maxprocs[1] run commands[1], ... (``ompi/mpi/c/comm_spawn_multiple.c``
    semantics).  Returns the parent↔children intercommunicator."""
    if len(commands) != len(maxprocs):
        raise MpiError(ErrorClass.ERR_ARG,
                       f"{len(commands)} commands vs {len(maxprocs)} counts")
    per_rank: list = []
    for cmd, cnt in zip(commands, maxprocs):
        per_rank.extend([list(cmd)] * int(cnt))
    return _spawn_common(comm, per_rank, len(per_rank), root, "spawnm")


def join(fd) -> Comm:
    """``MPI_Comm_join``: build the 1x1 intercommunicator with whatever
    process sits at the other end of the connected socket ``fd``
    (``ompi/dpm/dpm.c`` ``ompi_dpm_dyn_init`` join path).

    The socket carries only the rendezvous (a port name, like the
    reference exchanges port strings over it); the intercomm itself is
    wired through the coordination service, so both processes must
    belong to the same coordination domain (same ``OTPU_COORD``).
    """
    import socket as _socket

    import ompi_tpu

    self_comm = ompi_tpu.COMM_SELF
    sock = (fd if isinstance(fd, _socket.socket)
            else _socket.socket(fileno=fd))
    try:
        # deterministic role election: both send their world rank
        me = self_comm.rte.my_world_rank
        sock.sendall(int(me).to_bytes(8, "big"))
        other = int.from_bytes(_recv_exact(sock, 8), "big")
        if me == other:
            raise MpiError(ErrorClass.ERR_INTERN,
                           "join requires two distinct processes")
        if me < other:
            port = open_port(self_comm)
            blob = port.encode()
            sock.sendall(len(blob).to_bytes(4, "big") + blob)
            return accept(self_comm, port)
        n = int.from_bytes(_recv_exact(sock, 4), "big")
        port = _recv_exact(sock, n).decode()
        return connect(self_comm, port)
    finally:
        if not isinstance(fd, _socket.socket):
            sock.detach()   # the caller still owns the raw fd


def _recv_exact(sock, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise MpiError(ErrorClass.ERR_INTERN,
                           "join peer closed the socket")
        out += chunk
    return out


_parent_intercomm: Optional[Comm] = None


def get_parent() -> Optional[Comm]:
    """``MPI_Comm_get_parent``: the spawned side of the bridge (None in a
    job that was not spawned)."""
    global _parent_intercomm
    if _parent_intercomm is not None:
        return _parent_intercomm
    import ompi_tpu

    world = ompi_tpu.init()
    rte = world.rte
    parent_ranks = getattr(rte, "parent_ranks", None)
    if not parent_ranks:
        return None
    cid = int(getattr(rte, "parent_cid", -1))
    if cid < 0:
        return None
    _parent_intercomm = _make_intercomm(
        world, cid, parent_ranks, name="parent~spawn")
    return _parent_intercomm


# -- connect / accept (MPI_Open_port / MPI_Comm_accept / MPI_Comm_connect)

def open_port(comm=None) -> str:
    """Generate a unique port name for accept/connect."""
    import ompi_tpu

    comm = comm or ompi_tpu.COMM_WORLD
    client = _client(comm)
    seq = client.fetch_add(-1, "__dpm_port_seq__", 1)
    return f"otpu-port-{seq}"


def publish_name(service: str, port: str, comm=None) -> None:
    """``MPI_Publish_name``: bind a service name to a port so an unrelated
    job can find it (``ompi/mpi/c/publish_name.c`` — PMIx publish)."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    existing = client.put_new(-1, f"__dpm_svc_{service}__", port)
    if existing is not None and existing != port:
        raise MpiError(ErrorClass.ERR_NAME,
                       f"service {service!r} already published")


def lookup_name(service: str, comm=None, wait: bool = False) -> str:
    """``MPI_Lookup_name``: resolve a published service name to a port."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    port = client.get(-1, f"__dpm_svc_{service}__", wait=wait)
    if port is None:
        raise MpiError(ErrorClass.ERR_NAME,
                       f"service {service!r} not published")
    return port


def unpublish_name(service: str, comm=None) -> None:
    """``MPI_Unpublish_name``."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    client.delete(-1, f"__dpm_svc_{service}__")


def accept(comm, port: str, root: int = 0) -> Comm:
    """Collective: publish our group under ``port`` and wait for a
    connector; returns the intercommunicator."""
    comm._check_state()
    info = np.zeros(1, np.int64)
    if comm.rank == root:
        client = _client(comm)
        cid = _new_bridge_cid(client)
        client.put(-1, f"__dpm_accept__:{port}",
                   {"cid": cid, "ranks": list(comm.group.world_ranks)})
        other = None
        while other is None:   # block past the KV's 60 s get timeout
            other = client.get(-1, f"__dpm_connect__:{port}", wait=True)
        # consume the pairing: a later accept on this port must wait for
        # a NEW connector, not pair with this stale one
        client.delete(-1, f"__dpm_accept__:{port}")
        client.delete(-1, f"__dpm_connect__:{port}")
        info[0] = cid
        remote = other["ranks"]
    else:
        remote = None
    info = np.asarray(comm.bcast(info, root=root))
    remote = _bcast_obj(comm, remote, root)
    return _make_intercomm(comm, int(info[0]), remote,
                           name=f"{comm.name}~accept")


def connect(comm, port: str, root: int = 0) -> Comm:
    """Collective: join the acceptor publishing ``port``."""
    comm._check_state()
    info = np.zeros(1, np.int64)
    if comm.rank == root:
        client = _client(comm)
        token = client.fetch_add(-1, "__dpm_conn_seq__", 1)
        while True:
            other = None
            while other is None:   # block past the KV's 60 s get timeout
                other = client.get(-1, f"__dpm_accept__:{port}", wait=True)
            # first connector wins the pairing (put_new is atomic); a
            # loser waits for the acceptor to consume the pair and
            # retries against the NEXT accept on this port
            mine = {"ranks": list(comm.group.world_ranks), "token": token}
            got = client.put_new(-1, f"__dpm_connect__:{port}", mine)
            if got.get("token") == token:
                break
            import time as _time

            while client.get(-1, f"__dpm_connect__:{port}",
                             wait=False) is not None:
                _time.sleep(0.01)
        info[0] = other["cid"]
        remote = other["ranks"]
    else:
        remote = None
    info = np.asarray(comm.bcast(info, root=root))
    remote = _bcast_obj(comm, remote, root)
    return _make_intercomm(comm, int(info[0]), remote,
                           name=f"{comm.name}~connect")


def _bcast_obj(comm, obj, root: int):
    """Broadcast a small picklable object over the comm."""
    import pickle

    if comm.size == 1:
        return obj
    if comm.rank == root:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        comm.bcast(np.array([payload.size], np.int64), root=root)
        comm.bcast(payload, root=root)
        return obj
    n = int(np.asarray(comm.bcast(np.zeros(1, np.int64), root=root))[0])
    payload = np.asarray(comm.bcast(np.zeros(n, np.uint8), root=root))
    return pickle.loads(payload.tobytes())


def merge(intercomm, high: bool = False) -> Comm:
    """``MPI_Intercomm_merge``: one intracommunicator over both groups.

    The ``high=False`` group's ranks come first.  Collective over the
    intercommunicator; the low side's root allocates the merged CID and
    bridges it to the high side's root over intercomm p2p.
    """
    if not intercomm.is_inter:
        raise MpiError(ErrorClass.ERR_COMM, "merge needs an intercomm")
    local = getattr(intercomm, "local_comm", None)
    if local is None:
        raise MpiError(ErrorClass.ERR_COMM,
                       "intercomm carries no local collective channel")
    from ompi_tpu.runtime import init as rt

    client = _client(intercomm)
    # deterministic CID allocator: the group containing the smaller world
    # rank allocates and bridges it over (`high` only orders ranks, below)
    my_min = min(intercomm.group.world_ranks)
    other_min = min(intercomm.remote_group.world_ranks)
    i_am_low = my_min < other_min
    buf = np.zeros(1, np.int64)
    if i_am_low:
        if local.rank == 0:
            buf[0] = _new_bridge_cid(client)
            intercomm.send(buf, 0, tag=-7)
    else:
        if local.rank == 0:
            intercomm.recv(buf, 0, tag=-7)
    cid = int(np.asarray(local.bcast(buf, root=0))[0])
    # merged rank order: the group that passed high=False first; both
    # sides must agree, so order by (my `high` flag exchanged via minimum
    # world rank convention): low-world-rank group first unless IT set
    # high=True — exchange the flags over the bridge
    flag = np.array([1 if high else 0], np.int64)
    oflag = np.zeros(1, np.int64)
    if local.rank == 0:
        if i_am_low:
            intercomm.send(flag, 0, tag=-8)
            intercomm.recv(oflag, 0, tag=-8)
        else:
            intercomm.recv(oflag, 0, tag=-8)
            intercomm.send(flag, 0, tag=-8)
    oflag = np.asarray(local.bcast(oflag, root=0))
    mine = list(intercomm.group.world_ranks)
    theirs = list(intercomm.remote_group.world_ranks)
    if int(flag[0]) == int(oflag[0]):
        # same flag: low-world-rank group first (MPI leaves it undefined;
        # this is the reference's deterministic tie-break)
        first = mine if my_min < other_min else theirs
    else:
        first = theirs if high else mine
    second = theirs if first is mine else mine
    merged = Comm(Group(first + second), cid, intercomm.rte,
                  name=f"{intercomm.name}~merge", epoch=0,
                  parent=local)
    rt.reserve_cid(cid)
    local._finish_create(merged)
    return merged
