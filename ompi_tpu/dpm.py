"""dpm — dynamic process management (spawn / connect / accept / merge).

Re-design of ``/root/reference/ompi/dpm/dpm.c:1-2152``: the reference spawns
via ``PMIx_Spawn`` (the launcher execs children, children PMIx_Init back,
both sides build an intercommunicator over agreed CIDs).  Here the
coordination service plays PMIx: ``spawn`` allocates fresh *global* world
ranks and the launcher (tpurun) execs the children as their own job with
their own COMM_WORLD; parent and children meet through the coord KV and an
intercommunicator is built from the published groups.

Cross-job CIDs come from the coord's atomic counter in a reserved high
range (``comm_cid.c``'s agreement cannot run before the bridge exists; the
reference solves this with its next_cid exchange over the bridge — the
counter is the same decision made central).

This also completes the ULFM recovery loop: shrink (degrade) → spawn
(replace) → merge (re-form a full-size world) — the forward-recovery story
``README.FT.ULFM.md`` leaves to the application.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ompi_tpu.api.comm import Comm
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.group import Group

# cross-job CIDs live far above any locally-agreed CID
_DPM_CID_BASE = 1 << 20


def _client(comm) -> object:
    client = getattr(comm.rte, "client", None)
    if client is None:
        raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                       "dynamic process management needs the coordination "
                       "service (run under tpurun)")
    return client


def _new_bridge_cid(client) -> int:
    return _DPM_CID_BASE + client.fetch_add(-1, "__dpm_cid__", 1)


def _make_intercomm(comm, cid: int, remote_ranks: Sequence[int],
                    name: str) -> Comm:
    from ompi_tpu.runtime import init as rt

    # bridge comms pin epoch 0: the two sides' local epochs can differ
    # (e.g. spawn from a shrunk comm), and the revocation key
    # (scope, cid, epoch) must match across jobs — bridge CIDs are
    # globally unique so the epoch carries no extra information
    inter = Comm(comm.group, cid, comm.rte, name=name, epoch=0,
                 parent=comm, remote_group=Group(list(remote_ranks)))
    inter.local_comm = comm       # local-side collective channel (merge)
    rt.reserve_cid(cid)
    comm._finish_create(inter)
    return inter


def spawn(comm, command: Sequence[str], maxprocs: int,
          root: int = 0) -> Comm:
    """``MPI_Comm_spawn``: launch ``maxprocs`` new ranks running
    ``command``; returns the parent↔children intercommunicator.

    Collective over ``comm``.  Children find their side via
    ``get_parent()``.
    """
    comm._check_state()
    info = np.zeros(2 + maxprocs, np.int64)
    err = None
    if comm.rank == root:
        try:
            client = _client(comm)
            cid = _new_bridge_cid(client)
            parent_ranks = ",".join(str(w) for w in comm.group.world_ranks)
            ranks, job = client.spawn(
                list(command), maxprocs,
                env={"OTPU_PARENT_RANKS": parent_ranks,
                     "OTPU_PARENT_CID": str(cid)})
            if len(ranks) != maxprocs:
                raise MpiError(ErrorClass.ERR_SPAWN,
                               f"spawn returned {len(ranks)} ranks")
            info[0] = cid
            info[1] = maxprocs
            info[2:2 + maxprocs] = ranks
        except Exception as exc:
            # error sentinel: non-roots are already blocked in the bcast
            # and must learn the spawn failed rather than hang
            err = exc
            info[0] = -1
    info = np.asarray(comm.bcast(info, root=root))
    if int(info[0]) < 0:
        if err is not None:
            raise err
        raise MpiError(ErrorClass.ERR_SPAWN, "spawn failed at root")
    cid = int(info[0])
    children = [int(r) for r in info[2:2 + int(info[1])]]
    return _make_intercomm(comm, cid, children,
                           name=f"{comm.name}~spawn")


def spawn_multiple(comm, commands: Sequence[Sequence[str]],
                   maxprocs: Sequence[int], root: int = 0) -> Comm:
    """``MPI_Comm_spawn_multiple``: one child WORLD running several
    executables — child ranks [0, maxprocs[0]) run commands[0], the next
    maxprocs[1] run commands[1], ... (``ompi/mpi/c/comm_spawn_multiple.c``
    semantics).  Returns the parent↔children intercommunicator."""
    if len(commands) != len(maxprocs):
        raise MpiError(ErrorClass.ERR_ARG,
                       f"{len(commands)} commands vs {len(maxprocs)} counts")
    per_rank: list = []
    for cmd, cnt in zip(commands, maxprocs):
        per_rank.extend([list(cmd)] * int(cnt))
    comm._check_state()
    total = len(per_rank)
    info = np.zeros(2 + total, np.int64)
    err = None
    if comm.rank == root:
        try:
            client = _client(comm)
            cid = _new_bridge_cid(client)
            parent_ranks = ",".join(str(w) for w in comm.group.world_ranks)
            ranks, job = client.spawn(
                per_rank, total,
                env={"OTPU_PARENT_RANKS": parent_ranks,
                     "OTPU_PARENT_CID": str(cid)})
            if len(ranks) != total:
                raise MpiError(ErrorClass.ERR_SPAWN,
                               f"spawn returned {len(ranks)} ranks")
            info[0] = cid
            info[1] = total
            info[2:2 + total] = ranks
        except Exception as exc:
            err = exc
            info[0] = -1
    info = np.asarray(comm.bcast(info, root=root))
    if int(info[0]) < 0:
        if err is not None:
            raise err
        raise MpiError(ErrorClass.ERR_SPAWN, "spawn_multiple failed at root")
    children = [int(r) for r in info[2:2 + int(info[1])]]
    return _make_intercomm(comm, int(info[0]), children,
                           name=f"{comm.name}~spawnm")


def join(fd) -> Comm:
    """``MPI_Comm_join``: build the 1x1 intercommunicator with whatever
    process sits at the other end of the connected socket ``fd``
    (``ompi/dpm/dpm.c`` ``ompi_dpm_dyn_init`` join path).

    The socket carries only the rendezvous (a port name, like the
    reference exchanges port strings over it); the intercomm itself is
    wired through the coordination service, so both processes must
    belong to the same coordination domain (same ``OTPU_COORD``).
    """
    import socket as _socket

    import ompi_tpu

    self_comm = ompi_tpu.COMM_SELF
    sock = (fd if isinstance(fd, _socket.socket)
            else _socket.socket(fileno=fd))
    try:
        # deterministic role election: both send their world rank
        me = self_comm.rte.my_world_rank
        sock.sendall(int(me).to_bytes(8, "big"))
        other = int.from_bytes(_recv_exact(sock, 8), "big")
        if me == other:
            raise MpiError(ErrorClass.ERR_INTERN,
                           "join requires two distinct processes")
        if me < other:
            port = open_port(self_comm)
            blob = port.encode()
            sock.sendall(len(blob).to_bytes(4, "big") + blob)
            return accept(self_comm, port)
        n = int.from_bytes(_recv_exact(sock, 4), "big")
        port = _recv_exact(sock, n).decode()
        return connect(self_comm, port)
    finally:
        if not isinstance(fd, _socket.socket):
            sock.detach()   # the caller still owns the raw fd


def _recv_exact(sock, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise MpiError(ErrorClass.ERR_INTERN,
                           "join peer closed the socket")
        out += chunk
    return out


_parent_intercomm: Optional[Comm] = None


def get_parent() -> Optional[Comm]:
    """``MPI_Comm_get_parent``: the spawned side of the bridge (None in a
    job that was not spawned)."""
    global _parent_intercomm
    if _parent_intercomm is not None:
        return _parent_intercomm
    import ompi_tpu

    world = ompi_tpu.init()
    rte = world.rte
    parent_ranks = getattr(rte, "parent_ranks", None)
    if not parent_ranks:
        return None
    cid = int(getattr(rte, "parent_cid", -1))
    if cid < 0:
        return None
    _parent_intercomm = _make_intercomm(
        world, cid, parent_ranks, name="parent~spawn")
    return _parent_intercomm


# -- connect / accept (MPI_Open_port / MPI_Comm_accept / MPI_Comm_connect)

def open_port(comm=None) -> str:
    """Generate a unique port name for accept/connect."""
    import ompi_tpu

    comm = comm or ompi_tpu.COMM_WORLD
    client = _client(comm)
    seq = client.fetch_add(-1, "__dpm_port_seq__", 1)
    return f"otpu-port-{seq}"


def publish_name(service: str, port: str, comm=None) -> None:
    """``MPI_Publish_name``: bind a service name to a port so an unrelated
    job can find it (``ompi/mpi/c/publish_name.c`` — PMIx publish)."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    existing = client.put_new(-1, f"__dpm_svc_{service}__", port)
    if existing is not None and existing != port:
        raise MpiError(ErrorClass.ERR_NAME,
                       f"service {service!r} already published")


def lookup_name(service: str, comm=None, wait: bool = False) -> str:
    """``MPI_Lookup_name``: resolve a published service name to a port."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    port = client.get(-1, f"__dpm_svc_{service}__", wait=wait)
    if port is None:
        raise MpiError(ErrorClass.ERR_NAME,
                       f"service {service!r} not published")
    return port


def unpublish_name(service: str, comm=None) -> None:
    """``MPI_Unpublish_name``."""
    import ompi_tpu

    client = _client(comm or ompi_tpu.COMM_WORLD)
    client.delete(-1, f"__dpm_svc_{service}__")


def accept(comm, port: str, root: int = 0) -> Comm:
    """Collective: publish our group under ``port`` and wait for a
    connector; returns the intercommunicator."""
    comm._check_state()
    info = np.zeros(1, np.int64)
    if comm.rank == root:
        client = _client(comm)
        cid = _new_bridge_cid(client)
        client.put(-1, f"__dpm_accept__:{port}",
                   {"cid": cid, "ranks": list(comm.group.world_ranks)})
        other = None
        while other is None:   # block past the KV's 60 s get timeout
            other = client.get(-1, f"__dpm_connect__:{port}", wait=True)
        # consume the pairing: a later accept on this port must wait for
        # a NEW connector, not pair with this stale one
        client.delete(-1, f"__dpm_accept__:{port}")
        client.delete(-1, f"__dpm_connect__:{port}")
        info[0] = cid
        remote = other["ranks"]
    else:
        remote = None
    info = np.asarray(comm.bcast(info, root=root))
    remote = _bcast_obj(comm, remote, root)
    return _make_intercomm(comm, int(info[0]), remote,
                           name=f"{comm.name}~accept")


def connect(comm, port: str, root: int = 0) -> Comm:
    """Collective: join the acceptor publishing ``port``."""
    comm._check_state()
    info = np.zeros(1, np.int64)
    if comm.rank == root:
        client = _client(comm)
        token = client.fetch_add(-1, "__dpm_conn_seq__", 1)
        while True:
            other = None
            while other is None:   # block past the KV's 60 s get timeout
                other = client.get(-1, f"__dpm_accept__:{port}", wait=True)
            # first connector wins the pairing (put_new is atomic); a
            # loser waits for the acceptor to consume the pair and
            # retries against the NEXT accept on this port
            mine = {"ranks": list(comm.group.world_ranks), "token": token}
            got = client.put_new(-1, f"__dpm_connect__:{port}", mine)
            if got.get("token") == token:
                break
            import time as _time

            while client.get(-1, f"__dpm_connect__:{port}",
                             wait=False) is not None:
                _time.sleep(0.01)
        info[0] = other["cid"]
        remote = other["ranks"]
    else:
        remote = None
    info = np.asarray(comm.bcast(info, root=root))
    remote = _bcast_obj(comm, remote, root)
    return _make_intercomm(comm, int(info[0]), remote,
                           name=f"{comm.name}~connect")


def _bcast_obj(comm, obj, root: int):
    """Broadcast a small picklable object over the comm."""
    import pickle

    if comm.size == 1:
        return obj
    if comm.rank == root:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        comm.bcast(np.array([payload.size], np.int64), root=root)
        comm.bcast(payload, root=root)
        return obj
    n = int(np.asarray(comm.bcast(np.zeros(1, np.int64), root=root))[0])
    payload = np.asarray(comm.bcast(np.zeros(n, np.uint8), root=root))
    return pickle.loads(payload.tobytes())


def merge(intercomm, high: bool = False) -> Comm:
    """``MPI_Intercomm_merge``: one intracommunicator over both groups.

    The ``high=False`` group's ranks come first.  Collective over the
    intercommunicator; the low side's root allocates the merged CID and
    bridges it to the high side's root over intercomm p2p.
    """
    if not intercomm.is_inter:
        raise MpiError(ErrorClass.ERR_COMM, "merge needs an intercomm")
    local = getattr(intercomm, "local_comm", None)
    if local is None:
        raise MpiError(ErrorClass.ERR_COMM,
                       "intercomm carries no local collective channel")
    from ompi_tpu.runtime import init as rt

    client = _client(intercomm)
    # deterministic CID allocator: the group containing the smaller world
    # rank allocates and bridges it over (`high` only orders ranks, below)
    my_min = min(intercomm.group.world_ranks)
    other_min = min(intercomm.remote_group.world_ranks)
    i_am_low = my_min < other_min
    buf = np.zeros(1, np.int64)
    if i_am_low:
        if local.rank == 0:
            buf[0] = _new_bridge_cid(client)
            intercomm.send(buf, 0, tag=-7)
    else:
        if local.rank == 0:
            intercomm.recv(buf, 0, tag=-7)
    cid = int(np.asarray(local.bcast(buf, root=0))[0])
    # merged rank order: the group that passed high=False first; both
    # sides must agree, so order by (my `high` flag exchanged via minimum
    # world rank convention): low-world-rank group first unless IT set
    # high=True — exchange the flags over the bridge
    flag = np.array([1 if high else 0], np.int64)
    oflag = np.zeros(1, np.int64)
    if local.rank == 0:
        if i_am_low:
            intercomm.send(flag, 0, tag=-8)
            intercomm.recv(oflag, 0, tag=-8)
        else:
            intercomm.recv(oflag, 0, tag=-8)
            intercomm.send(flag, 0, tag=-8)
    oflag = np.asarray(local.bcast(oflag, root=0))
    mine = list(intercomm.group.world_ranks)
    theirs = list(intercomm.remote_group.world_ranks)
    if int(flag[0]) == int(oflag[0]):
        # same flag: low-world-rank group first (MPI leaves it undefined;
        # this is the reference's deterministic tie-break)
        first = mine if my_min < other_min else theirs
    else:
        first = theirs if high else mine
    second = theirs if first is mine else mine
    merged = Comm(Group(first + second), cid, intercomm.rte,
                  name=f"{intercomm.name}~merge", epoch=0,
                  parent=local)
    rt.reserve_cid(cid)
    local._finish_create(merged)
    return merged
