"""Stateful pack/unpack convertor with partial-buffer resume.

Re-design of ``/root/reference/opal/datatype/opal_convertor.c`` (780 lines)
and the pack state machine (``opal_datatype_pack.c``): a convertor binds a
(datatype, count, user buffer) triple and iterates the packed byte stream in
caller-sized chunks, resumable at any byte position
(``opal_convertor_set_position``).  Host copies are numpy-vectorized: full
elements move through a precomputed byte-offset template (the flattened type
map), partial elements walk segment prefix sums.  Flags mirror
``opal_convertor.h:50-57``: CHECKSUM (CRC32 of the stream), EXTERNAL32
(canonical big-endian), DEVICE (buffer lives in TPU HBM — the
``CONVERTOR_CUDA`` analog; such buffers take the XLA path and must be staged
before host packing).
"""
from __future__ import annotations

import enum
import zlib
from typing import Optional, Union

import numpy as np

from ompi_tpu.datatype.core import Datatype
from ompi_tpu.runtime.hotpath import hot_path

# whole-element pack jobs at least this many bytes fan out over the
# threads-framework worker pool instead of the single-thread native loop.
# fastpath: raised from 256KB — the bench threads_pool_pack_4MB row
# measured the pool barely breaking even at 4MB (1.09x) because pool
# dispatch (job split + cross-thread handoff + wait) costs tens of µs
# that a sub-megabyte native pack never earns back; below this the
# serial native loop is flatly faster and skips the dispatch entirely
# (pinned by test_perf_guard.test_small_pack_skips_pool_dispatch)
_POOL_PACK_MIN = 2 * 1024 * 1024


class ConvertorFlags(enum.IntFlag):
    NONE = 0
    CHECKSUM = 1
    EXTERNAL32 = 2
    DEVICE = 4


def _as_byte_view(buffer) -> np.ndarray:
    """A writable (when possible) flat uint8 view of the caller's buffer."""
    if isinstance(buffer, np.ndarray):
        if not buffer.flags.c_contiguous:
            raise ValueError("convertor requires a C-contiguous buffer")
        return buffer.reshape(-1).view(np.uint8)
    return np.frombuffer(buffer, dtype=np.uint8)


class Convertor:
    """Iterates the packed stream of ``count`` elements of ``datatype``."""

    def __init__(
        self,
        datatype: Datatype,
        count: int,
        buffer=None,
        flags: ConvertorFlags = ConvertorFlags.NONE,
        base_offset: int = 0,
    ) -> None:
        if datatype.true_lb < 0 and base_offset + datatype.true_lb < 0:
            raise ValueError("buffer does not cover negative true_lb")
        self.datatype = datatype
        self.count = count
        self.flags = flags
        self.base_offset = base_offset
        self._mem: Optional[np.ndarray] = None
        if buffer is not None:
            self.prepare(buffer)
        self.position = 0
        self.checksum = 0
        segs = datatype.segments
        self._native = None
        # the segment tables depend only on the datatype: build once and
        # cache ON the datatype — convertor construction is per-message
        # (every send/recv request makes one) and must stay O(1)
        cache = getattr(datatype, "_convertor_cache", None)
        if cache is None:
            seg_offs = np.array([s.offset for s in segs], dtype=np.int64)
            seg_lens = np.array([s.nbytes for s in segs], dtype=np.int64)
            seg_prefix = np.concatenate(([0], np.cumsum(seg_lens)))
            # byte-offset template of one element's packed stream
            tmpl = np.empty(datatype.size, dtype=np.int64)
            pos = 0
            for s in segs:
                tmpl[pos:pos + s.nbytes] = s.offset + np.arange(s.nbytes)
                pos += s.nbytes
            # gap-free single segment ⇒ the packed stream IS the memory
            # layout: pack/unpack collapse to one slice copy
            contig = (len(segs) == 1 and datatype.extent == datatype.size
                      and segs[0].nbytes == datatype.size)
            cache = (seg_offs, seg_lens, seg_prefix, tmpl, contig)
            try:
                datatype._convertor_cache = cache
            except AttributeError:
                pass   # slots/frozen types: just rebuild next time
        (self._seg_offs, self._seg_lens, self._seg_prefix,
         self._template, self._contig) = cache
        # per-position itemsize (for external32 byteswap alignment)
        if flags & ConvertorFlags.EXTERNAL32:
            self._swap_plan = [
                (int(self._seg_prefix[j]), s.dtype.itemsize, s.count)
                for j, s in enumerate(segs)
            ]

    # -- buffer binding --------------------------------------------------
    def prepare(self, buffer) -> "Convertor":
        """Bind the user buffer (``opal_convertor_prepare_for_send/recv``)."""
        if self.flags & ConvertorFlags.DEVICE:
            raise RuntimeError(
                "DEVICE-flagged convertor: stage through the accelerator "
                "component (coll/xla path) before host pack/unpack")
        self._mem = _as_byte_view(buffer)
        # Reject layouts that would index outside the buffer: numpy would
        # wrap negative indices to the buffer's end and silently corrupt.
        dt, n = self.datatype, self.count
        if n > 0 and dt.size > 0:
            lo = self.base_offset + min(0, (n - 1) * dt.extent) + dt.true_lb
            hi = self.base_offset + max(0, (n - 1) * dt.extent) + dt.true_ub
            if lo < 0 or hi > len(self._mem):
                raise ValueError(
                    f"buffer of {len(self._mem)} bytes does not cover type "
                    f"span [{lo}, {hi}) for count={n}")
        return self

    @property
    def packed_size(self) -> int:
        return self.count * self.datatype.size

    @property
    def finished(self) -> bool:
        return self.position >= self.packed_size

    def set_position(self, position: int) -> None:
        if not 0 <= position <= self.packed_size:
            raise ValueError(f"position {position} out of range")
        if self.flags & ConvertorFlags.EXTERNAL32 and self.datatype.size:
            rem = position % self.datatype.size
            j = int(np.searchsorted(self._seg_prefix, rem, side="right")) - 1
            if j < len(self._seg_lens):
                off_in_seg = rem - int(self._seg_prefix[j])
                isz = self.datatype.segments[j].dtype.itemsize
                if off_in_seg % isz:
                    raise ValueError(
                        "external32 position must be item-aligned")
        self.position = position

    # -- core copy loop --------------------------------------------------
    def _stream_ranges(self, start: int, nbytes: int):
        """Yield (mem_lo, mem_hi, stream_off) contiguous copy ranges."""
        dt = self.datatype
        size, ext = dt.size, dt.extent
        p, remaining = start, nbytes
        while remaining > 0:
            e, r = divmod(p, size)
            j = int(np.searchsorted(self._seg_prefix, r, side="right")) - 1
            seg = dt.segments[j]
            o = r - int(self._seg_prefix[j])
            take = min(remaining, seg.nbytes - o)
            lo = self.base_offset + e * ext + seg.offset + o
            yield lo, lo + take, p - start
            p += take
            remaining -= take

    def _full_element_copy(self, first_elem: int, nelem: int,
                           packed: np.ndarray, to_packed: bool) -> None:
        """Gather/scatter of whole elements: native C++ pack loop when the
        library is built (``ompi_tpu.native``, the
        ``opal_datatype_pack.c`` twin), numpy template indexing otherwise."""
        dt = self.datatype
        if nelem <= 0:
            return
        if self._use_native():
            from ompi_tpu import native

            view = packed[: nelem * dt.size]
            # big jobs go wide: the threads framework's pool splits the
            # element loop across native workers (the GIL-free analog of
            # the reference running its pack engine on progress threads)
            if nelem * dt.size >= _POOL_PACK_MIN:
                from ompi_tpu.mca.threads import base as threads_base

                pool = threads_base.get_pool()
                if getattr(pool, "parallel_pack", False) and pool.size > 1:
                    if to_packed:
                        pool.pack(self._mem, view, self._seg_offs,
                                  self._seg_lens, dt.extent,
                                  self.base_offset, first_elem,
                                  nelem).wait()
                    else:
                        chunk = np.ascontiguousarray(view)
                        pool.unpack(self._mem, chunk, self._seg_offs,
                                    self._seg_lens, dt.extent,
                                    self.base_offset, first_elem,
                                    nelem).wait()
                    return
            if to_packed:
                native.pack_elems(self._mem, view, self._seg_offs,
                                  self._seg_lens, dt.extent,
                                  self.base_offset, first_elem, nelem)
            else:
                native.unpack_elems(self._mem, np.ascontiguousarray(view),
                                    self._seg_offs, self._seg_lens,
                                    dt.extent, self.base_offset,
                                    first_elem, nelem)
            return
        idx = (self.base_offset
               + (first_elem + np.arange(nelem, dtype=np.int64))[:, None]
               * dt.extent
               + self._template[None, :]).reshape(-1)
        view = packed[: nelem * dt.size]
        if to_packed:
            view[:] = self._mem[idx]
        else:
            self._mem[idx] = view

    def _use_native(self) -> bool:
        if self._native is None:
            try:
                from ompi_tpu import native

                # the native loop wins when elements are many and small
                # (interpreter-bound); huge contiguous runs are equally
                # fast either way
                # writeable: native unpack memcpy's into the buffer and
                # must not bypass numpy's read-only protection
                self._native = (native.available()
                                and self._mem.flags.c_contiguous
                                and self._mem.flags.writeable)
            except Exception:
                self._native = False
        return self._native

    def _swap_external32(self, chunk: np.ndarray, stream_start: int) -> None:
        """In-place byteswap of a packed chunk (item-aligned chunks only)."""
        dt = self.datatype
        size = dt.size
        pos = 0
        n = len(chunk)
        while pos < n:
            p = stream_start + pos
            e, r = divmod(p, size)
            j = int(np.searchsorted(self._seg_prefix, r, side="right")) - 1
            seg = dt.segments[j]
            o = r - int(self._seg_prefix[j])
            take = min(n - pos, seg.nbytes - o)
            isz = seg.dtype.itemsize
            if o % isz or take % isz:
                raise ValueError("external32 chunk not item-aligned")
            if isz > 1:
                sub = chunk[pos:pos + take].reshape(-1, isz)
                sub[:] = sub[:, ::-1]
            pos += take

    def pack(self, max_bytes: Optional[int] = None) -> np.ndarray:
        """Return the next <= max_bytes of the packed stream; advances.

        Returns an OWNED uint8 array (bytes-like; btls write it straight
        to the wire — returning ``bytes`` would add a full-size copy per
        fragment on the host hot path)."""
        if self._mem is None:
            raise RuntimeError("convertor has no buffer bound")
        if self.packed_size == 0:
            return np.empty(0, np.uint8)
        dt = self.datatype
        n = self.packed_size - self.position
        if max_bytes is not None:
            n = min(n, max_bytes)
        n = self._align_external32(n)
        start = self.position
        if self._contig and not (self.flags & ConvertorFlags.EXTERNAL32):
            # contiguous fast path: stream position == memory offset
            lo = self.base_offset + dt.segments[0].offset + start
            out = np.array(self._mem[lo:lo + n])   # owned copy
            if self.flags & ConvertorFlags.CHECKSUM:
                self.checksum = zlib.crc32(out, self.checksum)
            self.position = start + n
            return out
        out = np.empty(n, dtype=np.uint8)
        # head partial element
        written = 0
        size = dt.size
        e0, r0 = divmod(start, size)
        if r0:
            head = min(n, size - r0)
            for lo, hi, so in self._stream_ranges(start, head):
                out[so:so + (hi - lo)] = self._mem[lo:hi]
            written = head
        # full elements
        nfull = (n - written) // size
        if nfull:
            self._full_element_copy(
                (start + written) // size, nfull,
                out[written:written + nfull * size], to_packed=True)
            written += nfull * size
        # tail partial
        if written < n:
            for lo, hi, so in self._stream_ranges(start + written, n - written):
                out[written + so: written + so + (hi - lo)] = self._mem[lo:hi]
            written = n
        if self.flags & ConvertorFlags.EXTERNAL32:
            self._swap_external32(out, start)
        if self.flags & ConvertorFlags.CHECKSUM:
            self.checksum = zlib.crc32(out, self.checksum)
        self.position = start + n
        return out

    @hot_path
    def pack_borrow(self, max_bytes: Optional[int] = None):
        """Like :meth:`pack` but may return a zero-copy VIEW of the bound
        user buffer: ``(chunk, borrowed)``.  When ``borrowed`` is True the
        chunk aliases user memory — a transport must either consume it
        synchronously (copy to wire/ring before returning) or take an
        owned copy before queueing it anywhere (the reference's btl
        descriptors make the same send-in-place vs buffered distinction).
        """
        if (self._contig and self._mem is not None and self.packed_size
                and not self.flags & (ConvertorFlags.EXTERNAL32
                                      | ConvertorFlags.CHECKSUM)):
            n = self.packed_size - self.position
            if max_bytes is not None:
                n = min(n, max_bytes)
            lo = (self.base_offset + self.datatype.segments[0].offset
                  + self.position)
            self.position += n
            return self._mem[lo:lo + n], True
        return self.pack(max_bytes), False

    def unpack_view(self, n: int) -> Optional[np.ndarray]:
        """Writable zero-copy view of the next ``n`` destination bytes,
        or None when the layout/flags force the generic unpack path.
        The caller fills the view, then calls :meth:`advance` — the
        one-sided receive path (RGET) lands peer data straight in the
        user buffer this way, skipping the staging copy."""
        if (not self._contig or self._mem is None
                or self.flags & (ConvertorFlags.EXTERNAL32
                                 | ConvertorFlags.CHECKSUM)
                or not self._mem.flags.writeable):
            return None
        n = min(n, self.packed_size - self.position)
        lo = (self.base_offset + self.datatype.segments[0].offset
              + self.position)
        return self._mem[lo:lo + n]

    def advance(self, n: int) -> None:
        """Consume ``n`` stream bytes filled through :meth:`unpack_view`."""
        self.position = min(self.position + n, self.packed_size)

    def unpack(self, data: Union[bytes, memoryview, np.ndarray]) -> int:
        """Consume an incoming packed chunk at the current position."""
        if self._mem is None:
            raise RuntimeError("convertor has no buffer bound")
        if self.packed_size == 0:
            return 0
        chunk = np.frombuffer(data, dtype=np.uint8).copy() \
            if self.flags & ConvertorFlags.EXTERNAL32 \
            else np.frombuffer(data, dtype=np.uint8)
        n = min(len(chunk), self.packed_size - self.position)
        aligned = self._align_external32(n)
        if aligned != n and len(chunk) > aligned:
            n = aligned  # leave unaligned tail to the caller
        chunk = chunk[:n]
        start = self.position
        if self.flags & ConvertorFlags.CHECKSUM:
            self.checksum = zlib.crc32(np.ascontiguousarray(chunk),
                                       self.checksum)
        if self.flags & ConvertorFlags.EXTERNAL32:
            self._swap_external32(chunk, start)
        dt = self.datatype
        if self._contig and not (self.flags & ConvertorFlags.EXTERNAL32):
            lo = self.base_offset + dt.segments[0].offset + start
            self._mem[lo:lo + n] = chunk
            self.position = start + n
            return n
        size = dt.size
        written = 0
        e0, r0 = divmod(start, size)
        if r0:
            head = min(n, size - r0)
            for lo, hi, so in self._stream_ranges(start, head):
                self._mem[lo:hi] = chunk[so:so + (hi - lo)]
            written = head
        nfull = (n - written) // size
        if nfull:
            self._full_element_copy(
                (start + written) // size, nfull,
                chunk[written:written + nfull * size], to_packed=False)
            written += nfull * size
        if written < n:
            for lo, hi, so in self._stream_ranges(start + written, n - written):
                self._mem[lo:hi] = chunk[written + so: written + so + (hi - lo)]
        self.position = start + n
        return n

    def _align_external32(self, n: int) -> int:
        """Round a chunk size down to an item boundary in external32 mode."""
        if not (self.flags & ConvertorFlags.EXTERNAL32) or n == 0:
            return n
        dt = self.datatype
        size = dt.size
        end = self.position + n
        e, r = divmod(end, size)
        if r == 0:
            return n
        j = int(np.searchsorted(self._seg_prefix, r, side="right")) - 1
        seg = dt.segments[j]
        o = r - int(self._seg_prefix[j])
        slack = o % seg.dtype.itemsize
        if slack and n - slack <= 0:
            raise ValueError("external32 chunk smaller than one item")
        return n - slack
