"""Datatype descriptions: named types, constructors, flattened type maps.

Re-design of ``/root/reference/opal/datatype/opal_datatype.h`` +
``ompi/datatype/ompi_datatype.h``: a datatype is a *type map* — an ordered
list of (byte offset, elementary type, count) runs — with MPI extent
semantics (lb/ub, true extent, resizing).  Construction-time coalescing of
memory-adjacent same-type runs mirrors ``opal_datatype_optimize.c``.
Elementary types are numpy dtypes, which gives vectorized host pack/unpack
and direct interop with ``jax.Array`` host buffers; ``bfloat16`` (via
ml_dtypes) is a first-class named type for TPU payloads.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import numpy as np

from ompi_tpu.api.attributes import AttributeHost

try:  # ml_dtypes ships with jax; gives numpy bfloat16
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = np.dtype(np.uint16)  # bit-compatible fallback

ORDER_C = 0
ORDER_FORTRAN = 1
DISTRIBUTE_BLOCK = 0
DISTRIBUTE_CYCLIC = 1
DISTRIBUTE_NONE = 2
DISTRIBUTE_DFLT_DARG = -1


@dataclass(frozen=True)
class Segment:
    """One elementary run: ``count`` items of ``dtype`` at byte ``offset``."""

    offset: int
    dtype: np.dtype
    count: int

    @property
    def nbytes(self) -> int:
        return self.count * self.dtype.itemsize

    @property
    def end(self) -> int:
        return self.offset + self.nbytes


def _coalesce(segments: Iterable[Segment]) -> tuple[Segment, ...]:
    """Merge runs adjacent both in type-map order and in memory."""
    out: list[Segment] = []
    for seg in segments:
        if seg.count == 0:
            continue
        if out and out[-1].dtype == seg.dtype and out[-1].end == seg.offset:
            prev = out.pop()
            seg = Segment(prev.offset, prev.dtype, prev.count + seg.count)
        out.append(seg)
    return tuple(out)


class Datatype(AttributeHost):
    """An MPI-style datatype: committed type map + extent bookkeeping.

    Hosts attributes (``MPI_Type_set_attr`` family) via AttributeHost,
    like communicators and windows."""

    def __init__(
        self,
        segments: Sequence[Segment],
        lb: Optional[int] = None,
        ub: Optional[int] = None,
        name: str = "",
        combiner: str = "named",
        contents: tuple = (),
    ) -> None:
        self.segments = _coalesce(segments)
        self.size = sum(s.nbytes for s in self.segments)
        if self.segments:
            self.true_lb = min(s.offset for s in self.segments)
            self.true_ub = max(s.end for s in self.segments)
        else:
            self.true_lb = self.true_ub = 0
        self.lb = self.true_lb if lb is None else lb
        self.ub = self.true_ub if ub is None else ub
        self.name = name
        self.combiner = combiner
        self.contents = contents
        self.committed = False
        # single contiguous run starting at lb covering the whole extent
        self.is_contiguous = (
            len(self.segments) <= 1
            and self.lb == self.true_lb
            and self.extent == self.size
        )

    # -- MPI accessors ---------------------------------------------------
    @property
    def extent(self) -> int:
        return self.ub - self.lb

    @property
    def true_extent(self) -> int:
        return self.true_ub - self.true_lb

    def commit(self) -> "Datatype":
        self.committed = True
        return self

    def free(self) -> None:
        self.committed = False

    def dup(self) -> "Datatype":
        d = Datatype(self.segments, self.lb, self.ub, self.name, "dup",
                     (self,))
        d.committed = self.committed
        self._attrs_copy_to(d)   # MPI_Type_dup runs the keyval copy fns
        return d

    def get_envelope(self) -> tuple[str, tuple]:
        """(combiner, contents) — the decode API (``MPI_Type_get_envelope``)."""
        return self.combiner, self.contents

    def get_contents(self) -> tuple:
        """``MPI_Type_get_contents``: the constructor arguments."""
        return self.contents

    def set_name(self, name: str) -> None:
        """``MPI_Type_set_name``."""
        self.name = name

    def get_name(self) -> str:
        """``MPI_Type_get_name``."""
        return self.name

    # -- helpers used by the convertor and coll/op layers ---------------
    @property
    def elementary(self) -> Optional[np.dtype]:
        """The single elementary numpy dtype, if homogeneous (op kernels)."""
        dtypes = {s.dtype for s in self.segments}
        return next(iter(dtypes)) if len(dtypes) == 1 else None

    def element_count(self, nbytes: int) -> int:
        """How many elementary items fit in ``nbytes`` of packed stream."""
        if self.size == 0:
            return 0
        full, rem = divmod(nbytes, self.size)
        n = full * sum(s.count for s in self.segments)
        for s in self.segments:
            if rem <= 0:
                break
            take = min(rem, s.nbytes)
            n += take // s.dtype.itemsize
            rem -= take
        return n

    def __repr__(self) -> str:
        return (f"Datatype({self.name or self.combiner}, size={self.size}, "
                f"extent={self.extent}, nseg={len(self.segments)})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, Datatype)
                and self.segments == other.segments
                and self.lb == other.lb and self.ub == other.ub)

    def __hash__(self) -> int:
        return hash((self.segments, self.lb, self.ub))


def _named(np_dtype, name: str) -> Datatype:
    dt = np.dtype(np_dtype)
    return Datatype([Segment(0, dt, 1)], name=name).commit()


# Named types (``ompi/datatype/ompi_datatype_internal.h`` table equivalent;
# fixed-width only — TPU-native set includes bf16/f16).
BYTE = _named(np.uint8, "BYTE")
PACKED = _named(np.uint8, "PACKED")
BOOL = _named(np.bool_, "BOOL")
INT8 = _named(np.int8, "INT8")
INT16 = _named(np.int16, "INT16")
INT32 = _named(np.int32, "INT32")
INT64 = _named(np.int64, "INT64")
UINT8 = _named(np.uint8, "UINT8")
UINT16 = _named(np.uint16, "UINT16")
UINT32 = _named(np.uint32, "UINT32")
UINT64 = _named(np.uint64, "UINT64")
FLOAT16 = _named(np.float16, "FLOAT16")
BFLOAT16 = _named(_BF16, "BFLOAT16")
FLOAT32 = _named(np.float32, "FLOAT32")
FLOAT64 = _named(np.float64, "FLOAT64")
COMPLEX64 = _named(np.complex64, "COMPLEX64")
COMPLEX128 = _named(np.complex128, "COMPLEX128")


def _pair(first: np.dtype, name: str) -> Datatype:
    """MINLOC/MAXLOC pair types: C-struct layout of (value, int32 index)."""
    struct = np.dtype([("v", first), ("i", np.int32)], align=True)
    segs = [
        Segment(struct.fields["v"][1], np.dtype(first), 1),
        Segment(struct.fields["i"][1], np.dtype(np.int32), 1),
    ]
    return Datatype(segs, lb=0, ub=struct.itemsize, name=name).commit()


FLOAT_INT = _pair(np.float32, "FLOAT_INT")
DOUBLE_INT = _pair(np.float64, "DOUBLE_INT")
LONG_INT = _pair(np.int64, "LONG_INT")
SHORT_INT = _pair(np.int16, "SHORT_INT")
TWO_INT = _pair(np.int32, "TWO_INT")

NAMED_TYPES: dict[str, Datatype] = {
    t.name: t
    for t in (
        BYTE, PACKED, BOOL, INT8, INT16, INT32, INT64, UINT8, UINT16,
        UINT32, UINT64, FLOAT16, BFLOAT16, FLOAT32, FLOAT64, COMPLEX64,
        COMPLEX128, FLOAT_INT, DOUBLE_INT, LONG_INT, SHORT_INT, TWO_INT,
    )
}

_SIMPLE_NP: dict[str, Datatype] = {}
for _t in (BOOL, INT8, INT16, INT32, INT64, UINT8, UINT16, UINT32, UINT64,
           FLOAT16, BFLOAT16, FLOAT32, FLOAT64, COMPLEX64, COMPLEX128):
    _SIMPLE_NP.setdefault(np.dtype(_t.segments[0].dtype).str, _t)


def from_numpy_dtype(dt) -> Datatype:
    """Map a numpy dtype (simple or structured) to a Datatype."""
    dt = np.dtype(dt)
    if dt.fields:
        segs: list[Segment] = []
        for fname in dt.names:
            fdt, off = dt.fields[fname][0], dt.fields[fname][1]
            sub = from_numpy_dtype(fdt)
            for s in sub.segments:
                segs.append(Segment(off + s.offset, s.dtype, s.count))
        return Datatype(segs, lb=0, ub=dt.itemsize, name=str(dt),
                        combiner="struct")
    if dt.subdtype is not None:
        base, shape = dt.subdtype
        sub = from_numpy_dtype(base)
        return contiguous(math.prod(shape), sub)
    named = _SIMPLE_NP.get(dt.str)
    if named is not None:
        return named
    if dt.itemsize >= 1 and dt.kind in ("V", "S", "U"):
        return contiguous(dt.itemsize, BYTE)
    raise TypeError(f"unsupported numpy dtype {dt}")


# ---------------------------------------------------------------------------
# Constructors (``ompi/datatype/ompi_datatype_create_*.c`` equivalents)
# ---------------------------------------------------------------------------

def _replicate(old: Datatype, displacements_bytes: Iterable[int],
               blocklen: int = 1) -> list[Segment]:
    """Place ``blocklen`` consecutive copies of ``old`` at each displacement."""
    segs: list[Segment] = []
    ext = old.extent
    for disp in displacements_bytes:
        for b in range(blocklen):
            base = disp + b * ext
            for s in old.segments:
                segs.append(Segment(base + s.offset, s.dtype, s.count))
    return segs


def _bounds(old: Datatype, displacements_bytes: Sequence[int],
            blocklens) -> tuple[Optional[int], Optional[int]]:
    """MPI lb/ub rules: propagate explicit bounds through constructors."""
    if not displacements_bytes:
        return 0, 0
    if isinstance(blocklens, int):
        blocklens = [blocklens] * len(displacements_bytes)
    lbs = [d + old.lb for d in displacements_bytes]
    ubs = [d + old.lb + bl * old.extent + (old.ub - old.lb - old.extent)
           for d, bl in zip(displacements_bytes, blocklens)]
    # old.ub - old.lb == old.extent always, so ubs simplify to
    # d + old.lb + bl*extent; kept explicit for clarity with resized types.
    return min(lbs), max(ubs)


def contiguous(count: int, old: Datatype) -> Datatype:
    segs = _replicate(old, [0], count)
    return Datatype(segs, lb=old.lb, ub=old.lb + count * old.extent,
                    combiner="contiguous", contents=(count, old))


def vector(count: int, blocklength: int, stride: int, old: Datatype) -> Datatype:
    return _hvector(count, blocklength, stride * old.extent, old, "vector",
                    (count, blocklength, stride, old))


def hvector(count: int, blocklength: int, stride_bytes: int,
            old: Datatype) -> Datatype:
    return _hvector(count, blocklength, stride_bytes, old, "hvector",
                    (count, blocklength, stride_bytes, old))


def _hvector(count, blocklength, stride_bytes, old, combiner, contents):
    disps = [i * stride_bytes for i in range(count)]
    segs = _replicate(old, disps, blocklength)
    lb, ub = _bounds(old, disps, blocklength)
    return Datatype(segs, lb=lb, ub=ub, combiner=combiner, contents=contents)


def indexed(blocklengths: Sequence[int], displacements: Sequence[int],
            old: Datatype) -> Datatype:
    disps = [d * old.extent for d in displacements]
    return _hindexed(blocklengths, disps, old, "indexed",
                     (tuple(blocklengths), tuple(displacements), old))


def hindexed(blocklengths: Sequence[int], displacements_bytes: Sequence[int],
             old: Datatype) -> Datatype:
    return _hindexed(blocklengths, displacements_bytes, old, "hindexed",
                     (tuple(blocklengths), tuple(displacements_bytes), old))


def _hindexed(blocklengths, disps, old, combiner, contents):
    segs: list[Segment] = []
    for bl, d in zip(blocklengths, disps):
        segs.extend(_replicate(old, [d], bl))
    lb, ub = _bounds(old, disps, list(blocklengths))
    return Datatype(segs, lb=lb, ub=ub, combiner=combiner, contents=contents)


def hindexed_block(blocklength: int, displacements_bytes: Sequence[int],
                   old: Datatype) -> Datatype:
    """``MPI_Type_create_hindexed_block``: equal-length blocks at byte
    displacements (``ompi/mpi/c/type_create_hindexed_block.c``)."""
    return _hindexed([blocklength] * len(displacements_bytes),
                     list(displacements_bytes), old, "hindexed_block",
                     (blocklength, tuple(displacements_bytes), old))


def indexed_block(blocklength: int, displacements: Sequence[int],
                  old: Datatype) -> Datatype:
    return indexed([blocklength] * len(displacements), displacements, old)


def create_struct(blocklengths: Sequence[int],
                  displacements_bytes: Sequence[int],
                  types: Sequence[Datatype]) -> Datatype:
    segs: list[Segment] = []
    lbs, ubs = [], []
    for bl, d, t in zip(blocklengths, displacements_bytes, types):
        segs.extend(_replicate(t, [d], bl))
        lbs.append(d + t.lb)
        ubs.append(d + t.lb + bl * t.extent)
    lb = min(lbs) if lbs else 0
    ub = max(ubs) if ubs else 0
    return Datatype(segs, lb=lb, ub=ub, combiner="struct",
                    contents=(tuple(blocklengths), tuple(displacements_bytes),
                              tuple(types)))


def resized(old: Datatype, lb: int, extent: int) -> Datatype:
    return Datatype(old.segments, lb=lb, ub=lb + extent, combiner="resized",
                    contents=(old, lb, extent))


def subarray(sizes: Sequence[int], subsizes: Sequence[int],
             starts: Sequence[int], order: int, old: Datatype) -> Datatype:
    """n-dim subarray (``MPI_Type_create_subarray``), built as nested hvectors."""
    ndims = len(sizes)
    if order == ORDER_FORTRAN:
        sizes, subsizes, starts = sizes[::-1], subsizes[::-1], starts[::-1]
    ext = old.extent
    # strides (bytes) of each dim in the full array, C order
    strides = [ext] * ndims
    for d in range(ndims - 2, -1, -1):
        strides[d] = strides[d + 1] * sizes[d + 1]
    t = contiguous(subsizes[-1], old)
    for d in range(ndims - 2, -1, -1):
        t = hvector(subsizes[d], 1, strides[d], t)
    offset = sum(starts[d] * strides[d] for d in range(ndims))
    shifted = create_struct([1], [offset], [t])
    full = ext * math.prod(sizes)
    out = resized(shifted, 0, full)
    out.combiner = "subarray"
    out.contents = (tuple(sizes), tuple(subsizes), tuple(starts), order, old)
    return out


def darray(size: int, rank: int, gsizes: Sequence[int],
           distribs: Sequence[int], dargs: Sequence[int],
           psizes: Sequence[int], order: int, old: Datatype) -> Datatype:
    """Distributed array filetype (``MPI_Type_create_darray``).

    Built by computing this rank's global element indices per dimension
    (block / cyclic(k) / none) with numpy and emitting coalesced runs —
    correct by construction; intended for I/O file views at test/checkpoint
    scale (guarded at 2^22 local elements).
    """
    ndims = len(gsizes)
    if math.prod(psizes) != size:
        raise ValueError("prod(psizes) != size")
    # rank -> process grid coords (C order: last dim fastest, MPI standard)
    coords = []
    r = rank
    for d in range(ndims - 1, -1, -1):
        coords.append(r % psizes[d])
        r //= psizes[d]
    coords = coords[::-1]

    def dim_indices(d: int) -> np.ndarray:
        n, p, c = gsizes[d], psizes[d], coords[d]
        dist, darg = distribs[d], dargs[d]
        if dist == DISTRIBUTE_NONE:
            return np.arange(n)
        if dist == DISTRIBUTE_BLOCK:
            bs = darg if darg != DISTRIBUTE_DFLT_DARG else (n + p - 1) // p
            if bs * p < n:
                raise ValueError(
                    f"darray dim {d}: block size {bs} x {p} procs < {n} "
                    f"global elements (MPI_ERR_ARG)")
            lo = c * bs
            hi = min(lo + bs, n)
            return np.arange(lo, max(lo, hi))
        if dist == DISTRIBUTE_CYCLIC:
            bs = darg if darg != DISTRIBUTE_DFLT_DARG else 1
            idx = np.arange(n)
            return idx[(idx // bs) % p == c]
        return np.arange(n)

    per_dim = [dim_indices(d) for d in range(ndims)]
    nlocal = math.prod(len(ix) for ix in per_dim)
    if nlocal > (1 << 22):
        raise ValueError("darray too large for explicit-map construction")
    ext = old.extent
    if order == ORDER_FORTRAN:
        strides = [ext * math.prod(gsizes[:d]) for d in range(ndims)]
    else:
        strides = [ext * math.prod(gsizes[d + 1:]) for d in range(ndims)]
    grids = np.meshgrid(*per_dim, indexing="ij")
    lin = sum(g.astype(np.int64) * s for g, s in zip(grids, strides))
    lin = np.sort(lin.ravel())
    segs = _replicate(old, [int(x) for x in lin])
    out = Datatype(segs, lb=0, ub=ext * math.prod(gsizes), combiner="darray",
                   contents=(size, rank, tuple(gsizes), tuple(distribs),
                             tuple(dargs), tuple(psizes), order, old))
    return out


def match_size(typeclass: str, size: int) -> Datatype:
    """``MPI_Type_match_size``: the named type of ``typeclass``
    ("integer" | "real" | "complex") with exactly ``size`` bytes
    (``ompi/mpi/c/type_match_size.c``)."""
    table = {
        "integer": {1: INT8, 2: INT16, 4: INT32, 8: INT64},
        "real": {2: BFLOAT16, 4: FLOAT32, 8: FLOAT64},
        "complex": {8: COMPLEX64, 16: COMPLEX128},
    }
    try:
        return table[str(typeclass).lower()][int(size)]
    except KeyError:
        raise ValueError(
            f"no {typeclass!r} type of {size} bytes") from None
