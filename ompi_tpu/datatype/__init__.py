"""Datatype engine: described-layout memory + stateful pack/unpack convertor.

TPU-native re-design of the reference datatype stack
(``/root/reference/opal/datatype/`` — 8,249 LoC — and ``ompi/datatype/``):
MPI named types and the full constructor set build a *type map* that is
flattened and coalesced into elementary segments
(``opal_datatype_optimize.c`` equivalent); the :class:`Convertor` is the
stateful pack/unpack iterator with partial-buffer resume and repositioning
(``opal_convertor.c`` — 780 lines; ``opal_datatype_pack.c`` state machine),
plus heterogeneous/external32 conversion and checksums.  TPU-first additions:
``bfloat16``/``float16`` as first-class named types, and device-residency
flags on the convertor (the analog of ``CONVERTOR_CUDA``,
``opal_convertor.h:50-57``) so device buffers route to the XLA path instead
of host pack/unpack.
"""
from ompi_tpu.datatype.core import (  # noqa: F401
    Datatype,
    BYTE,
    PACKED,
    BOOL,
    INT8,
    INT16,
    INT32,
    INT64,
    UINT8,
    UINT16,
    UINT32,
    UINT64,
    FLOAT16,
    BFLOAT16,
    FLOAT32,
    FLOAT64,
    COMPLEX64,
    COMPLEX128,
    FLOAT_INT,
    DOUBLE_INT,
    LONG_INT,
    SHORT_INT,
    TWO_INT,
    NAMED_TYPES,
    from_numpy_dtype,
    contiguous,
    vector,
    hvector,
    indexed,
    hindexed,
    hindexed_block,
    indexed_block,
    create_struct,
    subarray,
    darray,
    resized,
    ORDER_C,
    ORDER_FORTRAN,
    DISTRIBUTE_BLOCK,
    DISTRIBUTE_CYCLIC,
    DISTRIBUTE_NONE,
    DISTRIBUTE_DFLT_DARG,
)
from ompi_tpu.datatype.convertor import Convertor, ConvertorFlags  # noqa: F401


def pack(buf, count, datatype, external32: bool = False) -> bytes:
    """``MPI_Pack`` (/ ``MPI_Pack_external``): described memory → a
    contiguous byte stream, via the convertor (``ompi/mpi/c/pack.c``)."""
    flags = ConvertorFlags.EXTERNAL32 if external32 else ConvertorFlags.NONE
    # user-facing MPI_Pack keeps the documented bytes contract; the hot
    # path (pml/btl) consumes the convertor's zero-extra-copy array form
    return Convertor(datatype, count, buf, flags=flags).pack().tobytes()


def unpack(data, buf, count, datatype, external32: bool = False) -> int:
    """``MPI_Unpack``: byte stream → described memory; returns the bytes
    consumed."""
    flags = ConvertorFlags.EXTERNAL32 if external32 else ConvertorFlags.NONE
    return Convertor(datatype, count, buf, flags=flags).unpack(data)


def pack_size(count, datatype, external32: bool = False) -> int:
    """``MPI_Pack_size``: an upper bound on pack()'s output size."""
    return count * datatype.size


def reduce_local(inbuf, inoutbuf, op) -> None:
    """``MPI_Reduce_local``: inoutbuf = inbuf (op) inoutbuf — the op
    kernel applied locally (``ompi/mpi/c/reduce_local.c``; kernel table
    ≅ ``ompi/mca/op``)."""
    op(inbuf, inoutbuf)
