"""Pallas VPU reduction kernels — the op/avx analog on TPU.

Two entry points, both shape-polymorphic over arbitrary operand shapes:

``combine2(op_name, a, b)``
    Elementwise ``a (op) b`` through a tiled VMEM kernel — the two-operand
    reduction primitive every MPI_Reduce-family algorithm folds with
    (reference kernel table ``ompi/mca/op/avx/op_avx_functions.c``).

``reduce_stack(op_name, x)``
    Reduce a ``(k, ...)`` stack along axis 0 in ONE pass through VMEM.
    This is the fused form of the k-1 chained folds the coll algorithm
    library performs after an allgather (Rabenseifner post-reduce, tree
    reduce leaves) — a bandwidth win over materialising each intermediate
    in HBM.

Operands are flattened and padded to (rows, 128) lanes; the grid walks
row-tiles so arbitrarily large buffers stream through VMEM.  Off-TPU the
kernels run in interpreter mode so the same code path is exercised by the
CPU test mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128
ROW_TILE = 512  # 512x128 f32 tile = 256 KiB per operand in VMEM

_FOLDS = {
    "SUM": lambda a, b: a + b,
    "PROD": lambda a, b: a * b,
    "MAX": jnp.maximum,
    "MIN": jnp.minimum,
    "BAND": lambda a, b: a & b,
    "BOR": lambda a, b: a | b,
    "BXOR": lambda a, b: a ^ b,
    "LAND": lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype),
    "LOR": lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype),
    "LXOR": lambda a, b: ((a != 0) ^ (b != 0)).astype(a.dtype),
}
_BITWISE = ("BAND", "BOR", "BXOR")


def supported_ops() -> tuple:
    return tuple(_FOLDS)


def _interpret() -> bool:
    from ompi_tpu.base.jaxenv import pallas_interpret_default

    return pallas_interpret_default()


def _supported_dtype(op_name: str, dtype) -> bool:
    if op_name in _BITWISE:
        return jnp.issubdtype(dtype, jnp.integer) or dtype == jnp.bool_
    return jnp.issubdtype(dtype, jnp.floating) or \
        jnp.issubdtype(dtype, jnp.integer)


def _pad_rows(flat, rows_mult: int):
    """Flatten → (rows, LANES) padded so rows % rows_mult == 0."""
    n = flat.size
    rows = max(1, -(-n // LANES))
    rows = -(-rows // rows_mult) * rows_mult
    pad = rows * LANES - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, LANES), rows


def _combine_kernel(fold, a_ref, b_ref, o_ref):
    o_ref[:] = fold(a_ref[:], b_ref[:])


@functools.partial(jax.jit, static_argnums=0,
                   static_argnames=("interpret",))
def combine2(op_name: str, a, b, *, interpret=None):
    """Elementwise ``a (op) b`` on the VPU; shape/dtype of ``a``.

    ``interpret`` is a static jit-cache-key ingredient: None resolves
    from the backend at trace time; an explicit value (the AOT Mosaic
    gate passes False) always wins and can never be served a cached
    interpreter trace."""
    fold = _FOLDS[op_name]
    a2, rows = _pad_rows(a.ravel(), ROW_TILE)
    b2, _ = _pad_rows(b.ravel(), ROW_TILE)
    grid = (rows // ROW_TILE,)
    spec = pl.BlockSpec((ROW_TILE, LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_combine_kernel, fold),
        out_shape=jax.ShapeDtypeStruct(a2.shape, a2.dtype),
        grid=grid, in_specs=[spec, spec], out_specs=spec,
        interpret=_interpret() if interpret is None else interpret,
    )(a2, b2)
    return out.ravel()[: a.size].reshape(a.shape)


def _stack_kernel(fold, k, x_ref, o_ref):
    acc = x_ref[0]
    for i in range(1, k):  # k is static — unrolled VPU chain, one VMEM pass
        acc = fold(acc, x_ref[i])
    o_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("op_name", "interpret"))
def reduce_stack(op_name: str, x, *, interpret=None):
    """Reduce ``x[k, ...]`` along axis 0 in one streaming VMEM pass.

    ``interpret`` is a static jit-cache-key ingredient (see combine2)."""
    fold = _FOLDS[op_name]
    k = x.shape[0]
    if k == 1:
        return x[0]
    # row tile sized so k operand tiles + out fit VMEM comfortably
    tile = max(8, min(ROW_TILE, 4096 // k * 8))
    per = x[0].size
    rows_k = max(1, -(-per // LANES))
    rows_k = -(-rows_k // tile) * tile
    pad = rows_k * LANES - per
    xp = jnp.pad(x.reshape(k, per), ((0, 0), (0, pad)))
    xp = xp.reshape(k, rows_k, LANES)
    out = pl.pallas_call(
        functools.partial(_stack_kernel, fold, k),
        out_shape=jax.ShapeDtypeStruct((rows_k, LANES), x.dtype),
        grid=(rows_k // tile,),
        in_specs=[pl.BlockSpec((k, tile, LANES), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((tile, LANES), lambda i: (i, 0)),
        interpret=_interpret() if interpret is None else interpret,
    )(xp)
    return out.ravel()[:per].reshape(x.shape[1:])


def device_fold(op_name: str, dtype):
    """Return a two-operand fold callable for (op, dtype), or None.

    The op framework's component query hook: None means "this kernel set
    does not cover the type", and selection falls through to the next
    component (plain-XLA jnp fold), mirroring the reference's per-type
    function tables (``op_avx_functions.c`` dispatch by flags+type).
    """
    if op_name not in _FOLDS or not _supported_dtype(op_name, dtype):
        return None
    return functools.partial(combine2, op_name)
