"""Fused compute+communicate kernels — the collective matmul.

THE reason the explicit-schedule transport exists (SURVEY §2.6; module
docstring of :mod:`ompi_tpu.ops.pallas_collectives`): XLA schedules a
matmul THEN an all-reduce; an explicit kernel interleaves them so the
ICI is busy while the MXU computes.  The classic case is the
contraction-sharded ("tensor-parallel k-split") matmul

    C = Σ_i  A_i @ B_i        A_i: (M, K/n),  B_i: (K/n, N)

whose partial products ring-reduce across the mesh.  The fused schedule
computes the row-block of the partial product **just in time**, one ring
step before it is needed, so each step's remote DMA flies while the MXU
computes the next block:

  step k: start DMA of the running partial for block (my-k) rightward
          compute local partial P[my-1-k]      <- overlaps the DMA
          wait DMA; fold P[my-1-k] + incoming into the running partial

After n-1 such steps block (my+1) is fully reduced; a plain all-gather
ring replicates C.  Ring schedule = ``coll_base_allreduce.c:341``; the
overlap is the TPU-first "async collective matmul" the compiler cannot
always produce on its own.

Interpreter-mode runs (tests, virtual meshes) execute the same schedule
serially; on hardware the DMA/compute overlap is real.
"""
from __future__ import annotations

import functools

import numpy as np

from ompi_tpu.ops.pallas_collectives import _ag_phase, _mods, _ring_kernels


def _prep_operands(a, b, mesh, axis):
    """Shared wrapper preamble: validate the contraction, promote mixed
    dtypes OUTSIDE the kernel (mismatched refs vs VMEM scratch fail
    tracing), and extract the static shapes.  Returns
    (a, b, n, m, k_loc, n_out, dtype)."""
    n = mesh.shape[axis]
    m, k_loc = int(a.shape[1]), int(a.shape[2])
    n_out = int(b.shape[2])
    if int(b.shape[1]) != k_loc:
        raise ValueError(
            f"contraction mismatch: a has K/n={k_loc}, b has "
            f"{int(b.shape[1])}")
    dtype = np.result_type(a.dtype, b.dtype)
    if a.dtype != dtype or b.dtype != dtype:
        a = a.astype(dtype)
        b = b.astype(dtype)
    return a, b, n, m, k_loc, n_out, dtype


@functools.lru_cache(maxsize=64)
def _build_fused_matmul(n: int, axis: str, m_blk: int, k_loc: int,
                        n_out: int, dtype_str: str, interpret: bool,
                        align: int, with_ag: bool, cid: int):
    """ONE fused matmul+ring builder for both output layouts.

    ``align=0, with_ag=True``: the all-reduce form — after the fused
    reduce-scatter, block (my+1) is complete and an all-gather ring
    replicates the full product (out: (n, m_blk, n_out)).
    ``align=-1, with_ag=False``: the owner-aligned reduce-scatter form —
    block ``my`` completes locally and IS the output (out: (m_blk,
    n_out)), the Megatron-style row-parallel GEMM.  Same VMEM staging,
    just-in-time block compute, and DMA/semaphore discipline either way
    (a fix to one schedule is a fix to both).
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)

    def kernel(a_ref, b_ref, out_ref, a_vmem, b_vmem, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems, *maybe_ag_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        # operands land in VMEM first: compute dereferences need VMEM
        # residency on hardware (ANY-space inputs may live in HBM)
        ca = pltpu.make_async_copy(a_ref, a_vmem, local_sem)
        ca.start()
        ca.wait()
        cb = pltpu.make_async_copy(b_ref, b_vmem, local_sem)
        cb.start()
        cb.wait()

        def partial(b):
            """Local partial product for row-block b (MXU work)."""
            rows = a_vmem[pl.ds(b * m_blk, m_blk), :]
            return jnp.dot(rows, b_vmem[...],
                           preferred_element_type=jnp.float32
                           ).astype(acc_ref.dtype)

        # the block sent at step 0 is needed first
        first = lax.rem(my + align + n, n)
        acc_ref[pl.ds(first, 1)] = partial(first)[None]

        def rs_step(k, carry):
            send_idx = lax.rem(my + align - k + 2 * n, n)
            recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[send_idx], dst_ref=recv_ref.at[k],
                send_sem=send_sem, recv_sem=rs_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            # the overlap: THIS matmul runs while the DMA is in flight
            mine = partial(recv_idx)
            rdma.wait()
            acc_ref[pl.ds(recv_idx, 1)] = \
                mine[None] + recv_ref[pl.ds(k, 1)]
            return carry

        lax.fori_loop(0, n - 1, rs_step, 0)
        done = lax.rem(my + align + 1 + n, n)
        if with_ag:
            cp = pltpu.make_async_copy(acc_ref.at[done],
                                       out_ref.at[done], local_sem)
            cp.start()
            cp.wait()
            _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                      out_ref=out_ref, send_sem=send_sem,
                      ag_sems=maybe_ag_sems[0])
        else:
            cp = pltpu.make_async_copy(acc_ref.at[done], out_ref,
                                       local_sem)
            cp.start()
            cp.wait()

    out_shape = (n, m_blk, n_out) if with_ag else (m_blk, n_out)
    scratch = [pltpu.VMEM((n * m_blk, k_loc), jnp.dtype(dtype_str)),
               pltpu.VMEM((k_loc, n_out), jnp.dtype(dtype_str)),
               pltpu.VMEM((n, m_blk, n_out), jnp.dtype(dtype_str)),
               pltpu.VMEM((n - 1, m_blk, n_out), jnp.dtype(dtype_str)),
               pltpu.SemaphoreType.DMA(()),
               pltpu.SemaphoreType.DMA(()),
               pltpu.SemaphoreType.DMA((n - 1,))]
    if with_ag:
        scratch.append(pltpu.SemaphoreType.DMA((n - 1,)))

    def call(a, b):   # a: (n*m_blk, k_loc), b: (k_loc, n_out)
        kw = {}
        cp = cparams(cid)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(out_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=scratch,
            interpret=interpret,
            **kw,
        )(a, b)

    return call


@functools.lru_cache(maxsize=256)
def _jit_matmul_reduce_scatter(mesh, axis: str, m: int, k_loc: int,
                               n_out: int, dtype_str: str,
                               interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    m_blk = -(-m // n)
    m_pad = m_blk * n
    inner = _build_fused_matmul(n, axis, m_blk, k_loc, n_out,
                                dtype_str, interpret, align=-1,
                                with_ag=False, cid=11)

    def body(a, b):   # a: (1, m, k_loc), b: (1, k_loc, n_out)
        a2 = a[0]
        if m_pad != m:
            a2 = jnp.pad(a2, ((0, m_pad - m), (0, 0)))
        return inner(a2, b[0])[None]     # (1, m_blk, n_out)

    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(axis), P(axis)),
                             out_specs=P(axis), check_vma=False))


def matmul_reduce_scatter(a, b, mesh, axis: str,
                          interpret: bool = True):
    """Row-parallel fused GEMM: device i returns row-block i of
    Σ_j A_j @ B_j (global shape (n, M/n-padded, N) sharded on the mesh
    axis) — the reduce-scatter half of :func:`matmul_allreduce`, the
    Megatron-style TP output projection.  M is padded to a multiple of
    n; callers slice the tail block if M % n != 0."""
    a, b, n, m, k_loc, n_out, dtype = _prep_operands(a, b, mesh, axis)
    if n == 1:
        return (a[0] @ b[0])[None]
    return _jit_matmul_reduce_scatter(mesh, axis, m, k_loc, n_out,
                                      str(dtype), interpret)(a, b)


@functools.lru_cache(maxsize=256)
def _jit_matmul_allreduce(mesh, axis: str, m: int, k_loc: int,
                          n_out: int, dtype_str: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    m_blk = -(-m // n)
    m_pad = m_blk * n
    inner = _build_fused_matmul(n, axis, m_blk, k_loc, n_out,
                                dtype_str, interpret, align=0,
                                with_ag=True, cid=10)

    def body(a, b):   # a: (1, m, k_loc), b: (1, k_loc, n_out)
        a2 = a[0]
        if m_pad != m:
            a2 = jnp.pad(a2, ((0, m_pad - m), (0, 0)))
        out = inner(a2, b[0])            # (n, m_blk, n_out)
        return out.reshape(m_pad, n_out)[:m]

    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(axis), P(axis)),
                             out_specs=P(), check_vma=False))


def matmul_allreduce(a, b, mesh, axis: str, interpret: bool = True):
    """Contraction-sharded matmul with fused ring reduction.

    ``a``: (n, M, K/n) — per-device A shards on the leading mesh axis;
    ``b``: (n, K/n, N) — matching contraction shards.  Returns the
    replicated (M, N) product Σ_i A_i @ B_i, computed by the fused
    just-in-time-block ring (compute overlaps each step's DMA).
    """
    a, b, n, m, k_loc, n_out, dtype = _prep_operands(a, b, mesh, axis)
    if n == 1:
        return a[0] @ b[0]
    return _jit_matmul_allreduce(mesh, axis, m, k_loc, n_out,
                                 str(dtype), interpret)(a, b)
