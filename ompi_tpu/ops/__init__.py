"""TPU compute kernels (Pallas) for the framework's hot ops.

The reference keeps its SIMD reduction kernels in an MCA op component
(``ompi/mca/op/avx/op_avx_functions.c`` — AVX2/AVX-512 sum/min/max/...);
the TPU analog is Pallas kernels driving the VPU (elementwise reductions)
and MXU (attention blocks).  The MCA ``op`` framework
(``ompi_tpu/mca/op/``) selects these when running on a TPU backend and
falls back to plain XLA (jnp) elsewhere, mirroring the reference's
runtime CPU-capability dispatch (``op_avx_component.c``).
"""
from ompi_tpu.ops.pallas_reduce import (  # noqa: F401
    combine2,
    reduce_stack,
    supported_ops,
)
