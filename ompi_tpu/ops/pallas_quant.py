"""Pallas block-quantization kernels — the device half of coll/quant.

The EQuARX-style codec (PAPERS.md, arxiv 2506.17615) at kernel
granularity: one *block* is one 128-lane row of the flattened operand,
and each block carries an f32 scale ``max(|x|)/127`` next to its int8
payload.  Three entry points, shape-polymorphic like
``ops/pallas_reduce.py``:

``encode_int8(x)``
    Flatten + pad ``x`` to ``(rows, 128)`` lanes and quantize through a
    tiled VMEM kernel: per-row absmax → scale, round-half-even to int8.
    Returns ``(q (rows,128) int8, scales (rows,1) f32)``.

``dequant_accumulate(q, s)``
    The dequant-accumulate reduction: ``sum_i q[i] * s[i]`` over a
    ``(k, rows, 128)`` stack of quantized contributions in ONE VMEM
    pass — the post-allgather fold of the block-quantized allreduce,
    fused so no dequantized intermediate ever lands in HBM (the
    ``reduce_stack`` shape pointed at quantized operands).

``decode_int8(q, s)``
    Elementwise ``q * s`` back to f32 (the allgather decode).

Mosaic tiling discipline (pallas_guide.md): int8 blocks keep the
(32, 128) minimum tile; per-row scales are produced LANE-PADDED to
``(rows, 128)`` inside the kernel (a trailing dim of 1 is not a legal
Mosaic tile) and sliced to ``(rows, 1)`` at the XLA level, so only 4
bytes per BLOCK — not per element — ride any gather.  Off-TPU the
kernels run in interpreter mode so the CPU test mesh exercises the
same code path, and ``interpret`` is an explicit static jit key so the
AOT gate can force real Mosaic lowering (the ``combine2`` contract).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128          # one codec block = one lane row
ROW_TILE = 256       # 256x128 f32 tile = 128 KiB per operand in VMEM


def _interpret() -> bool:
    from ompi_tpu.base.jaxenv import pallas_interpret_default

    return pallas_interpret_default()


def _pad_rows(flat, rows_mult: int):
    """Flatten → (rows, LANES) padded so rows % rows_mult == 0."""
    n = flat.size
    rows = max(1, -(-n // LANES))
    rows = -(-rows // rows_mult) * rows_mult
    pad = rows * LANES - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, LANES), rows


def _enc_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:]
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)      # (tile, 1)
    inv = jnp.where(amax > 0, 127.0 / amax, jnp.zeros_like(amax))
    # round-half-even (jnp.round == np.rint): DETERMINISTIC, so every
    # rank/process encodes identical bytes — the cross-process
    # determinism the host codec tests pin (stochastic rounding would
    # trade that away for unbiasedness)
    q_ref[:] = jnp.round(x * inv).astype(jnp.int8)
    # scale lane-padded to the full row (trailing dim 1 is not a legal
    # Mosaic tile); the XLA caller slices [:, :1]
    s_ref[:] = jnp.broadcast_to(amax * (1.0 / 127.0), x.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def encode_int8(x, *, interpret=None):
    """Block-quantize ``x`` → ``(q (rows,128) int8, s (rows,1) f32)``.

    ``interpret`` is a static jit-cache-key ingredient (see
    ``pallas_reduce.combine2``): None resolves from the backend at
    trace time; an explicit value (the AOT Mosaic gate passes False)
    always wins."""
    flat = x.reshape(-1).astype(jnp.float32)
    x2, rows = _pad_rows(flat, ROW_TILE)
    grid = (rows // ROW_TILE,)
    spec = pl.BlockSpec((ROW_TILE, LANES), lambda i: (i, 0))
    q, s = pl.pallas_call(
        _enc_kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.float32)),
        grid=grid, in_specs=[spec], out_specs=(spec, spec),
        interpret=_interpret() if interpret is None else interpret,
    )(x2)
    return q, s[:, :1]


def _deq_acc_kernel(k, q_ref, s_ref, o_ref):
    acc = q_ref[0].astype(jnp.float32) * s_ref[0]
    for i in range(1, k):   # k is static — unrolled VPU chain
        acc = acc + q_ref[i].astype(jnp.float32) * s_ref[i]
    o_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def dequant_accumulate(q, s, *, interpret=None):
    """``sum_i q[i] * s[i]`` over a (k, rows, 128) quantized stack in
    one streaming VMEM pass; ``s`` is (k, rows, 1) per-block scales
    (broadcast to lane width at the XLA level so the kernel's tiles
    stay legal)."""
    k, rows = q.shape[0], q.shape[1]
    if k == 1:
        return decode_int8(q[0], s[0], interpret=interpret)
    sb = jnp.broadcast_to(s, (k, rows, LANES))
    # row tile sized so k int8 + k f32 operand tiles + out fit VMEM
    tile = max(8, min(ROW_TILE, 4096 // k * 8))
    pad = (-rows) % tile
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0)))
        sb = jnp.pad(sb, ((0, 0), (0, pad), (0, 0)))
    rows_p = rows + pad
    out = pl.pallas_call(
        functools.partial(_deq_acc_kernel, k),
        out_shape=jax.ShapeDtypeStruct((rows_p, LANES), jnp.float32),
        grid=(rows_p // tile,),
        in_specs=[pl.BlockSpec((k, tile, LANES), lambda i: (0, i, 0)),
                  pl.BlockSpec((k, tile, LANES), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((tile, LANES), lambda i: (i, 0)),
        interpret=_interpret() if interpret is None else interpret,
    )(q, sb)
    return out[:rows]


def _dec_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_int8(q, s, *, interpret=None):
    """Elementwise dequant of one (rows, 128) quantized block array
    (``s`` is (rows, 1)); leading axes fold into rows first."""
    lead = q.shape[:-2]
    rows = 1
    for d in q.shape[:-1]:
        rows *= d
    q2 = q.reshape(rows, LANES)
    s2 = jnp.broadcast_to(s, q.shape[:-1] + (LANES,)).reshape(rows, LANES)
    tile = ROW_TILE
    pad = (-rows) % tile
    if pad:
        q2 = jnp.pad(q2, ((0, pad), (0, 0)))
        s2 = jnp.pad(s2, ((0, pad), (0, 0)))
    rows_p = rows + pad
    spec = pl.BlockSpec((tile, LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _dec_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, LANES), jnp.float32),
        grid=(rows_p // tile,), in_specs=[spec, spec], out_specs=spec,
        interpret=_interpret() if interpret is None else interpret,
    )(q2, s2)
    return out[:rows].reshape(lead + (q.shape[-2], LANES))
