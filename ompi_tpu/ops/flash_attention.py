"""Pallas flash-attention block kernel for ring attention.

Ring attention (``ompi_tpu/parallel/model.py``) rotates K/V shards around
the sequence-parallel mesh axis with ``ppermute`` and, per step, combines
one K/V block into a running (max, numerator, denominator) softmax state.
That per-step block combine is the FLOPs hot spot — two MXU matmuls plus
the online-softmax rescale — and is what this kernel fuses: one VMEM
round-trip instead of the five separate HBM-materialised intermediates
(scores, max, probs, weighted-V, rescales) the jnp version produces.

The ring/communication structure stays at the JAX level (XLA schedules the
ICI ppermute); only the local block math drops into Pallas — the same
split the reference makes between its coll algorithms (schedules) and its
op kernels (``ompi/mca/op/avx``).

Grid: (batch*heads, q row tiles).  K/V blocks ride whole in VMEM (s_kv up
to a few thousand at 128-lane alignment); scores compute at f32 on the
MXU via ``preferred_element_type``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Q_TILE = 256


def _interpret() -> bool:
    from ompi_tpu.base.jaxenv import pallas_interpret_default

    return pallas_interpret_default()


def _block_kernel(scale, biased, *refs):
    if biased:
        bias_ref, q_ref, k_ref, v_ref, m_ref, num_ref, den_ref, \
            mo_ref, numo_ref, deno_ref = refs
    else:
        q_ref, k_ref, v_ref, m_ref, num_ref, den_ref, \
            mo_ref, numo_ref, deno_ref = refs
        bias_ref = None
    q = q_ref[0]            # (tq, d)
    k = k_ref[0]            # (skv, d)
    v = v_ref[0]
    m = m_ref[0]            # (tq, LANES) broadcast copies, col 0 is live
    num = num_ref[0]        # (tq, d)
    den = den_ref[0]        # (tq, LANES)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (tq, skv)
    if bias_ref is not None:
        # additive bias per (q row, kv col): -inf entries mask (causal,
        # padding), finite entries shift (ALiBi) — fused into the same
        # VMEM pass
        s = s + bias_ref[...]
    blk_max = jnp.max(s, axis=-1, keepdims=True)         # (tq, 1)
    new_m = jnp.maximum(m[:, :1], blk_max)               # (tq, 1)
    c = jnp.exp(m[:, :1] - new_m)                        # (tq, 1)
    p = jnp.exp(s - new_m)                               # (tq, skv)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (tq, d)
    numo_ref[0] = (num * c + pv).astype(num.dtype)
    deno_ref[0] = (den[:, :1] * c + jnp.sum(p, axis=-1, keepdims=True)
                   ) * jnp.ones_like(den)
    mo_ref[0] = new_m * jnp.ones_like(m)


def _update_jnp(q, k_blk, v_blk, m, num, den, bias=None):
    """The same block update in plain jnp — autodiff reference and the
    source of the custom-VJP backward (recompute, flash-style: nothing
    beyond the step inputs is saved).  ``bias`` (sq, skv) is added to
    the scores (broadcast over batch/heads)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
    if bias is not None:
        s = s + bias
    new_m = jnp.maximum(m, s.max(axis=-1))
    c = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m[..., None])
    new_num = num * c[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    new_den = den * c + p.sum(axis=-1)
    return new_m, new_num, new_den


@jax.custom_vjp
def flash_block_update(q, k_blk, v_blk, m, num, den):
    """One online-softmax accumulation step against a K/V block.

    q: (b, h, sq, d); k_blk/v_blk: (b, h, skv, d); m/den: (b, h, sq);
    num: (b, h, sq, d).  Returns updated (m, num, den).  Forward runs the
    fused Pallas kernel; reverse-mode recomputes through the jnp block
    math (the Pallas custom-VJP pattern — kernels have no autodiff rule).
    """
    return _update_pallas(q, k_blk, v_blk, m, num, den)


def _flash_fwd(q, k_blk, v_blk, m, num, den):
    return (_update_pallas(q, k_blk, v_blk, m, num, den),
            (q, k_blk, v_blk, m, num, den))


def _flash_bwd(res, ct):
    _, vjp = jax.vjp(_update_jnp, *res)
    return vjp(ct)


flash_block_update.defvjp(_flash_fwd, _flash_bwd)


@jax.custom_vjp
def flash_block_update_biased(q, k_blk, v_blk, m, num, den, bias):
    """Block update with an additive score bias (sq, skv): -inf masks
    (causal ring attention, padding), finite shifts (ALiBi).  Same
    fused Pallas forward; reverse recomputes through the jnp twin."""
    return _update_pallas(q, k_blk, v_blk, m, num, den, bias=bias)


def _flash_biased_fwd(q, k_blk, v_blk, m, num, den, bias):
    return (_update_pallas(q, k_blk, v_blk, m, num, den, bias=bias),
            (q, k_blk, v_blk, m, num, den, bias))


# _flash_bwd handles both residual arities: jax.vjp adapts to the
# 6- (unbiased) vs 7-element (biased) tuple
flash_block_update_biased.defvjp(_flash_biased_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _update_pallas(q, k_blk, v_blk, m, num, den, bias=None, *,
                   interpret=None):
    # ``interpret`` is part of the jit cache key: an explicit False (the
    # AOT Mosaic gate) can never be served a cached interpreter trace,
    # and vice versa.  None = resolve from the backend at trace time.
    b, h, sq, d = q.shape
    skv = k_blk.shape[2]
    scale = 1.0 / math.sqrt(d)
    bh = b * h
    tq = min(Q_TILE, sq)
    if sq % tq:
        tq = sq  # ragged seq tiles: fall back to one tile per (b, h)

    lanes = 128
    qf = q.reshape(bh, sq, d)
    kf = k_blk.reshape(bh, skv, d)
    vf = v_blk.reshape(bh, skv, d)
    # carry scalars per row are lane-broadcast so refs stay (…, 128)-tiled
    mf = jnp.broadcast_to(m.reshape(bh, sq)[..., None], (bh, sq, lanes))
    nf = num.reshape(bh, sq, d)
    df = jnp.broadcast_to(den.reshape(bh, sq)[..., None], (bh, sq, lanes))

    grid = (bh, sq // tq)
    row = lambda i, j: (i, j, 0)
    blk = lambda i, j: (i, 0, 0)
    q_spec = pl.BlockSpec((1, tq, d), row)
    kv_spec = pl.BlockSpec((1, skv, d), blk)
    s_spec = pl.BlockSpec((1, tq, lanes), row)

    biased = bias is not None
    in_specs = [q_spec, kv_spec, kv_spec, s_spec, q_spec, s_spec]
    operands = [qf, kf, vf, mf.astype(jnp.float32), nf,
                df.astype(jnp.float32)]
    if biased:
        # (sq, skv) shared across (b, h): one q-tile row slice per step
        in_specs.insert(0, pl.BlockSpec((tq, skv), lambda i, j: (j, 0)))
        operands.insert(0, bias.astype(jnp.float32))

    mo, numo, deno = pl.pallas_call(
        functools.partial(_block_kernel, scale, biased),
        out_shape=(
            jax.ShapeDtypeStruct(mf.shape, jnp.float32),
            jax.ShapeDtypeStruct(nf.shape, nf.dtype),
            jax.ShapeDtypeStruct(df.shape, jnp.float32),
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=(s_spec, q_spec, s_spec),
        interpret=_interpret() if interpret is None else interpret,
    )(*operands)

    return (mo[..., 0].reshape(b, h, sq).astype(m.dtype),
            numo.reshape(num.shape),
            deno[..., 0].reshape(b, h, sq).astype(den.dtype))
