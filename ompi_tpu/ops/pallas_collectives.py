"""Pallas remote-DMA ring collectives — the explicit ICI transport path.

The reference's lowest layer is an explicit transport with RDMA verbs
(``/root/reference/opal/mca/btl/btl.h:949`` put / ``:987`` get); its
collectives are schedules of those verbs over a topology.  coll/xla rides
XLA's compiler-scheduled collectives instead — this module is the
explicit-schedule twin: ring algorithms written directly against the ICI
with ``pltpu.make_async_remote_copy`` (one-sided remote DMA + send/recv
semaphore discipline), the TPU-native form of the reference's
``btl_put``-based ring (``coll_base_allreduce.c:341``).

Why have both: XLA's collectives are near-optimal for the standard cases,
but an explicit schedule composes with compute inside ONE kernel (overlap
of reduce + forward per ring step, custom quantized wire formats, PP
activation handoff fused into the stage loop) — the knob the reference
keeps by owning its transport.  SURVEY.md §2.6 maps this slot to "Pallas
remote DMA".

All kernels are SPMD under ``shard_map`` over a 1-D mesh axis; payloads
are split into per-device ring blocks outside the kernel.  They run in
interpreter mode on a virtual CPU mesh (tests) and compile for real
multi-chip ICI unchanged.

Two accumulator regimes (round 4):

* **fused** — the whole (n, blk) accumulator lives in VMEM; lowest
  latency, bounded by VMEM size (the component's ``vmem_max_bytes``).
* **segmented** — the accumulator and receive buffers are HBM-resident
  and only a bounded double-buffered window (2 × ``seg`` elements)
  streams through VMEM for the reduction, so payload size is bounded by
  HBM, not VMEM — the explicit-DMA twin of the reference's *segmented*
  ring (``coll_base_allreduce.c:618`` ring_segmented) whose entire point
  is pipelining large payloads through bounded buffers.

The **bidirectional** ring variant splits the payload in half and runs
mirrored clockwise/counter-clockwise schedules concurrently — ICI links
are duplex, so both directions carry traffic every step and the bisection
time halves (the reference gets the same effect from its two-proc-group
rdb/segmented hybrids; here it is one kernel).

**Torus schedules** (``all_reduce_torus``) ride sub-rings of a
linearized (n0, n1) mesh — reduce-scatter along one torus dimension,
all-reduce along the other on 1/n0-sized blocks, all-gather back — so
every link of BOTH dimensions carries traffic and per-phase step count
follows the axis lengths, not their product (coll/han's hierarchical
composition, expressed as explicit DMA).  The **explicit all-to-all**
(pairwise exchange over direct per-peer DMAs, ``coll_base_alltoall.c``)
is the SP/MoE dispatch primitive.

Reduction is parameterized (sum/max/min/prod) — one op argument, the
same way ``ompi_op``'s function table parameterizes the reference's ring
(``coll_base_allreduce.c:341`` takes any ``ompi_op_t``).
"""
from __future__ import annotations

import functools

import numpy as np

def _op_fn(jnp, op: str):
    """Elementwise fold for a ring-kernel reduction op name."""
    try:
        return {
            "sum": lambda a, b: a + b,
            "max": jnp.maximum,
            "min": jnp.minimum,
            "prod": lambda a, b: a * b,
        }[op]
    except KeyError:
        raise ValueError(
            f"unsupported ring reduction {op!r}: one of sum/max/min/prod")


def _mods():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, jnp, lax, pl, pltpu


def _ring_kernels(n: int, axis: str, interpret: bool):
    """Build the kernel-constructor namespace once per (n, axis, mode)."""
    jax, jnp, lax, pl, pltpu = _mods()

    def compiler_params(collective_id: int):
        # distinct collective_id per kernel family: concurrent pallas
        # collectives must not share barrier/semaphore identity on real
        # hardware (Mosaic matches collective instances by this id)
        if interpret:
            return None
        return pltpu.CompilerParams(has_side_effects=True,
                                    collective_id=collective_id)

    def barrier(*peers):
        """Kernel-entry barrier with every DMA peer: signal each peer's
        barrier semaphore (allocated per collective_id), wait until all
        of them have signalled ours.  On hardware no remote DMA may
        depart before the receiver's kernel is live — its recv
        semaphores and scratch only exist then (Mosaic refuses a
        collective_id kernel without this).  The interpreter emulates
        remote copies as per-op rendezvous, so it needs no barrier and
        does not model one."""
        if interpret:
            return
        bsem = pltpu.get_barrier_semaphore()
        for p in peers:
            pltpu.semaphore_signal(
                bsem, 1, device_id=p,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(bsem, len(peers))

    return jax, jnp, lax, pl, pltpu, compiler_params, barrier


def _ring_fn(lax, axis: str, sub):
    """(ring position, position->logical-device-id map) for this device.

    ``sub=None``: the ring IS the whole 1-D mesh (identity map).
    ``sub=(n0, n1, j)``: the mesh linearizes a (n0, n1) torus row-major
    and the ring rides axis j — position p maps to device p*n1+i1
    (column ring pinned at my i1) or i0*n1+p (row ring pinned at my
    i0).  Index arithmetic on scalar LOGICAL ids keeps every kernel
    interpreter-runnable (the Pallas interpreter has no multi-axis DMA
    mesh support) and lowers identically on hardware, where ICI routes
    non-neighbor ids."""
    my = lax.axis_index(axis)
    if sub is None:
        return my, (lambda p: p)
    n0, n1, j = sub
    i0 = my // n1
    i1 = lax.rem(my, n1)
    if j == 0:
        return i0, (lambda p: p * n1 + i1)
    return i1, (lambda p: i0 * n1 + p)


@functools.lru_cache(maxsize=64)
def _build_right_permute(n: int, axis: str, shape, dtype_str: str,
                         interpret: bool):
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    def call(x):
        kw = {}
        cp = cparams(1)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_gather(n: int, axis: str, blk_shape, dtype_str: str,
                      interpret: bool, sub=None, cid: int = 2):
    """Ring all-gather: n-1 steps, each forwarding the freshest block to
    the right neighbor (``jax docs distributed`` canonical schedule; the
    reference's ``coll_base_allgather.c`` ring)."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, local_sem, send_sem, recv_sems):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        barrier(right, dev(lax.rem(my - 1 + n, n)))
        cp = pltpu.make_async_copy(x_ref, out_ref.at[my], local_sem)
        cp.start()
        cp.wait()

        def step(k, carry):
            slot = lax.rem(my - k + n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[slot],
                send_sem=send_sem, recv_sem=recv_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # send done + block (my-k-1) landed from the left
            return carry

        lax.fori_loop(0, n - 1, step, 0)

    def call(x):
        kw = {}
        cp = cparams(cid)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_gather_bidi(n: int, axis: str, blk_shape, dtype_str: str,
                           interpret: bool, sub=None):
    """Bidirectional ring all-gather: every step sends the freshest
    right-going block right AND the freshest left-going block left, so
    both directions of each duplex ICI link carry payload and the
    schedule finishes in ceil((n-1)/2) steps instead of n-1 — the
    duplex trick of ``_build_all_reduce`` ("bidi") applied to the
    gather schedule (reference menu analog:
    ``coll_base_allgather.c`` neighbor-exchange, which also halves the
    step count by pairing directions).

    Right-going chain at step k ships block (my-k) and lands block
    (my-1-k) from the left; left-going ships (my+k) and lands
    (my+1+k).  r_cnt = n//2 right deliveries + l_cnt = n-1-n//2 left
    deliveries cover the n-1 remote blocks exactly once.  The paired
    steps run in a fori_loop (constant kernel size in n, like the
    unidirectional builder); only the at-most-one direction-lopsided
    tail step (even n: r_cnt = l_cnt + 1) is emitted separately.
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(
        n, axis, interpret)
    r_cnt = n // 2
    l_cnt = n - 1 - r_cnt
    paired = min(r_cnt, l_cnt)

    def kernel(x_ref, out_ref, local_sem, send_r, send_l, recv_r,
               recv_l):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        left = dev(lax.rem(my - 1 + n, n))
        barrier(right, left)
        cp = pltpu.make_async_copy(x_ref, out_ref.at[my], local_sem)
        cp.start()
        cp.wait()

        def rdma_right(k):
            slot = lax.rem(my - k + n, n)
            return pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[slot],
                send_sem=send_r, recv_sem=recv_r.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)

        def step(k, carry):
            r = rdma_right(k)
            slot_l = lax.rem(my + k, n)
            ld = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot_l], dst_ref=out_ref.at[slot_l],
                send_sem=send_l, recv_sem=recv_l.at[k],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            r.start()    # both directions in flight together —
            ld.start()   # that simultaneity IS the bandwidth win
            r.wait()
            ld.wait()
            return carry

        lax.fori_loop(0, paired, step, 0)
        if r_cnt > paired:           # even n: one right-only tail step
            r = rdma_right(paired)
            r.start()
            r.wait()

    def call(x):
        kw = {}
        cp = cparams(16)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((max(1, r_cnt),)),
                            pltpu.SemaphoreType.DMA((max(1, l_cnt),))],
            interpret=interpret,
            **kw,
        )(x)

    return call


def _rs_phase(lax, pl, pltpu, *, n, my, right, acc_ref, recv_ref,
              send_sem, rs_sems, align: int, fold, stage_ref=None,
              decode=None):
    """The shared ring reduce-scatter phase: n-1 steps, each sending the
    running partial for block (my+align-k) to the right neighbor and
    fusing the incoming partial into block (my+align-1-k).  After the
    loop, block (my+align+1) % n is fully reduced on this device —
    align=0 for the all-reduce schedule (owner my+1), align=-1 for
    owner-aligned reduce-scatter (owner my).  ONE copy of the DMA /
    semaphore / accumulate discipline, shared by every ring kernel.
    ``fold`` is the elementwise reduction.

    ``stage_ref``/``decode`` are the wire-codec hooks (wire16): when
    given, each outgoing partial is written through ``stage_ref`` (a
    single (rows, 128) VMEM buffer at the WIRE dtype — safe to reuse
    per step because the wait covers send completion) and incoming
    partials pass through ``decode`` before the fold.

    Refs are block-leading 3-D — acc (n, rows, 128), recv (n-1, rows,
    128) — so every slice rides the UNTILED leading dim: Mosaic tiles
    the trailing (rows, 128) pair and rejects row-slices of a tiled
    dim ("slice must be aligned to tiling (8)"), which a flat (n, blk)
    layout would need."""

    def rs_step(k, carry):
        send_idx = lax.rem(my + align - k + 2 * n, n)
        recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
        if stage_ref is None:
            src = acc_ref.at[send_idx]
        else:
            stage_ref[...] = acc_ref[send_idx].astype(stage_ref.dtype)
            src = stage_ref
        rdma = pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=recv_ref.at[k],
            send_sem=send_sem, recv_sem=rs_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # my partial for block recv_idx arrived
        part = recv_ref[k]
        if decode is not None:
            part = decode(part)
        acc_ref[recv_idx] = fold(acc_ref[recv_idx], part)
        return carry

    lax.fori_loop(0, n - 1, rs_step, 0)
    return lax.rem(my + align + 1 + n, n)   # the completed block


@functools.lru_cache(maxsize=64)
def _build_all_reduce(n: int, axis: str, rows: int, dtype_str: str,
                      interpret: bool, op: str = "sum", sub=None):
    """Ring all-reduce: n-1 reduce-scatter steps with the fold fused
    into the ring loop, then n-1 all-gather steps — one kernel, the
    explicit-DMA form of ``coll_base_allreduce.c:341``.

    Per-device payload is pre-shaped to (n, rows, 128) — lane-major
    block-leading layout so all slicing rides the untiled leading dim
    (see ``_rs_phase``).  Distinct recv slots per step (scratch
    (n-1, rows, 128)) make the schedule self-synchronizing: no slot is
    ever reused, so the send/recv semaphore pair is the only ordering
    needed (the capacity/backpressure dance of a 2-slot scheme is
    deliberately traded for VMEM).
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems, ag_sems):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        barrier(right, dev(lax.rem(my - 1 + n, n)))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=0,
                         fold=fold)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()

        _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                  out_ref=out_ref, send_sem=send_sem, ag_sems=ag_sems)

    def call(x):  # x: (n, rows, 128) per device
        kw = {}
        cp = cparams(3)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, rows, 128), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, rows, 128),
                                       jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, rows, 128),
                                       jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_reduce_wire16(n: int, axis: str, rows: int,
                             interpret: bool, op: str = "sum"):
    """Wire-compressed ring all-reduce: f32 accumulation on-chip, bf16
    bytes on the ICI — each ring step casts the outgoing partial to
    bf16 (one VPU pass), DMAs HALF the bytes, and folds the incoming
    partial back at f32.  Per-step wire time halves; each partial takes
    one bf16 rounding per hop, so the ABSOLUTE error is bounded by
    ~n · 2^-8 · max|partial| (relative error is unbounded where the
    true sum cancels toward zero — inherent to any compressed
    reduction, and why this is opt-in) — the gradient-allreduce
    compression trade every
    DDP-style framework offers, possible here precisely because the
    transport is owned (the reference's ``ompi_op`` contract is
    full-precision end-to-end; an MPI layer cannot change the wire
    format without owning the btl).

    The completed block is rounded to bf16 BEFORE the all-gather phase,
    so every rank returns bit-identical results (MPI allreduce
    reproducibility contract) at bf16 value precision.  Output is bf16
    (n, rows, 128); the wrapper upcasts."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, stage_ref, recv_ref,
               local_sem, send_sem, rs_sems, ag_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        # the shared ring discipline with the bf16 wire codec hooks
        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=0,
                         fold=fold, stage_ref=stage_ref,
                         decode=lambda p: p.astype(jnp.float32))
        # round the completed block ONCE and circulate the rounded
        # value: every rank ends bit-identical
        stage_ref[...] = acc_ref[done].astype(jnp.bfloat16)
        cp2 = pltpu.make_async_copy(stage_ref, out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()
        _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                  out_ref=out_ref, send_sem=send_sem, ag_sems=ag_sems)

    def call(x):  # x: (n, rows, 128) f32 -> (n, rows, 128) bf16
        kw = {}
        cp = cparams(15)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, rows, 128),
                                           "bfloat16"),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, rows, 128),
                                       jnp.dtype("float32")),
                            pltpu.VMEM((rows, 128),
                                       jnp.dtype("bfloat16")),
                            pltpu.VMEM((n - 1, rows, 128),
                                       jnp.dtype("bfloat16")),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_reduce_scatter(n: int, axis: str, rows: int, dtype_str: str,
                          interpret: bool, op: str = "sum",
                          sub=None, wire16: bool = False,
                          cid: int = 4):
    """Ring reduce-scatter: n-1 steps, fold fused into the ring;
    device i ends owning fully-reduced block i (the first half of
    ``coll_base_allreduce.c:341``'s ring, block-owner aligned).
    Blocks are (rows, 128) — see ``_rs_phase`` on the layout.

    ``wire16`` (f32 payloads): partials cross the wire at bf16 through
    ``_rs_phase``'s codec hooks, folds stay f32, and — unlike the
    all-reduce twin — the owner's result needs no rounding pass: each
    block lives on exactly one rank, so full-f32 output is returned
    (absolute error ~n·2^-8·max|partial| from the wire roundings)."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems, *maybe_stage):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        barrier(right, dev(lax.rem(my - 1 + n, n)))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        # align=-1: the completed block is `my` — it IS my result
        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=-1,
                         fold=fold,
                         stage_ref=maybe_stage[0] if wire16 else None,
                         decode=(lambda p: p.astype(jnp.float32))
                         if wire16 else None)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref, local_sem)
        cp2.start()
        cp2.wait()

    def call(x):  # x: (n, rows, 128) per device -> (rows, 128)
        kw = {}
        cp = cparams(cid)
        if cp is not None:
            kw["compiler_params"] = cp
        dt = jnp.dtype(dtype_str)
        recv_dt = jnp.dtype("bfloat16") if wire16 else dt
        scratch = [pltpu.VMEM((n, rows, 128), dt),
                   pltpu.VMEM((n - 1, rows, 128), recv_dt),
                   pltpu.SemaphoreType.DMA(()),
                   pltpu.SemaphoreType.DMA(()),
                   pltpu.SemaphoreType.DMA((n - 1,))]
        if wire16:
            scratch.append(pltpu.VMEM((rows, 128), recv_dt))
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, 128), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=scratch,
            interpret=interpret,
            **kw,
        )(x)

    return call


def _ag_phase(lax, pl, pltpu, *, n, my, right, out_ref, send_sem,
              ag_sems):
    """The shared ring all-gather phase of the all-reduce kernels: n-1
    steps, each forwarding the freshest completed block (my+1-k) to the
    right neighbor in place on ``out_ref`` — pure DMA, no window."""

    def ag_step(k, carry):
        fwd = lax.rem(my + 1 - k + n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[fwd], dst_ref=out_ref.at[fwd],
            send_sem=send_sem, recv_sem=ag_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # completed block (my-k)%n landed from the left
        return carry

    lax.fori_loop(0, n - 1, ag_step, 0)


def _seg_fold_row(lax, pl, pltpu, *, acc_row, recv_row, nseg: int, va,
                  vb, load_sems, wb_sems, fold):
    """Fold one received HBM row into one accumulator row through the
    2-slot double-buffered VMEM window: while segment s reduces,
    segment s+1's loads are already in flight, and writebacks drain one
    segment behind.  Fully drained on return, so the window is
    immediately reusable (the bidi kernel folds both directions through
    one window).

    ``acc_row(s)`` / ``recv_row(s)`` hand back the (S, 128) ref of
    segment s — the caller owns the block/direction addressing, always
    through untiled leading dims (see ``_rs_phase`` on why)."""

    def start_load(s):
        slot = lax.rem(s, 2)
        pltpu.make_async_copy(acc_row(s), va.at[slot],
                              load_sems.at[slot, 0]).start()
        pltpu.make_async_copy(recv_row(s), vb.at[slot],
                              load_sems.at[slot, 1]).start()

    def wait_wb(slot, s_of_wb):
        # descriptor only carries the byte count to decrement
        pltpu.make_async_copy(va.at[slot], acc_row(s_of_wb),
                              wb_sems.at[slot]).wait()

    start_load(0)

    def seg_step(s, c):
        slot = lax.rem(s, 2)

        @pl.when(s + 1 < nseg)
        def _prefetch():
            @pl.when(s >= 1)
            def _drain_prev_wb():
                # slot 1-slot's writeback (segment s-1) must land
                # before its VMEM buffer is reloaded
                wait_wb(1 - slot, s - 1)
            start_load(s + 1)

        pltpu.make_async_copy(acc_row(s), va.at[slot],
                              load_sems.at[slot, 0]).wait()
        pltpu.make_async_copy(recv_row(s), vb.at[slot],
                              load_sems.at[slot, 1]).wait()
        va[slot] = fold(va[slot], vb[slot])
        pltpu.make_async_copy(va.at[slot], acc_row(s),
                              wb_sems.at[slot]).start()
        return c

    lax.fori_loop(0, nseg, seg_step, 0)
    # drain outstanding writebacks before this row is sent next step
    wait_wb(lax.rem(nseg - 1, 2), nseg - 1)
    if nseg >= 2:
        wait_wb(lax.rem(nseg - 2, 2), nseg - 2)


def _seg_rs_phase(lax, pl, pltpu, *, n, my, right, acc_ref, recv_ref,
                  send_sem, rs_sems, align: int, fold, nseg: int,
                  va, vb, load_sems, wb_sems):
    """Segmented twin of ``_rs_phase``: acc/recv live in HBM as
    (n, nseg, S, 128) / (n-1, nseg, S, 128); the fold streams through
    the bounded VMEM window (``_seg_fold_row``) — the bounded-buffer
    pipeline of the reference's segmented ring
    (``coll_base_allreduce.c:618``), which exists precisely so payload
    size is bounded by main memory, not the staging buffer."""

    def rs_step(k, carry):
        send_idx = lax.rem(my + align - k + 2 * n, n)
        recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc_ref.at[send_idx], dst_ref=recv_ref.at[k],
            send_sem=send_sem, recv_sem=rs_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # my partial for block recv_idx arrived (HBM)
        _seg_fold_row(lax, pl, pltpu,
                      acc_row=lambda s: acc_ref.at[recv_idx, s],
                      recv_row=lambda s: recv_ref.at[k, s],
                      nseg=nseg, va=va, vb=vb, load_sems=load_sems,
                      wb_sems=wb_sems, fold=fold)
        return carry

    lax.fori_loop(0, n - 1, rs_step, 0)
    return lax.rem(my + align + 1 + n, n)   # the completed block


@functools.lru_cache(maxsize=64)
def _build_all_reduce_seg(n: int, axis: str, nseg: int, srows: int,
                          dtype_str: str, interpret: bool,
                          op: str = "sum"):
    """Segmented ring all-reduce for large payloads: HBM-resident
    (n, nseg, S, 128) accumulator, bounded VMEM window, same ring
    schedule as the fused kernel.  The all-gather phase is pure
    HBM↔HBM remote DMA and needs no window at all."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref, va, vb,
               local_sem, send_sem, load_sems, wb_sems, rs_sems, ag_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _seg_rs_phase(
            lax, pl, pltpu, n=n, my=my, right=right, acc_ref=acc_ref,
            recv_ref=recv_ref, send_sem=send_sem, rs_sems=rs_sems,
            align=0, fold=fold, nseg=nseg,
            va=va, vb=vb, load_sems=load_sems, wb_sems=wb_sems)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()

        _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                  out_ref=out_ref, send_sem=send_sem, ag_sems=ag_sems)

    def call(x):  # x: (n, nseg, S, 128) per device
        kw = {}
        cp = cparams(5)
        if cp is not None:
            kw["compiler_params"] = cp
        dt = jnp.dtype(dtype_str)
        # acc/recv are HBM-resident ring state: Mosaic only allocates
        # VMEM/SMEM/semaphore scratch, so HBM buffers ride as extra
        # ANY-space outputs (discarded) — same kernel arg order
        out, _, _ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((n, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n - 1, nseg, srows, 128),
                                            dtype_str)),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[pltpu.VMEM((2, srows, 128), dt),
                            pltpu.VMEM((2, srows, 128), dt),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)
        return out

    return call


@functools.lru_cache(maxsize=64)
def _build_reduce_scatter_seg(n: int, axis: str, nseg: int, srows: int,
                              dtype_str: str, interpret: bool,
                              op: str = "sum"):
    """Segmented ring reduce-scatter (owner-aligned, align=-1) — the
    large-payload twin of ``_build_reduce_scatter``."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref, va, vb,
               local_sem, send_sem, load_sems, wb_sems, rs_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _seg_rs_phase(
            lax, pl, pltpu, n=n, my=my, right=right, acc_ref=acc_ref,
            recv_ref=recv_ref, send_sem=send_sem, rs_sems=rs_sems,
            align=-1, fold=fold, nseg=nseg,
            va=va, vb=vb, load_sems=load_sems, wb_sems=wb_sems)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref, local_sem)
        cp2.start()
        cp2.wait()

    def call(x):  # x: (n, nseg, S, 128) per device -> (nseg, S, 128)
        kw = {}
        cp = cparams(6)
        if cp is not None:
            kw["compiler_params"] = cp
        dt = jnp.dtype(dtype_str)
        # HBM ring state as extra ANY outputs (see _build_all_reduce_seg)
        out, _, _ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n - 1, nseg, srows, 128),
                                            dtype_str)),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[pltpu.VMEM((2, srows, 128), dt),
                            pltpu.VMEM((2, srows, 128), dt),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)
        return out

    return call


def _bidi_done_and_ag(lax, pl, pltpu, *, n, my, right, left,
                      acc_ref, out_ref, local_sem, send_cw_sem,
                      send_ccw_sem, ag_cw_sems, ag_ccw_sems):
    """Shared tail of the bidirectional all-reduce kernels: copy each
    direction's completed half-block out, then run the mirrored
    all-gather rings (both duplex directions busy every step).

    Refs are direction-leading — acc/out (n, 2, ..., S, 128), dir 0 =
    clockwise half, dir 1 = counter-clockwise — so the per-direction
    slices ride untiled leading dims (see ``_rs_phase``)."""
    done_cw = lax.rem(my + 1, n)
    done_ccw = lax.rem(my - 1 + n, n)
    c1 = pltpu.make_async_copy(acc_ref.at[done_cw, 0],
                               out_ref.at[done_cw, 0], local_sem)
    c1.start()
    c1.wait()
    c2 = pltpu.make_async_copy(acc_ref.at[done_ccw, 1],
                               out_ref.at[done_ccw, 1], local_sem)
    c2.start()
    c2.wait()

    def ag_step(k, carry):
        f_cw = lax.rem(my + 1 - k + n, n)
        f_ccw = lax.rem(my - 1 + k + n, n)
        d_cw = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[f_cw, 0],
            dst_ref=out_ref.at[f_cw, 0],
            send_sem=send_cw_sem, recv_sem=ag_cw_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        d_ccw = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[f_ccw, 1],
            dst_ref=out_ref.at[f_ccw, 1],
            send_sem=send_ccw_sem, recv_sem=ag_ccw_sems.at[k],
            device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        d_cw.start()
        d_ccw.start()
        d_cw.wait()
        d_ccw.wait()
        return carry

    lax.fori_loop(0, n - 1, ag_step, 0)


@functools.lru_cache(maxsize=64)
def _build_all_reduce_seg_bidi(n: int, axis: str, nseg: int, srows: int,
                               dtype_str: str, interpret: bool,
                               op: str = "sum"):
    """Segmented AND bidirectional ring all-reduce — the large-payload
    champion: the (n, 2, nseg, S, 128) payload is HBM-resident, dir 0
    rides the clockwise ring and dir 1 the counter-clockwise ring
    concurrently (both duplex ICI directions carry a half-payload every
    step), and each direction's fold streams through ONE shared
    double-buffered VMEM window (``_seg_fold_row`` drains fully between
    directions, so the window is reused — folds are VPU-sequential
    anyway; it is the DMAs that overlap).
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_cw, recv_ccw, va, vb,
               local_sem, send_cw_sem, send_ccw_sem, load_sems, wb_sems,
               rs_cw_sems, rs_ccw_sems, ag_cw_sems, ag_ccw_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        left = lax.rem(my - 1 + n, n)
        barrier(right, left)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        def rs_step(k, carry):
            s_cw = lax.rem(my - k + 2 * n, n)
            r_cw = lax.rem(my - 1 - k + 2 * n, n)
            s_ccw = lax.rem(my + k, n)
            r_ccw = lax.rem(my + 1 + k, n)
            d_cw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_cw, 0],
                dst_ref=recv_cw.at[k],
                send_sem=send_cw_sem, recv_sem=rs_cw_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_ccw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_ccw, 1],
                dst_ref=recv_ccw.at[k],
                send_sem=send_ccw_sem, recv_sem=rs_ccw_sems.at[k],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_cw.start()
            d_ccw.start()          # both directions' DMAs in flight
            d_cw.wait()
            _seg_fold_row(lax, pl, pltpu,
                          acc_row=lambda s: acc_ref.at[r_cw, 0, s],
                          recv_row=lambda s: recv_cw.at[k, s],
                          nseg=nseg, va=va, vb=vb,
                          load_sems=load_sems, wb_sems=wb_sems,
                          fold=fold)
            d_ccw.wait()
            _seg_fold_row(lax, pl, pltpu,
                          acc_row=lambda s: acc_ref.at[r_ccw, 1, s],
                          recv_row=lambda s: recv_ccw.at[k, s],
                          nseg=nseg, va=va, vb=vb,
                          load_sems=load_sems, wb_sems=wb_sems,
                          fold=fold)
            return carry

        lax.fori_loop(0, n - 1, rs_step, 0)
        _bidi_done_and_ag(lax, pl, pltpu, n=n, my=my, right=right,
                          left=left, acc_ref=acc_ref,
                          out_ref=out_ref, local_sem=local_sem,
                          send_cw_sem=send_cw_sem,
                          send_ccw_sem=send_ccw_sem,
                          ag_cw_sems=ag_cw_sems, ag_ccw_sems=ag_ccw_sems)

    def call(x):  # x: (n, 2, nseg, S, 128) per device
        kw = {}
        cp = cparams(12)
        if cp is not None:
            kw["compiler_params"] = cp
        dt = jnp.dtype(dtype_str)
        # HBM ring state as extra ANY outputs (see _build_all_reduce_seg)
        out, _, _, _ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((n, 2, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n, 2, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n - 1, nseg, srows, 128),
                                            dtype_str),
                       jax.ShapeDtypeStruct((n - 1, nseg, srows, 128),
                                            dtype_str)),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[pltpu.VMEM((2, srows, 128), dt),
                            pltpu.VMEM((2, srows, 128), dt),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)
        return out

    return call


@functools.lru_cache(maxsize=64)
def _build_all_reduce_bidi(n: int, axis: str, rows: int, dtype_str: str,
                           interpret: bool, op: str = "sum"):
    """Bidirectional ring all-reduce: the (n, 2, rows, 128) payload is
    split into a clockwise half (dir 0, sent rightward) and a
    counter-clockwise half (dir 1, sent leftward), with mirrored
    reduce-scatter + all-gather schedules running concurrently.  ICI
    links are duplex, so both directions carry a half-payload every
    step — per-step wire time halves vs the unidirectional ring.

    CW completes block (my+1)'s dir-0 half; CCW completes block
    (my-1)'s dir-1 half; the mirrored all-gather phases circulate both.
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_cw, recv_ccw,
               local_sem, send_cw_sem, send_ccw_sem,
               rs_cw_sems, rs_ccw_sems, ag_cw_sems, ag_ccw_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        left = lax.rem(my - 1 + n, n)
        barrier(right, left)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        def rs_step(k, carry):
            s_cw = lax.rem(my - k + 2 * n, n)
            r_cw = lax.rem(my - 1 - k + 2 * n, n)
            s_ccw = lax.rem(my + k, n)
            r_ccw = lax.rem(my + 1 + k, n)
            d_cw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_cw, 0],
                dst_ref=recv_cw.at[k],
                send_sem=send_cw_sem, recv_sem=rs_cw_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_ccw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_ccw, 1],
                dst_ref=recv_ccw.at[k],
                send_sem=send_ccw_sem, recv_sem=rs_ccw_sems.at[k],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_cw.start()
            d_ccw.start()
            d_cw.wait()
            d_ccw.wait()
            acc_ref[r_cw, 0] = fold(acc_ref[r_cw, 0], recv_cw[k])
            acc_ref[r_ccw, 1] = fold(acc_ref[r_ccw, 1], recv_ccw[k])
            return carry

        lax.fori_loop(0, n - 1, rs_step, 0)
        _bidi_done_and_ag(lax, pl, pltpu, n=n, my=my, right=right,
                          left=left, acc_ref=acc_ref,
                          out_ref=out_ref, local_sem=local_sem,
                          send_cw_sem=send_cw_sem,
                          send_ccw_sem=send_ccw_sem,
                          ag_cw_sems=ag_cw_sems, ag_ccw_sems=ag_ccw_sems)

    def call(x):  # x: (n, 2, rows, 128) per device
        kw = {}
        cp = cparams(7)
        if cp is not None:
            kw["compiler_params"] = cp
        dt = jnp.dtype(dtype_str)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, 2, rows, 128),
                                           dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, 2, rows, 128), dt),
                            pltpu.VMEM((n - 1, rows, 128), dt),
                            pltpu.VMEM((n - 1, rows, 128), dt),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_to_all(n: int, axis: str, blk_shape, dtype_str: str,
                      interpret: bool):
    """Explicit all-to-all: n-1 steps, at step k every device DMAs its
    block for the device k hops right DIRECTLY to that device (ICI
    routes non-neighbor transfers), landing in the sender's slot —
    the SP/MoE dispatch primitive (``lax.all_to_all`` twin;
    ``coll_base_alltoall.c`` pairwise-exchange algorithm, where step k
    pairs (i, i+k)).  Fully symmetric: one DMA per device per step.
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, local_sem, send_sem, recv_sems):
        my = lax.axis_index(axis)
        # pairwise exchange touches every peer: the entry barrier must
        # cover them all, not just ring neighbors
        barrier(*[lax.rem(my + k, n) for k in range(1, n)])
        cp = pltpu.make_async_copy(x_ref.at[my], out_ref.at[my],
                                   local_sem)
        cp.start()
        cp.wait()

        def step(k, carry):
            peer = lax.rem(my + k, n)     # send my block for `peer`
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[peer], dst_ref=out_ref.at[my],
                send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                device_id=peer,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # send done + block from (my-k) landed
            return carry

        lax.fori_loop(1, n, step, 0)

    def call(x):  # x: (n, *blk) per device -> (n, *blk) transposed
        kw = {}
        cp = cparams(9)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_to_all_v(n: int, axis: str, max_rows: int, width: int,
                        chunk: int, dtype_str: str, interpret: bool):
    """Ragged pairwise all-to-all — true alltoallv for MoE/EP dispatch
    (``coll_base_alltoall.c`` pairwise exchange with per-pair sizes).

    The per-pair row counts arrive as a runtime (n, n) int32 table in
    SMEM, so ONE compile serves every routing outcome — MoE re-routes
    every step, and a counts-specialized kernel would recompile per
    batch.  Each pair moves ceil(cnt/chunk) fixed-shape (chunk, W)
    DMAs: Mosaic needs static DMA shapes, but trip counts may be
    dynamic scalars — wasted wire is bounded by chunk-1 rows per pair,
    vs the padded ``all_to_all`` moving max_rows for every pair
    regardless of raggedness.

    Asymmetric counts mean send and receive chunk totals differ per
    device, so the send loop uses ``wait_send`` and a separate receive
    loop drains ``recv_sems`` by ``wait_recv`` — the split-phase form
    of the symmetric kernels' ``wait()``.
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    nchunks = _ragged_nchunks(max_rows, chunk, interpret)

    def kernel(counts_ref, x_ref, out_ref, local_sem, send_sem,
               recv_sems):
        my = lax.axis_index(axis)
        barrier(*[lax.rem(my + k, n) for k in range(1, n)])

        # local block: out[my] rows [:counts[my,my]] come from x[my]
        def local_chunk(c, carry):
            sl = pl.ds(c * chunk, chunk)
            cp = pltpu.make_async_copy(x_ref.at[my, sl],
                                       out_ref.at[my, sl], local_sem)
            cp.start()
            cp.wait()
            return carry

        lax.fori_loop(0, nchunks(counts_ref[my, my]), local_chunk, 0)

        def pair_step(k, carry):
            dst = lax.rem(my + k, n)
            src = lax.rem(my - k + n, n)

            def send_chunk(c, carry2):
                sl = pl.ds(c * chunk, chunk)
                rdma = pltpu.make_async_remote_copy(
                    src_ref=x_ref.at[dst, sl],
                    dst_ref=out_ref.at[my, sl],
                    send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                    device_id=dst,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                rdma.start()
                rdma.wait_send()
                return carry2

            lax.fori_loop(0, nchunks(counts_ref[my, dst]), send_chunk,
                          0, unroll=False)

            def recv_chunk(c, carry2):
                sl = pl.ds(c * chunk, chunk)
                # shape-only descriptor: wait_recv consumes exactly one
                # inbound (chunk, W) DMA's bytes from recv_sems[k-1]
                pltpu.make_async_remote_copy(
                    src_ref=out_ref.at[src, sl],
                    dst_ref=out_ref.at[src, sl],
                    send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                    device_id=src,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ).wait_recv()
                return carry2

            lax.fori_loop(0, nchunks(counts_ref[src, my]), recv_chunk,
                          0, unroll=False)
            return carry

        lax.fori_loop(1, n, pair_step, 0)

    def call(counts, x):  # counts: (n, n) i32; x: (n, max_rows, W)
        kw = {}
        cp = cparams(13)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, max_rows, width),
                                           dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(counts, x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_gather_v(n: int, axis: str, max_rows: int, width: int,
                        chunk: int, dtype_str: str, interpret: bool):
    """Ragged ring all-gather (true allgatherv): per-rank valid row
    counts arrive as a runtime (n,) int32 table, and each ring step
    forwards a block as ceil(count/chunk) fixed-shape (chunk, W) DMAs —
    wire bytes follow the raggedness instead of every block moving
    max_rows (``coll_base_allgatherv.c`` ring with per-peer counts).
    Same static-shape/dynamic-trip-count discipline as
    ``_build_all_to_all_v``; the interpreter runs the symmetric
    full-block schedule (its DMA emulation needs matched op counts) and
    the ragged trip counts are AOT-compile-proven."""
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    nchunks = _ragged_nchunks(max_rows, chunk, interpret)

    def kernel(counts_ref, x_ref, out_ref, local_sem, send_sem,
               recv_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        left = lax.rem(my - 1 + n, n)
        barrier(right, left)

        def local_chunk(c, carry):
            sl = pl.ds(c * chunk, chunk)
            cp = pltpu.make_async_copy(x_ref.at[sl],
                                       out_ref.at[my, sl], local_sem)
            cp.start()
            cp.wait()
            return carry

        lax.fori_loop(0, nchunks(counts_ref[my]), local_chunk, 0)

        def step(k, carry):
            s_send = lax.rem(my - k + 1 + 2 * n, n)   # freshest block
            s_recv = lax.rem(my - k + 2 * n, n)       # lands from left

            def send_chunk(c, c2):
                sl = pl.ds(c * chunk, chunk)
                rdma = pltpu.make_async_remote_copy(
                    src_ref=out_ref.at[s_send, sl],
                    dst_ref=out_ref.at[s_send, sl],
                    send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                rdma.start()
                rdma.wait_send()
                return c2

            lax.fori_loop(0, nchunks(counts_ref[s_send]), send_chunk,
                          0, unroll=False)

            def recv_chunk(c, c2):
                sl = pl.ds(c * chunk, chunk)
                pltpu.make_async_remote_copy(
                    src_ref=out_ref.at[s_recv, sl],
                    dst_ref=out_ref.at[s_recv, sl],
                    send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                    device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ).wait_recv()
                return c2

            lax.fori_loop(0, nchunks(counts_ref[s_recv]), recv_chunk,
                          0, unroll=False)
            return carry

        lax.fori_loop(1, n, step, 0)

    def call(counts, x):  # counts: (n,) i32; x: (max_rows, W)
        kw = {}
        cp = cparams(14)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, max_rows, width),
                                           dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(counts, x)

    return call


@functools.lru_cache(maxsize=64)
def _build_bcast(n: int, axis: str, nseg: int, srows: int,
                 dtype_str: str, interpret: bool):
    """Pipelined segmented ring broadcast — the "clamped conveyor": root
    streams S segments rightward and every hop forwards segment s one
    wave after receiving it, so all links are busy simultaneously and
    total time ≈ (S + n - 2) segment-hops instead of (n-1) full-payload
    hops — the explicit-DMA form of the reference's pipeline bcast
    (``coll_base_bcast.c`` pipeline/chain algorithms).

    The schedule is fully symmetric (SPMD-clean, no masked DMAs — a
    masked send would desync the per-op DMA rendezvous the interpreter
    emulates remote copies with): at wave j, the device at ring position
    r = (my-root) mod n forwards slot ``clamp(j-r, 0, S-1)``.  Below the
    clamp the payload is not-yet-valid filler that a valid write always
    overwrites before the receiver forwards that slot (position r first
    forwards slot s at wave s+r, having received the valid copy at wave
    s+r-1); above the clamp it is a benign same-bytes re-send.  The last
    device aims its writes at a sink row (``out[S]``) so the conveyor
    never races root's source rows.
    """
    jax, jnp, lax, pl, pltpu, cparams, barrier = _ring_kernels(n, axis, interpret)
    waves = nseg + n - 2

    # root arrives as a runtime SMEM scalar, not a cache key: the kernel
    # only uses it through rel = (my - root) mod n, so one compile
    # serves every root (round-robin-root workloads stay cache-hot)
    def kernel(root_ref, x_ref, out_ref, local_sem, send_sem, recv_sem):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        barrier(right, lax.rem(my - 1 + n, n))
        rel = lax.rem(my - root_ref[0] + n, n)
        # everyone seeds out with its local buffer: root's rows are the
        # payload, other devices' rows are pre-valid filler the conveyor
        # overwrites in time
        cp = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(0, nseg)],
                                   local_sem)
        cp.start()
        cp.wait()

        def wave(j, carry):
            slot = lax.clamp(0, j - rel, nseg - 1)
            # the ring's last device (rel n-1) writes into root's sink
            # row: root's real rows are the source of truth
            dst = lax.select(rel == n - 1, nseg, slot)
            # ONE recv semaphore for all waves (semaphore memory is a
            # small fixed chip resource — per-wave semaphores would
            # scale with payload size): safe because each sender's
            # wave-j+1 DMA starts only after its wave-j wait(), so
            # signals arrive in wave order and every wave moves the
            # same byte count; run-ahead just accumulates counts
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[dst],
                send_sem=send_sem, recv_sem=recv_sem,
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()
            return carry

        lax.fori_loop(0, waves, wave, 0)

    def call(root, x):  # x: (nseg, S, 128) per device; root's rows back
        kw = {}
        cp = cparams(8)
        if cp is not None:
            kw["compiler_params"] = cp
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((nseg + 1, srows, 128),
                                           dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
            **kw,
        )(root, x)
        return out[:nseg]

    return call


# -- public entry points (shard_map wrappers) ----------------------------
#
# Each wrapper resolves to a CACHED jitted program (lru keyed on mesh /
# shape / dtype / op / variant): building jax.jit around a fresh closure
# per call would retrace and recompile every time, turning each
# collective into compile time (jax.sharding.Mesh is hashable and
# equality-stable, so it can key the cache directly).

@functools.lru_cache(maxsize=256)
def _jit_right_permute(mesh, axis: str, payload_shape, dtype_str: str,
                       interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    fn = _build_right_permute(n, axis, (1,) + payload_shape, dtype_str,
                              interpret)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def right_permute(x, mesh, axis: str, interpret: bool = True):
    """Rotate the leading (rank) axis by +1 via neighbor remote DMA —
    the PP activation-handoff primitive (``lax.ppermute`` twin)."""
    if mesh.shape[axis] == 1:
        return x
    return _jit_right_permute(mesh, axis, tuple(x.shape[1:]),
                              str(x.dtype), interpret)(x)


@functools.lru_cache(maxsize=256)
def _jit_all_gather(mesh, axis: str, blk_shape, dtype_str: str,
                    interpret: bool, variant: str = "ring"):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    build = (_build_all_gather_bidi if variant == "bidi"
             else _build_all_gather)
    inner = build(n, axis, blk_shape, dtype_str, interpret)

    def body(t):                       # t: (1, *S)
        return inner(t[0])             # (n, *S)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))


def all_gather(x, mesh, axis: str, interpret: bool = True,
               variant: str = "ring"):
    """(n, *S) sharded -> (n, *S) replicated via the DMA ring.

    ``variant="bidi"`` runs the bidirectional schedule (both ICI
    directions per step, ceil((n-1)/2) steps); n<=2 degenerates to the
    plain ring (one remote block — nothing to pair)."""
    n = mesh.shape[axis]
    if n == 1:
        return x
    if n <= 2:
        variant = "ring"
    return _jit_all_gather(mesh, axis, tuple(x.shape[1:]), str(x.dtype),
                           interpret, variant)(x)


#: default VMEM window (elements) for the segmented kernels when the
#: caller does not size it
_DEFAULT_SEG_ELEMS = 131072


def _ragged_nchunks(max_rows: int, chunk: int, interpret: bool):
    """Trip-count rule shared by the ragged (counts-driven) kernels.

    The interpreter emulates every remote DMA as a cross-device
    rendezvous, so per-device op counts must be SYMMETRIC there:
    interpret mode always moves whole blocks (validating addressing
    and semaphore schedules); the dynamic ragged trip counts are a
    hardware feature, compile-proven by the AOT gate."""
    full = (max_rows + chunk - 1) // chunk

    def nchunks(rows):
        if interpret:
            return full
        return (rows + chunk - 1) // chunk

    return nchunks


def _rows_for(elems: int) -> int:
    """128-lane rows covering ``elems`` elements (≥1).  Every kernel
    payload is shaped (..., rows, 128): Mosaic tiles the trailing two
    dims, so the lane dim must be exactly 128 and all block/segment
    indexing rides untiled leading dims."""
    return max(1, -(-elems // 128))


def _seg_rows(rows: int, seg_elems: int | None) -> tuple[int, int]:
    """(window rows, padded block rows): the VMEM window is
    ``seg_elems`` rounded down to whole 128-lane rows, never exceeding
    the ring block; the block is rounded up to a whole number of
    windows."""
    srows = max(1, min((seg_elems or _DEFAULT_SEG_ELEMS) // 128, rows))
    return srows, -(-rows // srows) * srows


def _pad_value(op: str, dtype) -> float | int:
    """Neutral element used to pad the flattened payload to n equal ring
    blocks — must not perturb the fold, for any dtype (±inf is not a
    valid neutral for integers: use the dtype's extrema there).

    ml_dtypes types (bfloat16, fp8) report numpy kind 'V': treat
    anything np.finfo understands as floating (ml_dtypes registers its
    finfo), only genuinely integer kinds go to np.iinfo — the old
    kind=='f' test sent bf16 to iinfo and max/min bf16 rings raised
    "Invalid integer data type 'V'" (found by the round-5 randomized
    kernel sweep)."""
    dtype = np.dtype(dtype)
    if op == "sum":
        return 0
    if op == "prod":
        return 1
    if dtype.kind in "iu":
        lim = np.iinfo(dtype)
    else:
        import ml_dtypes

        lim = (np.finfo(dtype) if dtype.kind == "f"
               else ml_dtypes.finfo(dtype))
    return lim.min if op == "max" else lim.max


@functools.lru_cache(maxsize=256)
def _jit_reduce_scatter(mesh, axis: str, payload_shape, dtype_str: str,
                        op: str, interpret: bool, variant: str,
                        seg_elems):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    blk = int(np.prod(payload_shape)) if payload_shape else 1
    rows = _rows_for(blk)
    if variant == "seg":
        srows, rows = _seg_rows(rows, seg_elems)
        inner = _build_reduce_scatter_seg(n, axis, rows // srows, srows,
                                          dtype_str, interpret, op)
        shape_in = (n, rows // srows, srows, 128)
    elif variant == "wire16":
        if dtype_str not in ("float32", "f32"):
            raise ValueError(
                "wire16 compresses float32 payloads to bf16 wire "
                f"bytes; got dtype {dtype_str}")
        inner = _build_reduce_scatter(n, axis, rows, dtype_str,
                                      interpret, op, wire16=True)
        shape_in = (n, rows, 128)
    else:
        inner = _build_reduce_scatter(n, axis, rows, dtype_str,
                                      interpret, op)
        shape_in = (n, rows, 128)
    padded = rows * 128

    def body(t):                       # t: (1, n, *S)
        r2 = t[0].reshape(n, blk)
        if padded != blk:
            r2 = jnp.pad(r2, ((0, 0), (0, padded - blk)),
                         constant_values=_pad_value(op, dtype_str))
        out = inner(r2.reshape(shape_in))
        return out.reshape(-1)[:blk].reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def reduce_scatter(x, mesh, axis: str, op: str = "sum",
                   interpret: bool = True, variant: str = "fused",
                   seg_elems: int | None = None):
    """(n, n, *S) sharded on the leading rank axis -> (n, *S) sharded:
    rank i receives the reduction of everyone's block i via the DMA
    ring.  ``variant='seg'`` uses the HBM-resident segmented kernel
    (window of ``seg_elems``) for payloads too large for VMEM."""
    payload_shape = tuple(x.shape[2:])
    if mesh.shape[axis] == 1:
        return x.reshape((1,) + payload_shape)
    return _jit_reduce_scatter(mesh, axis, payload_shape, str(x.dtype),
                               op, interpret, variant, seg_elems)(x)


def reduce_scatter_sum(x, mesh, axis: str, interpret: bool = True):
    return reduce_scatter(x, mesh, axis, "sum", interpret)


@functools.lru_cache(maxsize=256)
def _jit_all_reduce(mesh, axis: str, payload_shape, dtype_str: str,
                    op: str, interpret: bool, variant: str, seg_elems):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    blk = -(-size // n)                # ceil
    rows = _rows_for(blk)
    if variant == "seg":
        srows, rows = _seg_rows(rows, seg_elems)
        inner = _build_all_reduce_seg(n, axis, rows // srows, srows,
                                      dtype_str, interpret, op)
        shape_in = (n, rows // srows, srows, 128)
    elif variant == "seg_bidi":
        hrows = -(-rows // 2)
        srows, hrows = _seg_rows(hrows, seg_elems)
        rows = 2 * hrows
        inner = _build_all_reduce_seg_bidi(n, axis, hrows // srows,
                                           srows, dtype_str, interpret,
                                           op)
        shape_in = (n, 2, hrows // srows, srows, 128)
    elif variant == "bidi":
        hrows = -(-rows // 2)          # even row split per direction
        rows = 2 * hrows
        inner = _build_all_reduce_bidi(n, axis, hrows, dtype_str,
                                       interpret, op)
        shape_in = (n, 2, hrows, 128)
    elif variant == "wire16":
        if dtype_str not in ("float32", "f32"):
            raise ValueError(
                "wire16 compresses float32 payloads to bf16 wire "
                f"bytes; got dtype {dtype_str}")
        raw = _build_all_reduce_wire16(n, axis, rows, interpret, op)
        inner = (lambda t: raw(t).astype("float32"))
        shape_in = (n, rows, 128)
    else:
        inner = _build_all_reduce(n, axis, rows, dtype_str, interpret,
                                  op)
        shape_in = (n, rows, 128)
    padded = rows * 128 * n

    def body(t):                       # t: (1, *S)
        flat = t.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size),
                           constant_values=_pad_value(op, dtype_str))
        out = inner(flat.reshape(shape_in))
        return out.reshape(-1)[:size].reshape(payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))


def all_reduce(x, mesh, axis: str, op: str = "sum",
               interpret: bool = True, variant: str = "fused",
               seg_elems: int | None = None):
    """(n, *S) sharded -> (*S) replicated reduction via a ring kernel.

    The per-rank payload is flattened and neutrally-padded to n equal
    ring blocks outside the kernel (XLA fuses the pad/reshape into the
    surrounding program).  Variants:

    * ``'fused'``    — whole accumulator in VMEM (lowest latency, small).
    * ``'seg'``      — HBM accumulator + bounded VMEM window of
      ``seg_elems`` (large payloads; `coll_base_allreduce.c:618` twin).
    * ``'bidi'``     — both ICI directions carry half the payload each
      step (duplex links; halves per-step wire time).  VMEM-bounded.
    * ``'seg_bidi'`` — both at once: HBM-resident halves ride both
      directions concurrently, folds stream through the shared window
      (the large-payload duplex champion).
    * ``'wire16'``   — f32 accumulation, bf16 wire bytes: each step
      casts the outgoing partial to bf16 (half the ICI time) and folds
      at f32.  Results are bit-identical on every rank at bf16 value
      precision; absolute error ≤ ~n·2^-8·max|partial| (relative error
      unbounded under cancellation) — the opt-in gradient-compression
      trade; f32 payloads only.
    """
    payload_shape = tuple(x.shape[1:])
    if mesh.shape[axis] == 1:
        return x.reshape(payload_shape)
    return _jit_all_reduce(mesh, axis, payload_shape, str(x.dtype), op,
                           interpret, variant, seg_elems)(x)


def all_reduce_sum(x, mesh, axis: str, interpret: bool = True):
    return all_reduce(x, mesh, axis, "sum", interpret)


@functools.lru_cache(maxsize=256)
def _jit_all_to_all(mesh, axis: str, blk_shape, dtype_str: str,
                    interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    inner = _build_all_to_all(n, axis, blk_shape, dtype_str, interpret)

    def body(t):                       # t: (1, n, *S)
        return inner(t[0])[None]       # (1, n, *S): row = my received

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def all_to_all(x, mesh, axis: str, interpret: bool = True):
    """(n, n, *S) sharded on the leading rank axis: rank i's block j
    moves to rank j's slot i (``x[i, j] -> out[j, i]``, the coll/xla
    ``alltoall_array`` convention) via direct per-peer remote DMA."""
    n = mesh.shape[axis]
    if x.ndim < 2 or x.shape[0] != n or x.shape[1] != n:
        # the kernel indexes n blocks per rank: anything else would be
        # an out-of-bounds remote DMA, not a reshape-able layout
        raise ValueError(
            f"all_to_all needs a ({n}, {n}, *S) array on this mesh, "
            f"got {tuple(x.shape)}")
    if n == 1:
        return x
    return _jit_all_to_all(mesh, axis, tuple(x.shape[2:]), str(x.dtype),
                           interpret)(x)


@functools.lru_cache(maxsize=256)
def _jit_all_gather_v(mesh, axis: str, max_rows: int, width: int,
                      chunk: int, dtype_str: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    inner = _build_all_gather_v(n, axis, max_rows, width, chunk,
                                dtype_str, interpret)

    def body(c, t):                    # c: (n,) replicated; t: (1, R, W)
        return inner(c, t[0])          # (n, R, W) replicated

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P(axis)),
                             out_specs=P(), check_vma=False))


def all_gather_v(x, counts, mesh, axis: str, chunk_rows: int = 8,
                 interpret: bool = True):
    """Ragged all-gather (true allgatherv): ``x`` is (n, R, W) sharded
    on the leading rank axis — rank i's block carries ``counts[i]``
    valid rows (≤ R) — and every rank receives (n, R, W) with
    ``out[i, :counts[i]]`` valid.  ``counts`` is a runtime operand:
    one compile serves every raggedness.  Wire bytes per block are
    ceil(count/chunk_rows)*chunk_rows rows where the padded all_gather
    always moves R.  W must be a multiple of 128 lanes."""
    jax, jnp, lax, pl, pltpu = _mods()

    n = mesh.shape[axis]
    if x.ndim != 3 or x.shape[0] != n:
        raise ValueError(
            f"all_gather_v needs a ({n}, R, W) array on this mesh, "
            f"got {tuple(x.shape)}")
    if x.shape[2] % 128 != 0:
        raise ValueError(
            f"all_gather_v row width must be a multiple of 128 lanes, "
            f"got {x.shape[2]} (pad the feature dim)")
    if n == 1:
        return x
    chunk_rows = int(chunk_rows)
    R = int(x.shape[1])
    # clamp to the block size (see all_to_all_v: an oversized count
    # means out-of-bounds remote DMA on hardware)
    counts = jnp.clip(jnp.asarray(counts, jnp.int32), 0, R)
    if counts.shape != (n,):
        raise ValueError(
            f"all_gather_v needs ({n},) counts, got "
            f"{tuple(counts.shape)}")
    if R == 0 or x.shape[2] == 0:
        # zero-row / zero-width slab: every count clamps to 0 valid
        # rows, so the gather is a no-op.  Return without building a
        # kernel — an empty block has no (chunk, W) window to slice
        # (interpret-mode DMA discharge rejects the slice statically)
        return x
    Rp = -(-R // chunk_rows) * chunk_rows
    if Rp != R:
        x = jnp.pad(x, ((0, 0), (0, Rp - R), (0, 0)))
    fn = _jit_all_gather_v(mesh, axis, Rp, int(x.shape[2]), chunk_rows,
                           str(x.dtype), interpret)
    out = fn(counts, x)
    return out[:, :R] if Rp != R else out


@functools.lru_cache(maxsize=256)
def _jit_all_to_all_v(mesh, axis: str, max_rows: int, width: int,
                      chunk: int, dtype_str: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    inner = _build_all_to_all_v(n, axis, max_rows, width, chunk,
                                dtype_str, interpret)

    def body(c, t):                    # c: (n, n) replicated; t: (1, n, R, W)
        return inner(c, t[0])[None]

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P(axis)),
                             out_specs=P(axis), check_vma=False))


def all_to_all_v(x, counts, mesh, axis: str, chunk_rows: int = 8,
                 interpret: bool = True):
    """Ragged all-to-all (true alltoallv): ``x`` is (n, n, R, W)
    sharded on the leading rank axis — rank i's block j carries
    ``counts[i, j]`` valid rows (≤ R) for rank j — and rank j receives
    them in ``out[j, i, :counts[i, j]]`` (the ``alltoall_array``
    row-is-my-received convention).  Rows past the count are
    unspecified.

    ``counts`` is a runtime (n, n) int32 operand, NOT a compile-time
    constant: one compiled program serves every MoE routing outcome.
    Wire bytes per pair are ceil(count/chunk_rows)*chunk_rows rows —
    ≤1.2x the ideal ragged byte count for real dispatch sizes, where
    the padded ``all_to_all`` moves the full R regardless.  W must be
    a multiple of 128 lanes (MoE hidden dims are)."""
    jax, jnp, lax, pl, pltpu = _mods()

    n = mesh.shape[axis]
    if x.ndim != 4 or x.shape[0] != n or x.shape[1] != n:
        raise ValueError(
            f"all_to_all_v needs a ({n}, {n}, R, W) array on this "
            f"mesh, got {tuple(x.shape)}")
    if x.shape[3] % 128 != 0:
        raise ValueError(
            f"all_to_all_v row width must be a multiple of 128 lanes, "
            f"got {x.shape[3]} (pad the feature dim)")
    if n == 1:
        return x
    chunk_rows = int(chunk_rows)
    R = int(x.shape[2])
    # clamp to the block size: a count beyond R would drive the chunk
    # loops past the block on hardware — out-of-bounds remote DMA into
    # the neighbor's adjacent slot, not an error
    counts = jnp.clip(jnp.asarray(counts, jnp.int32), 0, R)
    if counts.shape != (n, n):
        raise ValueError(
            f"all_to_all_v needs an ({n}, {n}) counts table, got "
            f"{tuple(counts.shape)}")
    if R == 0 or x.shape[3] == 0:
        # zero-row / zero-width slab: every count clamps to 0 valid
        # rows, so the exchange is a no-op.  Return without building a
        # kernel — an empty block has no (chunk, W) window to slice
        # (interpret-mode DMA discharge rejects the slice statically)
        return x
    # the kernel slices fixed (chunk, W) windows: the row dim must be a
    # whole number of chunks or the last window overruns the buffer
    Rp = -(-R // chunk_rows) * chunk_rows
    if Rp != R:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, Rp - R), (0, 0)))
    fn = _jit_all_to_all_v(mesh, axis, Rp, int(x.shape[3]), chunk_rows,
                           str(x.dtype), interpret)
    out = fn(counts, x)
    return out[:, :, :R] if Rp != R else out


@functools.lru_cache(maxsize=256)
def _jit_all_reduce_torus(mesh, axes, payload_shape, dtype_str: str,
                          op: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    a0, a1 = axes
    n0, n1 = mesh.shape[a0], mesh.shape[a1]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    rows0 = _rows_for(-(-size // n0))
    size1 = rows0 * 128                # phase-1 block, in elements
    rows1 = _rows_for(-(-size1 // n1))
    # the kernels run over a FLATTENED 1-D mesh with sub-ring index
    # arithmetic ((i0, i1) <-> i0*n1+i1): scalar LOGICAL device ids
    # stay interpreter-runnable and lower identically on hardware.
    # Transpose the device grid into ``axes`` order first — the sub-ring
    # arithmetic assumes a0-major linearization, and axes=("y","x") on
    # an ("x","y") mesh would otherwise still sum correctly but walk
    # non-neighbor ICI links
    flat_mesh = _torus_flat_mesh(mesh, a0, a1)
    rs0 = _build_reduce_scatter(n0, "_t", rows0, dtype_str, interpret,
                                op, sub=(n0, n1, 0))
    ar1 = _build_all_reduce(n1, "_t", rows1, dtype_str, interpret, op,
                            sub=(n0, n1, 1))
    ag0 = _build_all_gather(n0, "_t", (rows0, 128), dtype_str,
                            interpret, sub=(n0, n1, 0))
    pad = _pad_value(op, dtype_str)

    def body(t):                       # t: (1, *S)
        flat = t.reshape(-1)
        if rows0 * 128 * n0 != size:
            flat = jnp.pad(flat, (0, rows0 * 128 * n0 - size),
                           constant_values=pad)
        part = rs0(flat.reshape(n0, rows0, 128))  # (rows0, 128) over a0
        pflat = part.reshape(-1)
        if rows1 * 128 * n1 != size1:
            pflat = jnp.pad(pflat, (0, rows1 * 128 * n1 - size1),
                            constant_values=pad)
        red = ar1(pflat.reshape(n1, rows1, 128))  # over a1
        red = red.reshape(-1)[:size1].reshape(rows0, 128)
        full = ag0(red)                           # (n0, rows0, 128)
        return full.reshape(-1)[:size].reshape(payload_shape)

    return jax.jit(shard_map(body, mesh=flat_mesh, in_specs=P("_t"),
                             out_specs=P(), check_vma=False))


def all_reduce_torus(x, mesh, axes=("x", "y"), op: str = "sum",
                     interpret: bool = True):
    """(n0, n1, *S) sharded over both torus axes -> (*S) replicated
    reduction: reduce-scatter rings along ``axes[0]``, all-reduce rings
    along ``axes[1]`` on the scattered blocks, all-gather rings along
    ``axes[0]`` back.  Per-step wire time scales with the axis lengths
    (n0 + n1 ring steps on 1/n0-sized blocks) rather than one n0*n1
    ring, and every link of BOTH torus dimensions carries traffic — the
    2D schedule the reference reaches for with coll/han's hierarchical
    composition (``coll_han``), expressed as three explicit-DMA phases.
    """
    axes = tuple(axes)
    payload_shape = tuple(x.shape[2:])
    n0, n1 = mesh.shape[axes[0]], mesh.shape[axes[1]]
    if n0 == 1 or n1 == 1:
        # a degenerate torus axis is a plain 1-D ring (a single pod
        # row/column): the zero-sized (n-1, blk) recv scratch of an
        # n=1 sub-ring cannot build
        flat_mesh = _torus_flat_mesh(mesh, *axes)
        return all_reduce(x.reshape((n0 * n1,) + payload_shape),
                          flat_mesh, "_t", op, interpret)
    fn = _jit_all_reduce_torus(mesh, axes, payload_shape,
                               str(x.dtype), op, interpret)
    return fn(x.reshape((n0 * n1,) + payload_shape))


def _torus_flat_mesh(mesh, a0, a1):
    """Flatten the torus into a0-major order (see _jit_all_reduce_torus:
    the sub-ring arithmetic assumes (i0, i1) <-> i0*n1+i1, and the
    transpose keeps sub-rings on physical ICI neighbors)."""
    from jax.sharding import Mesh

    devs = np.asarray(mesh.devices)
    order = tuple(mesh.axis_names.index(a) for a in (a0, a1))
    devs = np.transpose(devs, order + tuple(
        i for i in range(devs.ndim) if i not in order))
    return Mesh(devs.reshape(-1), ("_t",))


@functools.lru_cache(maxsize=32)
def _jit_reduce_scatter_torus(mesh, axes, payload_shape, dtype_str: str,
                              op: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    a0, a1 = axes
    n0, n1 = mesh.shape[a0], mesh.shape[a1]
    N = n0 * n1
    blk = int(np.prod(payload_shape)) if payload_shape else 1
    rb = _rows_for(blk)
    flat_mesh = _torus_flat_mesh(mesh, a0, a1)
    # phase 1: scatter-reduce n0 super-blocks (n1 blocks each) down the
    # columns; phase 2: scatter-reduce the n1 surviving partials along
    # the row — device (i0, i1) ends with global block i0*n1+i1 fully
    # reduced.  Block boundaries stay row-aligned because each block is
    # padded to rb whole rows BEFORE the phase-1 stacking.
    # distinct collective_ids: two same-id kernels in one program
    # would share one Mosaic barrier semaphore, and a fast device
    # entering phase 2 could release a neighbor still at its phase-1
    # entry barrier (the hazard the _ring_kernels barrier comment
    # documents) — same discipline as _jit_all_reduce_torus's (4,3,2)
    rs0 = _build_reduce_scatter(n0, "_t", n1 * rb, dtype_str, interpret,
                                op, sub=(n0, n1, 0))
    rs1 = _build_reduce_scatter(n1, "_t", rb, dtype_str, interpret, op,
                                sub=(n0, n1, 1), cid=17)
    padded = rb * 128

    def body(t):                       # t: (1, N, *S)
        r2 = t[0].reshape(N, blk)
        if padded != blk:
            r2 = jnp.pad(r2, ((0, 0), (0, padded - blk)),
                         constant_values=_pad_value(op, dtype_str))
        p1 = rs0(r2.reshape(n0, n1 * rb, 128))   # (n1*rb, 128)
        p2 = rs1(p1.reshape(n1, rb, 128))        # (rb, 128)
        return p2.reshape(-1)[:blk].reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=flat_mesh, in_specs=P("_t"),
                             out_specs=P("_t"), check_vma=False))


def reduce_scatter_torus(x, mesh, axes=("x", "y"), op: str = "sum",
                         interpret: bool = True):
    """(N, N, *S) sharded -> (N, *S) sharded over the torus, N=n0*n1:
    two scatter-reduce phases (columns then rows), each ring walking
    physical ICI neighbors of its own torus dimension — the decomposed
    form of ``all_reduce_torus``'s first phase, for callers that want
    the scattered result (TP gradient buckets, han-style hierarchies).
    """
    axes = tuple(axes)
    payload_shape = tuple(x.shape[2:])
    n0, n1 = mesh.shape[axes[0]], mesh.shape[axes[1]]
    if n0 == 1 or n1 == 1:             # degenerate: plain 1-D ring
        flat_mesh = _torus_flat_mesh(mesh, *axes)
        return reduce_scatter(
            x.reshape((n0 * n1, n0 * n1) + payload_shape), flat_mesh,
            "_t", op, interpret)
    fn = _jit_reduce_scatter_torus(mesh, axes, payload_shape,
                                   str(x.dtype), op, interpret)
    return fn(x.reshape((n0 * n1, n0 * n1) + payload_shape))


@functools.lru_cache(maxsize=32)
def _jit_all_gather_torus(mesh, axes, blk_shape, dtype_str: str,
                          interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    a0, a1 = axes
    n0, n1 = mesh.shape[a0], mesh.shape[a1]
    N = n0 * n1
    blk = int(np.prod(blk_shape)) if blk_shape else 1
    rb = _rows_for(blk)
    flat_mesh = _torus_flat_mesh(mesh, a0, a1)
    # phase 1: gather the row's n1 blocks; phase 2: gather the n0
    # super-blocks down the column — (n0, n1) row-major == flat id
    # distinct collective_ids per phase (see _jit_reduce_scatter_torus)
    ag1 = _build_all_gather(n1, "_t", (rb, 128), dtype_str, interpret,
                            sub=(n0, n1, 1))
    ag0 = _build_all_gather(n0, "_t", (n1 * rb, 128), dtype_str,
                            interpret, sub=(n0, n1, 0), cid=18)

    def body(t):                       # t: (1, *S)
        flat = t[0].reshape(-1)
        if rb * 128 != blk:
            flat = jnp.pad(flat, (0, rb * 128 - blk))
        row = ag1(flat.reshape(rb, 128))          # (n1, rb, 128)
        full = ag0(row.reshape(n1 * rb, 128))     # (n0, n1*rb, 128)
        return full.reshape(N, rb * 128)[:, :blk].reshape(
            (N,) + blk_shape)

    return jax.jit(shard_map(body, mesh=flat_mesh, in_specs=P("_t"),
                             out_specs=P(), check_vma=False))


def all_gather_torus(x, mesh, axes=("x", "y"), interpret: bool = True):
    """(N, *S) sharded over the torus -> (N, *S) replicated: row rings
    then column rings, each on its own ICI dimension — (n1-1) + (n0-1)
    steps instead of the 1-D ring's N-1."""
    axes = tuple(axes)
    blk_shape = tuple(x.shape[1:])
    n0, n1 = mesh.shape[axes[0]], mesh.shape[axes[1]]
    if n0 == 1 or n1 == 1:
        flat_mesh = _torus_flat_mesh(mesh, *axes)
        return all_gather(x, flat_mesh, "_t", interpret)
    fn = _jit_all_gather_torus(mesh, axes, blk_shape, str(x.dtype),
                               interpret)
    return fn(x)


@functools.lru_cache(maxsize=256)
def _jit_bcast(mesh, axis: str, payload_shape, dtype_str: str,
               interpret: bool, seg_elems: int):
    jax, jnp, lax, pl, pltpu = _mods()
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    srows = max(1, min(seg_elems // 128, _rows_for(size)))
    nseg = -(-_rows_for(size) // srows)
    padded = nseg * srows * 128
    inner = _build_bcast(n, axis, nseg, srows, dtype_str, interpret)

    def body(r, t):                    # r: (1,) int32; t: (1, *S)
        flat = t.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        out = inner(r, flat.reshape(nseg, srows, 128))  # root's rows
        return out.reshape(-1)[:size].reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P(axis)),
                             out_specs=P(axis), check_vma=False))


def bcast(x, mesh, axis: str, root: int = 0, interpret: bool = True,
          seg_elems: int = 65536):
    """(n, *S) sharded -> (n, *S) with every row equal to root's row,
    via the pipelined segmented ring (time ≈ (S + n - 2) segment-hops).
    ``root`` is a runtime operand — every root shares one compile."""
    jax, jnp, lax, pl, pltpu = _mods()

    n = mesh.shape[axis]
    if n == 1:
        return x
    fn = _jit_bcast(mesh, axis, tuple(x.shape[1:]), str(x.dtype),
                    interpret, int(seg_elems))
    return fn(jnp.asarray([int(root) % n], dtype=jnp.int32), x)
