"""Pallas remote-DMA ring collectives — the explicit ICI transport path.

The reference's lowest layer is an explicit transport with RDMA verbs
(``/root/reference/opal/mca/btl/btl.h:949`` put / ``:987`` get); its
collectives are schedules of those verbs over a topology.  coll/xla rides
XLA's compiler-scheduled collectives instead — this module is the
explicit-schedule twin: ring algorithms written directly against the ICI
with ``pltpu.make_async_remote_copy`` (one-sided remote DMA + send/recv
semaphore discipline), the TPU-native form of the reference's
``btl_put``-based ring (``coll_base_allreduce.c:341``).

Why have both: XLA's collectives are near-optimal for the standard cases,
but an explicit schedule composes with compute inside ONE kernel (overlap
of reduce + forward per ring step, custom quantized wire formats, PP
activation handoff fused into the stage loop) — the knob the reference
keeps by owning its transport.  SURVEY.md §2.6 maps this slot to "Pallas
remote DMA".

All kernels are SPMD under ``shard_map`` over a 1-D mesh axis; payloads
are split into per-device ring blocks outside the kernel.  They run in
interpreter mode on a virtual CPU mesh (tests) and compile for real
multi-chip ICI unchanged.

Two accumulator regimes (round 4):

* **fused** — the whole (n, blk) accumulator lives in VMEM; lowest
  latency, bounded by VMEM size (the component's ``vmem_max_bytes``).
* **segmented** — the accumulator and receive buffers are HBM-resident
  and only a bounded double-buffered window (2 × ``seg`` elements)
  streams through VMEM for the reduction, so payload size is bounded by
  HBM, not VMEM — the explicit-DMA twin of the reference's *segmented*
  ring (``coll_base_allreduce.c:618`` ring_segmented) whose entire point
  is pipelining large payloads through bounded buffers.

The **bidirectional** ring variant splits the payload in half and runs
mirrored clockwise/counter-clockwise schedules concurrently — ICI links
are duplex, so both directions carry traffic every step and the bisection
time halves (the reference gets the same effect from its two-proc-group
rdb/segmented hybrids; here it is one kernel).

**Torus schedules** (``all_reduce_torus``) ride sub-rings of a
linearized (n0, n1) mesh — reduce-scatter along one torus dimension,
all-reduce along the other on 1/n0-sized blocks, all-gather back — so
every link of BOTH dimensions carries traffic and per-phase step count
follows the axis lengths, not their product (coll/han's hierarchical
composition, expressed as explicit DMA).  The **explicit all-to-all**
(pairwise exchange over direct per-peer DMAs, ``coll_base_alltoall.c``)
is the SP/MoE dispatch primitive.

Reduction is parameterized (sum/max/min/prod) — one op argument, the
same way ``ompi_op``'s function table parameterizes the reference's ring
(``coll_base_allreduce.c:341`` takes any ``ompi_op_t``).
"""
from __future__ import annotations

import functools

import numpy as np

def _op_fn(jnp, op: str):
    """Elementwise fold for a ring-kernel reduction op name."""
    try:
        return {
            "sum": lambda a, b: a + b,
            "max": jnp.maximum,
            "min": jnp.minimum,
            "prod": lambda a, b: a * b,
        }[op]
    except KeyError:
        raise ValueError(
            f"unsupported ring reduction {op!r}: one of sum/max/min/prod")


def _mods():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, jnp, lax, pl, pltpu


def _ring_kernels(n: int, axis: str, interpret: bool):
    """Build the kernel-constructor namespace once per (n, axis, mode)."""
    jax, jnp, lax, pl, pltpu = _mods()

    def compiler_params(collective_id: int):
        # distinct collective_id per kernel family: concurrent pallas
        # collectives must not share barrier/semaphore identity on real
        # hardware (Mosaic matches collective instances by this id)
        if interpret:
            return None
        return pltpu.CompilerParams(has_side_effects=True,
                                    collective_id=collective_id)

    return jax, jnp, lax, pl, pltpu, compiler_params


def _ring_fn(lax, axis: str, sub):
    """(ring position, position->logical-device-id map) for this device.

    ``sub=None``: the ring IS the whole 1-D mesh (identity map).
    ``sub=(n0, n1, j)``: the mesh linearizes a (n0, n1) torus row-major
    and the ring rides axis j — position p maps to device p*n1+i1
    (column ring pinned at my i1) or i0*n1+p (row ring pinned at my
    i0).  Index arithmetic on scalar LOGICAL ids keeps every kernel
    interpreter-runnable (the Pallas interpreter has no multi-axis DMA
    mesh support) and lowers identically on hardware, where ICI routes
    non-neighbor ids."""
    my = lax.axis_index(axis)
    if sub is None:
        return my, (lambda p: p)
    n0, n1, j = sub
    i0 = my // n1
    i1 = lax.rem(my, n1)
    if j == 0:
        return i0, (lambda p: p * n1 + i1)
    return i1, (lambda p: i0 * n1 + p)


@functools.lru_cache(maxsize=64)
def _build_right_permute(n: int, axis: str, shape, dtype_str: str,
                         interpret: bool):
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    def call(x):
        kw = {}
        cp = cparams(1)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_gather(n: int, axis: str, blk_shape, dtype_str: str,
                      interpret: bool, sub=None):
    """Ring all-gather: n-1 steps, each forwarding the freshest block to
    the right neighbor (``jax docs distributed`` canonical schedule; the
    reference's ``coll_base_allgather.c`` ring)."""
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, local_sem, send_sem, recv_sems):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        cp = pltpu.make_async_copy(x_ref, out_ref.at[my], local_sem)
        cp.start()
        cp.wait()

        def step(k, carry):
            slot = lax.rem(my - k + n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[slot],
                send_sem=send_sem, recv_sem=recv_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # send done + block (my-k-1) landed from the left
            return carry

        lax.fori_loop(0, n - 1, step, 0)

    def call(x):
        kw = {}
        cp = cparams(2)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


def _rs_phase(lax, pl, pltpu, *, n, my, right, acc_ref, recv_ref,
              send_sem, rs_sems, align: int, fold):
    """The shared ring reduce-scatter phase: n-1 steps, each sending the
    running partial for block (my+align-k) to the right neighbor and
    fusing the incoming partial into block (my+align-1-k).  After the
    loop, block (my+align+1) % n is fully reduced on this device —
    align=0 for the all-reduce schedule (owner my+1), align=-1 for
    owner-aligned reduce-scatter (owner my).  ONE copy of the DMA /
    semaphore / accumulate discipline, shared by both kernels.
    ``fold`` is the elementwise reduction."""

    def rs_step(k, carry):
        send_idx = lax.rem(my + align - k + 2 * n, n)
        recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc_ref.at[send_idx], dst_ref=recv_ref.at[k],
            send_sem=send_sem, recv_sem=rs_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # my partial for block recv_idx arrived
        part = recv_ref[pl.ds(k, 1), :]
        cur = acc_ref[pl.ds(recv_idx, 1), :]
        acc_ref[pl.ds(recv_idx, 1), :] = fold(cur, part)
        return carry

    lax.fori_loop(0, n - 1, rs_step, 0)
    return lax.rem(my + align + 1 + n, n)   # the completed block


@functools.lru_cache(maxsize=64)
def _build_all_reduce(n: int, axis: str, blk: int, dtype_str: str,
                      interpret: bool, op: str = "sum", sub=None):
    """Ring all-reduce: n-1 reduce-scatter steps with the fold fused
    into the ring loop, then n-1 all-gather steps — one kernel, the
    explicit-DMA form of ``coll_base_allreduce.c:341``.

    Per-device payload is pre-shaped to (n, blk).  Distinct recv slots
    per step (scratch (n-1, blk)) make the schedule self-synchronizing:
    no slot is ever reused, so the send/recv semaphore pair is the only
    ordering needed (the capacity/backpressure dance of a 2-slot scheme
    is deliberately traded for VMEM).
    """
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems, ag_sems):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=0,
                         fold=fold)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()

        _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                  out_ref=out_ref, send_sem=send_sem, ag_sems=ag_sems)

    def call(x):  # x: (n, blk) per device
        kw = {}
        cp = cparams(3)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, blk), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_reduce_scatter(n: int, axis: str, blk: int, dtype_str: str,
                          interpret: bool, op: str = "sum",
                          sub=None):
    """Ring reduce-scatter: n-1 steps, fold fused into the ring;
    device i ends owning fully-reduced block i (the first half of
    ``coll_base_allreduce.c:341``'s ring, block-owner aligned)."""
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems):
        my, dev = _ring_fn(lax, axis, sub)
        right = dev(lax.rem(my + 1, n))
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        # align=-1: the completed block is `my` — it IS my result
        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=-1,
                         fold=fold)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref, local_sem)
        cp2.start()
        cp2.wait()

    def call(x):  # x: (n, blk) per device -> (blk,) per device
        kw = {}
        cp = cparams(4)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((blk,), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


def _ag_phase(lax, pl, pltpu, *, n, my, right, out_ref, send_sem,
              ag_sems):
    """The shared ring all-gather phase of the all-reduce kernels: n-1
    steps, each forwarding the freshest completed block (my+1-k) to the
    right neighbor in place on ``out_ref`` — pure DMA, no window."""

    def ag_step(k, carry):
        fwd = lax.rem(my + 1 - k + n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[fwd], dst_ref=out_ref.at[fwd],
            send_sem=send_sem, recv_sem=ag_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # completed block (my-k)%n landed from the left
        return carry

    lax.fori_loop(0, n - 1, ag_step, 0)


def _seg_fold_row(lax, pl, pltpu, *, acc_ref, recv_ref, k, recv_idx,
                  col_off: int, nseg: int, seg: int, va, vb, load_sems,
                  wb_sems, fold):
    """Fold one received HBM row into one accumulator row through the
    2-slot double-buffered VMEM window: while segment s reduces,
    segment s+1's loads are already in flight, and writebacks drain one
    segment behind.  Fully drained on return, so the window is
    immediately reusable (the bidi kernel folds both directions through
    one window).  ``col_off`` addresses a column sub-range of the
    accumulator row (the bidi kernel's per-direction halves)."""

    def start_load(s):
        slot = lax.rem(s, 2)
        sl = pl.ds(col_off + s * seg, seg)
        rl = pl.ds(s * seg, seg)
        pltpu.make_async_copy(acc_ref.at[recv_idx, sl], va.at[slot],
                              load_sems.at[slot, 0]).start()
        pltpu.make_async_copy(recv_ref.at[k, rl], vb.at[slot],
                              load_sems.at[slot, 1]).start()

    def wait_wb(slot, s_of_wb):
        # descriptor only carries the byte count to decrement
        pltpu.make_async_copy(
            va.at[slot],
            acc_ref.at[recv_idx, pl.ds(col_off + s_of_wb * seg, seg)],
            wb_sems.at[slot]).wait()

    start_load(0)

    def seg_step(s, c):
        slot = lax.rem(s, 2)

        @pl.when(s + 1 < nseg)
        def _prefetch():
            @pl.when(s >= 1)
            def _drain_prev_wb():
                # slot 1-slot's writeback (segment s-1) must land
                # before its VMEM buffer is reloaded
                wait_wb(1 - slot, s - 1)
            start_load(s + 1)

        sl = pl.ds(col_off + s * seg, seg)
        rl = pl.ds(s * seg, seg)
        pltpu.make_async_copy(acc_ref.at[recv_idx, sl], va.at[slot],
                              load_sems.at[slot, 0]).wait()
        pltpu.make_async_copy(recv_ref.at[k, rl], vb.at[slot],
                              load_sems.at[slot, 1]).wait()
        cur = va[pl.ds(slot, 1), :]
        part = vb[pl.ds(slot, 1), :]
        va[pl.ds(slot, 1), :] = fold(cur, part)
        pltpu.make_async_copy(va.at[slot], acc_ref.at[recv_idx, sl],
                              wb_sems.at[slot]).start()
        return c

    lax.fori_loop(0, nseg, seg_step, 0)
    # drain outstanding writebacks before this row is sent next step
    wait_wb(lax.rem(nseg - 1, 2), nseg - 1)
    if nseg >= 2:
        wait_wb(lax.rem(nseg - 2, 2), nseg - 2)


def _seg_rs_phase(lax, pl, pltpu, *, n, my, right, acc_ref, recv_ref,
                  send_sem, rs_sems, align: int, fold, nseg: int, seg: int,
                  va, vb, load_sems, wb_sems):
    """Segmented twin of ``_rs_phase``: acc/recv live in HBM; the fold
    streams through the bounded VMEM window (``_seg_fold_row``) — the
    bounded-buffer pipeline of the reference's segmented ring
    (``coll_base_allreduce.c:618``), which exists precisely so payload
    size is bounded by main memory, not the staging buffer."""

    def rs_step(k, carry):
        send_idx = lax.rem(my + align - k + 2 * n, n)
        recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc_ref.at[send_idx], dst_ref=recv_ref.at[k],
            send_sem=send_sem, recv_sem=rs_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # my partial for block recv_idx arrived (HBM)
        _seg_fold_row(lax, pl, pltpu, acc_ref=acc_ref, recv_ref=recv_ref,
                      k=k, recv_idx=recv_idx, col_off=0, nseg=nseg,
                      seg=seg, va=va, vb=vb, load_sems=load_sems,
                      wb_sems=wb_sems, fold=fold)
        return carry

    lax.fori_loop(0, n - 1, rs_step, 0)
    return lax.rem(my + align + 1 + n, n)   # the completed block


@functools.lru_cache(maxsize=64)
def _build_all_reduce_seg(n: int, axis: str, blk: int, seg: int,
                          dtype_str: str, interpret: bool,
                          op: str = "sum"):
    """Segmented ring all-reduce for large payloads: HBM-resident
    (n, blk) accumulator, bounded VMEM window, same ring schedule as
    the fused kernel.  The all-gather phase is pure HBM↔HBM remote DMA
    and needs no window at all."""
    assert blk % seg == 0, (blk, seg)
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)
    nseg = blk // seg

    def kernel(x_ref, out_ref, acc_ref, recv_ref, va, vb,
               local_sem, send_sem, load_sems, wb_sems, rs_sems, ag_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _seg_rs_phase(
            lax, pl, pltpu, n=n, my=my, right=right, acc_ref=acc_ref,
            recv_ref=recv_ref, send_sem=send_sem, rs_sems=rs_sems,
            align=0, fold=fold, nseg=nseg, seg=seg,
            va=va, vb=vb, load_sems=load_sems, wb_sems=wb_sems)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()

        _ag_phase(lax, pl, pltpu, n=n, my=my, right=right,
                  out_ref=out_ref, send_sem=send_sem, ag_sems=ag_sems)

    def call(x):  # x: (n, blk) per device
        kw = {}
        cp = cparams(5)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, blk), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.HBM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.HBM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_reduce_scatter_seg(n: int, axis: str, blk: int, seg: int,
                              dtype_str: str, interpret: bool,
                              op: str = "sum"):
    """Segmented ring reduce-scatter (owner-aligned, align=-1) — the
    large-payload twin of ``_build_reduce_scatter``."""
    assert blk % seg == 0, (blk, seg)
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)
    nseg = blk // seg

    def kernel(x_ref, out_ref, acc_ref, recv_ref, va, vb,
               local_sem, send_sem, load_sems, wb_sems, rs_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _seg_rs_phase(
            lax, pl, pltpu, n=n, my=my, right=right, acc_ref=acc_ref,
            recv_ref=recv_ref, send_sem=send_sem, rs_sems=rs_sems,
            align=-1, fold=fold, nseg=nseg, seg=seg,
            va=va, vb=vb, load_sems=load_sems, wb_sems=wb_sems)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref, local_sem)
        cp2.start()
        cp2.wait()

    def call(x):  # x: (n, blk) per device -> (blk,) per device
        kw = {}
        cp = cparams(6)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((blk,), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.HBM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.HBM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


def _bidi_done_and_ag(lax, pl, pltpu, *, n, my, right, left, half,
                      acc_ref, out_ref, local_sem, send_cw_sem,
                      send_ccw_sem, ag_cw_sems, ag_ccw_sems):
    """Shared tail of the bidirectional all-reduce kernels: copy each
    direction's completed half-block out, then run the mirrored
    all-gather rings (both duplex directions busy every step)."""
    h = half
    done_cw = lax.rem(my + 1, n)
    done_ccw = lax.rem(my - 1 + n, n)
    c1 = pltpu.make_async_copy(acc_ref.at[done_cw, pl.ds(0, h)],
                               out_ref.at[done_cw, pl.ds(0, h)],
                               local_sem)
    c1.start()
    c1.wait()
    c2 = pltpu.make_async_copy(acc_ref.at[done_ccw, pl.ds(h, h)],
                               out_ref.at[done_ccw, pl.ds(h, h)],
                               local_sem)
    c2.start()
    c2.wait()

    def ag_step(k, carry):
        f_cw = lax.rem(my + 1 - k + n, n)
        f_ccw = lax.rem(my - 1 + k + n, n)
        d_cw = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[f_cw, pl.ds(0, h)],
            dst_ref=out_ref.at[f_cw, pl.ds(0, h)],
            send_sem=send_cw_sem, recv_sem=ag_cw_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        d_ccw = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[f_ccw, pl.ds(h, h)],
            dst_ref=out_ref.at[f_ccw, pl.ds(h, h)],
            send_sem=send_ccw_sem, recv_sem=ag_ccw_sems.at[k],
            device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        d_cw.start()
        d_ccw.start()
        d_cw.wait()
        d_ccw.wait()
        return carry

    lax.fori_loop(0, n - 1, ag_step, 0)


@functools.lru_cache(maxsize=64)
def _build_all_reduce_seg_bidi(n: int, axis: str, half: int, seg: int,
                               dtype_str: str, interpret: bool,
                               op: str = "sum"):
    """Segmented AND bidirectional ring all-reduce — the large-payload
    champion: the (n, 2*half) payload is HBM-resident, columns [:half]
    ride the clockwise ring and [half:] the counter-clockwise ring
    concurrently (both duplex ICI directions carry a half-payload every
    step), and each direction's fold streams through ONE shared
    double-buffered VMEM window (``_seg_fold_row`` drains fully between
    directions, so the window is reused — folds are VPU-sequential
    anyway; it is the DMAs that overlap).
    """
    assert half % seg == 0, (half, seg)
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)
    nseg = half // seg
    blk = 2 * half

    def kernel(x_ref, out_ref, acc_ref, recv_cw, recv_ccw, va, vb,
               local_sem, send_cw_sem, send_ccw_sem, load_sems, wb_sems,
               rs_cw_sems, rs_ccw_sems, ag_cw_sems, ag_ccw_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        left = lax.rem(my - 1 + n, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        h = half

        def rs_step(k, carry):
            s_cw = lax.rem(my - k + 2 * n, n)
            r_cw = lax.rem(my - 1 - k + 2 * n, n)
            s_ccw = lax.rem(my + k, n)
            r_ccw = lax.rem(my + 1 + k, n)
            d_cw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_cw, pl.ds(0, h)],
                dst_ref=recv_cw.at[k],
                send_sem=send_cw_sem, recv_sem=rs_cw_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_ccw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_ccw, pl.ds(h, h)],
                dst_ref=recv_ccw.at[k],
                send_sem=send_ccw_sem, recv_sem=rs_ccw_sems.at[k],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_cw.start()
            d_ccw.start()          # both directions' DMAs in flight
            d_cw.wait()
            _seg_fold_row(lax, pl, pltpu, acc_ref=acc_ref,
                          recv_ref=recv_cw, k=k, recv_idx=r_cw,
                          col_off=0, nseg=nseg, seg=seg, va=va, vb=vb,
                          load_sems=load_sems, wb_sems=wb_sems,
                          fold=fold)
            d_ccw.wait()
            _seg_fold_row(lax, pl, pltpu, acc_ref=acc_ref,
                          recv_ref=recv_ccw, k=k, recv_idx=r_ccw,
                          col_off=h, nseg=nseg, seg=seg, va=va, vb=vb,
                          load_sems=load_sems, wb_sems=wb_sems,
                          fold=fold)
            return carry

        lax.fori_loop(0, n - 1, rs_step, 0)
        _bidi_done_and_ag(lax, pl, pltpu, n=n, my=my, right=right,
                          left=left, half=half, acc_ref=acc_ref,
                          out_ref=out_ref, local_sem=local_sem,
                          send_cw_sem=send_cw_sem,
                          send_ccw_sem=send_ccw_sem,
                          ag_cw_sems=ag_cw_sems, ag_ccw_sems=ag_ccw_sems)

    def call(x):  # x: (n, 2*half) per device
        kw = {}
        cp = cparams(12)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, blk), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.HBM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.HBM((n - 1, half),
                                      jnp.dtype(dtype_str)),
                            pltpu.HBM((n - 1, half),
                                      jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.VMEM((2, seg), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((2, 2)),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_reduce_bidi(n: int, axis: str, half: int, dtype_str: str,
                           interpret: bool, op: str = "sum"):
    """Bidirectional ring all-reduce: the (n, 2*half) payload is split
    into a clockwise half (columns [:half], sent rightward) and a
    counter-clockwise half (columns [half:], sent leftward), with
    mirrored reduce-scatter + all-gather schedules running concurrently.
    ICI links are duplex, so both directions carry a half-payload every
    step — per-step wire time halves vs the unidirectional ring.

    CW completes block (my+1)'s left half; CCW completes block (my-1)'s
    right half; the mirrored all-gather phases then circulate both.
    """
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    fold = _op_fn(jnp, op)
    blk = 2 * half

    def kernel(x_ref, out_ref, acc_ref, recv_cw, recv_ccw,
               local_sem, send_cw_sem, send_ccw_sem,
               rs_cw_sems, rs_ccw_sems, ag_cw_sems, ag_ccw_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        left = lax.rem(my - 1 + n, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        h = half

        def rs_step(k, carry):
            s_cw = lax.rem(my - k + 2 * n, n)
            r_cw = lax.rem(my - 1 - k + 2 * n, n)
            s_ccw = lax.rem(my + k, n)
            r_ccw = lax.rem(my + 1 + k, n)
            d_cw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_cw, pl.ds(0, h)],
                dst_ref=recv_cw.at[k],
                send_sem=send_cw_sem, recv_sem=rs_cw_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_ccw = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[s_ccw, pl.ds(h, h)],
                dst_ref=recv_ccw.at[k],
                send_sem=send_ccw_sem, recv_sem=rs_ccw_sems.at[k],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d_cw.start()
            d_ccw.start()
            d_cw.wait()
            d_ccw.wait()
            cur_cw = acc_ref[pl.ds(r_cw, 1), pl.ds(0, h)]
            acc_ref[pl.ds(r_cw, 1), pl.ds(0, h)] = fold(
                cur_cw, recv_cw[pl.ds(k, 1), :])
            cur_ccw = acc_ref[pl.ds(r_ccw, 1), pl.ds(h, h)]
            acc_ref[pl.ds(r_ccw, 1), pl.ds(h, h)] = fold(
                cur_ccw, recv_ccw[pl.ds(k, 1), :])
            return carry

        lax.fori_loop(0, n - 1, rs_step, 0)
        _bidi_done_and_ag(lax, pl, pltpu, n=n, my=my, right=right,
                          left=left, half=half, acc_ref=acc_ref,
                          out_ref=out_ref, local_sem=local_sem,
                          send_cw_sem=send_cw_sem,
                          send_ccw_sem=send_ccw_sem,
                          ag_cw_sems=ag_cw_sems, ag_ccw_sems=ag_ccw_sems)

    def call(x):  # x: (n, 2*half) per device
        kw = {}
        cp = cparams(7)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, blk), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, half), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, half), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_to_all(n: int, axis: str, blk_shape, dtype_str: str,
                      interpret: bool):
    """Explicit all-to-all: n-1 steps, at step k every device DMAs its
    block for the device k hops right DIRECTLY to that device (ICI
    routes non-neighbor transfers), landing in the sender's slot —
    the SP/MoE dispatch primitive (``lax.all_to_all`` twin;
    ``coll_base_alltoall.c`` pairwise-exchange algorithm, where step k
    pairs (i, i+k)).  Fully symmetric: one DMA per device per step.
    """
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, local_sem, send_sem, recv_sems):
        my = lax.axis_index(axis)
        cp = pltpu.make_async_copy(x_ref.at[my], out_ref.at[my],
                                   local_sem)
        cp.start()
        cp.wait()

        def step(k, carry):
            peer = lax.rem(my + k, n)     # send my block for `peer`
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[peer], dst_ref=out_ref.at[my],
                send_sem=send_sem, recv_sem=recv_sems.at[k - 1],
                device_id=peer,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # send done + block from (my-k) landed
            return carry

        lax.fori_loop(1, n, step, 0)

    def call(x):  # x: (n, *blk) per device -> (n, *blk) transposed
        kw = {}
        cp = cparams(9)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_bcast(n: int, axis: str, nseg: int, seg: int, dtype_str: str,
                 interpret: bool):
    """Pipelined segmented ring broadcast — the "clamped conveyor": root
    streams S segments rightward and every hop forwards segment s one
    wave after receiving it, so all links are busy simultaneously and
    total time ≈ (S + n - 2) segment-hops instead of (n-1) full-payload
    hops — the explicit-DMA form of the reference's pipeline bcast
    (``coll_base_bcast.c`` pipeline/chain algorithms).

    The schedule is fully symmetric (SPMD-clean, no masked DMAs — a
    masked send would desync the per-op DMA rendezvous the interpreter
    emulates remote copies with): at wave j, the device at ring position
    r = (my-root) mod n forwards slot ``clamp(j-r, 0, S-1)``.  Below the
    clamp the payload is not-yet-valid filler that a valid write always
    overwrites before the receiver forwards that slot (position r first
    forwards slot s at wave s+r, having received the valid copy at wave
    s+r-1); above the clamp it is a benign same-bytes re-send.  The last
    device aims its writes at a sink row (``out[S]``) so the conveyor
    never races root's source rows.
    """
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)
    waves = nseg + n - 2

    # root arrives as a runtime SMEM scalar, not a cache key: the kernel
    # only uses it through rel = (my - root) mod n, so one compile
    # serves every root (round-robin-root workloads stay cache-hot)
    def kernel(root_ref, x_ref, out_ref, local_sem, send_sem, recv_sem):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        rel = lax.rem(my - root_ref[0] + n, n)
        # everyone seeds out with its local buffer: root's rows are the
        # payload, other devices' rows are pre-valid filler the conveyor
        # overwrites in time
        cp = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(0, nseg)],
                                   local_sem)
        cp.start()
        cp.wait()

        def wave(j, carry):
            slot = lax.clamp(0, j - rel, nseg - 1)
            # the ring's last device (rel n-1) writes into root's sink
            # row: root's real rows are the source of truth
            dst = lax.select(rel == n - 1, nseg, slot)
            # ONE recv semaphore for all waves (semaphore memory is a
            # small fixed chip resource — per-wave semaphores would
            # scale with payload size): safe because each sender's
            # wave-j+1 DMA starts only after its wave-j wait(), so
            # signals arrive in wave order and every wave moves the
            # same byte count; run-ahead just accumulates counts
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[dst],
                send_sem=send_sem, recv_sem=recv_sem,
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()
            return carry

        lax.fori_loop(0, waves, wave, 0)

    def call(root, x):  # x: (nseg, seg) per device; returns root's rows
        kw = {}
        cp = cparams(8)
        if cp is not None:
            kw["compiler_params"] = cp
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((nseg + 1, seg), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
            **kw,
        )(root, x)
        return out[:nseg]

    return call


# -- public entry points (shard_map wrappers) ----------------------------
#
# Each wrapper resolves to a CACHED jitted program (lru keyed on mesh /
# shape / dtype / op / variant): building jax.jit around a fresh closure
# per call would retrace and recompile every time, turning each
# collective into compile time (jax.sharding.Mesh is hashable and
# equality-stable, so it can key the cache directly).

@functools.lru_cache(maxsize=256)
def _jit_right_permute(mesh, axis: str, payload_shape, dtype_str: str,
                       interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    fn = _build_right_permute(n, axis, (1,) + payload_shape, dtype_str,
                              interpret)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def right_permute(x, mesh, axis: str, interpret: bool = True):
    """Rotate the leading (rank) axis by +1 via neighbor remote DMA —
    the PP activation-handoff primitive (``lax.ppermute`` twin)."""
    if mesh.shape[axis] == 1:
        return x
    return _jit_right_permute(mesh, axis, tuple(x.shape[1:]),
                              str(x.dtype), interpret)(x)


@functools.lru_cache(maxsize=256)
def _jit_all_gather(mesh, axis: str, blk_shape, dtype_str: str,
                    interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    inner = _build_all_gather(n, axis, blk_shape, dtype_str, interpret)

    def body(t):                       # t: (1, *S)
        return inner(t[0])             # (n, *S)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))


def all_gather(x, mesh, axis: str, interpret: bool = True):
    """(n, *S) sharded -> (n, *S) replicated via the DMA ring."""
    if mesh.shape[axis] == 1:
        return x
    return _jit_all_gather(mesh, axis, tuple(x.shape[1:]), str(x.dtype),
                           interpret)(x)


#: default VMEM window (elements) for the segmented kernels when the
#: caller does not size it
_DEFAULT_SEG_ELEMS = 131072


def _seg_shape(blk: int, seg_elems: int | None) -> tuple[int, int]:
    """(window, padded block): the segment window never exceeds the ring
    block, and the block is rounded up to a whole number of segments."""
    seg = min(seg_elems or _DEFAULT_SEG_ELEMS, blk)
    return seg, -(-blk // seg) * seg


def _pad_value(op: str, dtype) -> float | int:
    """Neutral element used to pad the flattened payload to n equal ring
    blocks — must not perturb the fold, for any dtype (±inf is not a
    valid neutral for integers: use the dtype's extrema there)."""
    dtype = np.dtype(dtype)
    if op == "sum":
        return 0
    if op == "prod":
        return 1
    lim = np.finfo(dtype) if dtype.kind == "f" else np.iinfo(dtype)
    return lim.min if op == "max" else lim.max


@functools.lru_cache(maxsize=256)
def _jit_reduce_scatter(mesh, axis: str, payload_shape, dtype_str: str,
                        op: str, interpret: bool, variant: str,
                        seg_elems):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    blk = int(np.prod(payload_shape)) if payload_shape else 1
    if variant == "seg":
        seg, blk_p = _seg_shape(blk, seg_elems)
        inner = _build_reduce_scatter_seg(n, axis, blk_p, seg,
                                          dtype_str, interpret, op)
    else:
        blk_p = blk
        inner = _build_reduce_scatter(n, axis, blk, dtype_str,
                                      interpret, op)

    def body(t):                       # t: (1, n, *S)
        rows = t[0].reshape(n, blk)
        if blk_p != blk:
            rows = jnp.pad(rows, ((0, 0), (0, blk_p - blk)),
                           constant_values=_pad_value(op, dtype_str))
        out = inner(rows)              # (blk_p,)
        return out[:blk].reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def reduce_scatter(x, mesh, axis: str, op: str = "sum",
                   interpret: bool = True, variant: str = "fused",
                   seg_elems: int | None = None):
    """(n, n, *S) sharded on the leading rank axis -> (n, *S) sharded:
    rank i receives the reduction of everyone's block i via the DMA
    ring.  ``variant='seg'`` uses the HBM-resident segmented kernel
    (window of ``seg_elems``) for payloads too large for VMEM."""
    payload_shape = tuple(x.shape[2:])
    if mesh.shape[axis] == 1:
        return x.reshape((1,) + payload_shape)
    return _jit_reduce_scatter(mesh, axis, payload_shape, str(x.dtype),
                               op, interpret, variant, seg_elems)(x)


def reduce_scatter_sum(x, mesh, axis: str, interpret: bool = True):
    return reduce_scatter(x, mesh, axis, "sum", interpret)


@functools.lru_cache(maxsize=256)
def _jit_all_reduce(mesh, axis: str, payload_shape, dtype_str: str,
                    op: str, interpret: bool, variant: str, seg_elems):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    blk = -(-size // n)                # ceil
    if variant == "seg":
        seg, blk = _seg_shape(blk, seg_elems)
        inner = _build_all_reduce_seg(n, axis, blk, seg, dtype_str,
                                      interpret, op)
    elif variant == "seg_bidi":
        half = -(-blk // 2)
        seg, half = _seg_shape(half, seg_elems)
        blk = 2 * half
        inner = _build_all_reduce_seg_bidi(n, axis, half, seg,
                                           dtype_str, interpret, op)
    elif variant == "bidi":
        blk = blk + (blk % 2)          # even split across directions
        inner = _build_all_reduce_bidi(n, axis, blk // 2, dtype_str,
                                       interpret, op)
    else:
        inner = _build_all_reduce(n, axis, blk, dtype_str, interpret,
                                  op)
    padded = blk * n

    def body(t):                       # t: (1, *S)
        flat = t.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size),
                           constant_values=_pad_value(op, dtype_str))
        out = inner(flat.reshape(n, blk))      # (n, blk) reduced
        return out.reshape(-1)[:size].reshape(payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))


def all_reduce(x, mesh, axis: str, op: str = "sum",
               interpret: bool = True, variant: str = "fused",
               seg_elems: int | None = None):
    """(n, *S) sharded -> (*S) replicated reduction via a ring kernel.

    The per-rank payload is flattened and neutrally-padded to n equal
    ring blocks outside the kernel (XLA fuses the pad/reshape into the
    surrounding program).  Variants:

    * ``'fused'``    — whole accumulator in VMEM (lowest latency, small).
    * ``'seg'``      — HBM accumulator + bounded VMEM window of
      ``seg_elems`` (large payloads; `coll_base_allreduce.c:618` twin).
    * ``'bidi'``     — both ICI directions carry half the payload each
      step (duplex links; halves per-step wire time).  VMEM-bounded.
    * ``'seg_bidi'`` — both at once: HBM-resident halves ride both
      directions concurrently, folds stream through the shared window
      (the large-payload duplex champion).
    """
    payload_shape = tuple(x.shape[1:])
    if mesh.shape[axis] == 1:
        return x.reshape(payload_shape)
    return _jit_all_reduce(mesh, axis, payload_shape, str(x.dtype), op,
                           interpret, variant, seg_elems)(x)


def all_reduce_sum(x, mesh, axis: str, interpret: bool = True):
    return all_reduce(x, mesh, axis, "sum", interpret)


@functools.lru_cache(maxsize=256)
def _jit_all_to_all(mesh, axis: str, blk_shape, dtype_str: str,
                    interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    inner = _build_all_to_all(n, axis, blk_shape, dtype_str, interpret)

    def body(t):                       # t: (1, n, *S)
        return inner(t[0])[None]       # (1, n, *S): row = my received

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def all_to_all(x, mesh, axis: str, interpret: bool = True):
    """(n, n, *S) sharded on the leading rank axis: rank i's block j
    moves to rank j's slot i (``x[i, j] -> out[j, i]``, the coll/xla
    ``alltoall_array`` convention) via direct per-peer remote DMA."""
    n = mesh.shape[axis]
    if x.ndim < 2 or x.shape[0] != n or x.shape[1] != n:
        # the kernel indexes n blocks per rank: anything else would be
        # an out-of-bounds remote DMA, not a reshape-able layout
        raise ValueError(
            f"all_to_all needs a ({n}, {n}, *S) array on this mesh, "
            f"got {tuple(x.shape)}")
    if n == 1:
        return x
    return _jit_all_to_all(mesh, axis, tuple(x.shape[2:]), str(x.dtype),
                           interpret)(x)


@functools.lru_cache(maxsize=256)
def _jit_all_reduce_torus(mesh, axes, payload_shape, dtype_str: str,
                          op: str, interpret: bool):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    a0, a1 = axes
    n0, n1 = mesh.shape[a0], mesh.shape[a1]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    blk0 = -(-size // n0)
    blk1 = -(-blk0 // n1)
    # the kernels run over a FLATTENED 1-D mesh with sub-ring index
    # arithmetic ((i0, i1) <-> i0*n1+i1): scalar LOGICAL device ids
    # stay interpreter-runnable and lower identically on hardware
    flat_mesh = Mesh(np.asarray(mesh.devices).reshape(-1), ("_t",))
    rs0 = _build_reduce_scatter(n0, "_t", blk0, dtype_str, interpret,
                                op, sub=(n0, n1, 0))
    ar1 = _build_all_reduce(n1, "_t", blk1, dtype_str, interpret, op,
                            sub=(n0, n1, 1))
    ag0 = _build_all_gather(n0, "_t", (blk0,), dtype_str, interpret,
                            sub=(n0, n1, 0))
    pad = _pad_value(op, dtype_str)

    def body(t):                       # t: (1, *S)
        flat = t.reshape(-1)
        if blk0 * n0 != size:
            flat = jnp.pad(flat, (0, blk0 * n0 - size),
                           constant_values=pad)
        part = rs0(flat.reshape(n0, blk0))         # (blk0,) over a0
        if blk1 * n1 != blk0:
            part = jnp.pad(part, (0, blk1 * n1 - blk0),
                           constant_values=pad)
        red = ar1(part.reshape(n1, blk1)).reshape(-1)[:blk0]  # over a1
        full = ag0(red)                            # (n0, blk0) over a0
        return full.reshape(-1)[:size].reshape(payload_shape)

    return jax.jit(shard_map(body, mesh=flat_mesh, in_specs=P("_t"),
                             out_specs=P(), check_vma=False))


def all_reduce_torus(x, mesh, axes=("x", "y"), op: str = "sum",
                     interpret: bool = True):
    """(n0, n1, *S) sharded over both torus axes -> (*S) replicated
    reduction: reduce-scatter rings along ``axes[0]``, all-reduce rings
    along ``axes[1]`` on the scattered blocks, all-gather rings along
    ``axes[0]`` back.  Per-step wire time scales with the axis lengths
    (n0 + n1 ring steps on 1/n0-sized blocks) rather than one n0*n1
    ring, and every link of BOTH torus dimensions carries traffic — the
    2D schedule the reference reaches for with coll/han's hierarchical
    composition (``coll_han``), expressed as three explicit-DMA phases.
    """
    axes = tuple(axes)
    payload_shape = tuple(x.shape[2:])
    n0, n1 = mesh.shape[axes[0]], mesh.shape[axes[1]]
    if n0 == 1 or n1 == 1:
        # a degenerate torus axis is a plain 1-D ring (a single pod
        # row/column): the zero-sized (n-1, blk) recv scratch of an
        # n=1 sub-ring cannot build
        from jax.sharding import Mesh

        flat_mesh = Mesh(np.asarray(mesh.devices).reshape(-1), ("_t",))
        return all_reduce(x.reshape((n0 * n1,) + payload_shape),
                          flat_mesh, "_t", op, interpret)
    fn = _jit_all_reduce_torus(mesh, axes, payload_shape,
                               str(x.dtype), op, interpret)
    return fn(x.reshape((n0 * n1,) + payload_shape))


@functools.lru_cache(maxsize=256)
def _jit_bcast(mesh, axis: str, payload_shape, dtype_str: str,
               interpret: bool, seg_elems: int):
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    size = int(np.prod(payload_shape)) if payload_shape else 1
    seg = min(seg_elems, size)
    nseg = -(-size // seg)
    padded = nseg * seg
    inner = _build_bcast(n, axis, nseg, seg, dtype_str, interpret)

    def body(r, t):                    # r: (1,) int32; t: (1, *S)
        flat = t.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        out = inner(r, flat.reshape(nseg, seg))   # (nseg, seg) = root's
        return out.reshape(-1)[:size].reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P(axis)),
                             out_specs=P(axis), check_vma=False))


def bcast(x, mesh, axis: str, root: int = 0, interpret: bool = True,
          seg_elems: int = 65536):
    """(n, *S) sharded -> (n, *S) with every row equal to root's row,
    via the pipelined segmented ring (time ≈ (S + n - 2) segment-hops).
    ``root`` is a runtime operand — every root shares one compile."""
    jax, jnp, lax, pl, pltpu = _mods()

    n = mesh.shape[axis]
    if n == 1:
        return x
    fn = _jit_bcast(mesh, axis, tuple(x.shape[1:]), str(x.dtype),
                    interpret, int(seg_elems))
    return fn(jnp.asarray([int(root) % n], dtype=jnp.int32), x)
