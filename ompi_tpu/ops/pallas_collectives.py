"""Pallas remote-DMA ring collectives — the explicit ICI transport path.

The reference's lowest layer is an explicit transport with RDMA verbs
(``/root/reference/opal/mca/btl/btl.h:949`` put / ``:987`` get); its
collectives are schedules of those verbs over a topology.  coll/xla rides
XLA's compiler-scheduled collectives instead — this module is the
explicit-schedule twin: ring algorithms written directly against the ICI
with ``pltpu.make_async_remote_copy`` (one-sided remote DMA + send/recv
semaphore discipline), the TPU-native form of the reference's
``btl_put``-based ring (``coll_base_allreduce.c:341``).

Why have both: XLA's collectives are near-optimal for the standard cases,
but an explicit schedule composes with compute inside ONE kernel (overlap
of reduce + forward per ring step, custom quantized wire formats, PP
activation handoff fused into the stage loop) — the knob the reference
keeps by owning its transport.  SURVEY.md §2.6 maps this slot to "Pallas
remote DMA".

All kernels are SPMD under ``shard_map`` over a 1-D mesh axis; payloads
are split into per-device ring blocks outside the kernel.  They run in
interpreter mode on a virtual CPU mesh (tests) and compile for real
multi-chip ICI unchanged.  VMEM bounds the block size (the accumulator
lives on-chip): huge payloads belong to coll/xla — the component's
``max_bytes`` var gates selection accordingly.
"""
from __future__ import annotations

import functools

import numpy as np


def _mods():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, jnp, lax, pl, pltpu


def _ring_kernels(n: int, axis: str, interpret: bool):
    """Build the kernel-constructor namespace once per (n, axis, mode)."""
    jax, jnp, lax, pl, pltpu = _mods()

    def compiler_params(collective_id: int):
        # distinct collective_id per kernel family: concurrent pallas
        # collectives must not share barrier/semaphore identity on real
        # hardware (Mosaic matches collective instances by this id)
        if interpret:
            return None
        return pltpu.CompilerParams(has_side_effects=True,
                                    collective_id=collective_id)

    return jax, jnp, lax, pl, pltpu, compiler_params


@functools.lru_cache(maxsize=64)
def _build_right_permute(n: int, axis: str, shape, dtype_str: str,
                         interpret: bool):
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    def call(x):
        kw = {}
        cp = cparams(1)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_all_gather(n: int, axis: str, blk_shape, dtype_str: str,
                      interpret: bool):
    """Ring all-gather: n-1 steps, each forwarding the freshest block to
    the right neighbor (``jax docs distributed`` canonical schedule; the
    reference's ``coll_base_allgather.c`` ring)."""
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, local_sem, send_sem, recv_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        cp = pltpu.make_async_copy(x_ref, out_ref.at[my], local_sem)
        cp.start()
        cp.wait()

        def step(k, carry):
            slot = lax.rem(my - k + n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[slot], dst_ref=out_ref.at[slot],
                send_sem=send_sem, recv_sem=recv_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # send done + block (my-k-1) landed from the left
            return carry

        lax.fori_loop(0, n - 1, step, 0)

    def call(x):
        kw = {}
        cp = cparams(2)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + blk_shape, dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


def _rs_phase(lax, pl, pltpu, *, n, my, right, acc_ref, recv_ref,
              send_sem, rs_sems, align: int):
    """The shared ring reduce-scatter phase: n-1 steps, each sending the
    running partial for block (my+align-k) to the right neighbor and
    fusing the incoming partial into block (my+align-1-k).  After the
    loop, block (my+align+1) % n is fully reduced on this device —
    align=0 for the all-reduce schedule (owner my+1), align=-1 for
    owner-aligned reduce-scatter (owner my).  ONE copy of the DMA /
    semaphore / accumulate discipline, shared by both kernels."""

    def rs_step(k, carry):
        send_idx = lax.rem(my + align - k + 2 * n, n)
        recv_idx = lax.rem(my + align - 1 - k + 2 * n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc_ref.at[send_idx], dst_ref=recv_ref.at[k],
            send_sem=send_sem, recv_sem=rs_sems.at[k],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()   # my partial for block recv_idx arrived
        part = recv_ref[pl.ds(k, 1), :]
        cur = acc_ref[pl.ds(recv_idx, 1), :]
        acc_ref[pl.ds(recv_idx, 1), :] = cur + part
        return carry

    lax.fori_loop(0, n - 1, rs_step, 0)
    return lax.rem(my + align + 1 + n, n)   # the completed block


@functools.lru_cache(maxsize=64)
def _build_all_reduce(n: int, axis: str, blk: int, dtype_str: str,
                      interpret: bool):
    """Ring all-reduce (sum): n-1 reduce-scatter steps with the add fused
    into the ring loop, then n-1 all-gather steps — one kernel, the
    explicit-DMA form of ``coll_base_allreduce.c:341``.

    Per-device payload is pre-shaped to (n, blk).  Distinct recv slots
    per step (scratch (n-1, blk)) make the schedule self-synchronizing:
    no slot is ever reused, so the send/recv semaphore pair is the only
    ordering needed (the capacity/backpressure dance of a 2-slot scheme
    is deliberately traded for VMEM).
    """
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems, ag_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=0)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref.at[done],
                                    local_sem)
        cp2.start()
        cp2.wait()

        # -- all-gather phase -----------------------------------------
        def ag_step(k, carry):
            fwd = lax.rem(my + 1 - k + n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_ref.at[fwd], dst_ref=out_ref.at[fwd],
                send_sem=send_sem, recv_sem=ag_sems.at[k],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait()   # completed block (my-k)%n landed from the left
            return carry

        lax.fori_loop(0, n - 1, ag_step, 0)

    def call(x):  # x: (n, blk) per device
        kw = {}
        cp = cparams(3)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, blk), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


@functools.lru_cache(maxsize=64)
def _build_reduce_scatter(n: int, axis: str, blk: int, dtype_str: str,
                          interpret: bool):
    """Ring reduce-scatter (sum): n-1 steps, add fused into the ring;
    device i ends owning fully-reduced block i (the first half of
    ``coll_base_allreduce.c:341``'s ring, block-owner aligned)."""
    jax, jnp, lax, pl, pltpu, cparams = _ring_kernels(n, axis, interpret)

    def kernel(x_ref, out_ref, acc_ref, recv_ref,
               local_sem, send_sem, rs_sems):
        my = lax.axis_index(axis)
        right = lax.rem(my + 1, n)
        cp = pltpu.make_async_copy(x_ref, acc_ref, local_sem)
        cp.start()
        cp.wait()

        # align=-1: the completed block is `my` — it IS my result
        done = _rs_phase(lax, pl, pltpu, n=n, my=my, right=right,
                         acc_ref=acc_ref, recv_ref=recv_ref,
                         send_sem=send_sem, rs_sems=rs_sems, align=-1)
        cp2 = pltpu.make_async_copy(acc_ref.at[done], out_ref, local_sem)
        cp2.start()
        cp2.wait()

    def call(x):  # x: (n, blk) per device -> (blk,) per device
        kw = {}
        cp = cparams(4)
        if cp is not None:
            kw["compiler_params"] = cp
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((blk,), dtype_str),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((n, blk), jnp.dtype(dtype_str)),
                            pltpu.VMEM((n - 1, blk), jnp.dtype(dtype_str)),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA((n - 1,))],
            interpret=interpret,
            **kw,
        )(x)

    return call


# -- public entry points (shard_map wrappers) ----------------------------

def right_permute(x, mesh, axis: str, interpret: bool = True):
    """Rotate the leading (rank) axis by +1 via neighbor remote DMA —
    the PP activation-handoff primitive (``lax.ppermute`` twin)."""
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    if n == 1:
        return x
    shard_shape = (1,) + tuple(x.shape[1:])
    fn = _build_right_permute(n, axis, shard_shape, str(x.dtype), interpret)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))(x)


def all_gather(x, mesh, axis: str, interpret: bool = True):
    """(n, *S) sharded -> (n, *S) replicated via the DMA ring."""
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    if n == 1:
        return x
    blk_shape = tuple(x.shape[1:])
    inner = _build_all_gather(n, axis, blk_shape, str(x.dtype), interpret)

    def body(t):                       # t: (1, *S)
        return inner(t[0])             # (n, *S)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))(x)


def reduce_scatter_sum(x, mesh, axis: str, interpret: bool = True):
    """(n, n, *S) sharded on the leading rank axis -> (n, *S) sharded:
    rank i receives the sum of everyone's block i via the DMA ring."""
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    payload_shape = tuple(x.shape[2:])
    if n == 1:
        return x.reshape((1,) + payload_shape)
    blk = int(np.prod(payload_shape)) if payload_shape else 1
    inner = _build_reduce_scatter(n, axis, blk, str(x.dtype), interpret)

    def body(t):                       # t: (1, n, *S)
        out = inner(t[0].reshape(n, blk))      # (blk,)
        return out.reshape((1,) + payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))(x)


def all_reduce_sum(x, mesh, axis: str, interpret: bool = True):
    """(n, *S) sharded -> (*S) replicated sum via the fused ring kernel.

    The per-rank payload is flattened and zero-padded to n equal ring
    blocks outside the kernel (XLA fuses the pad/reshape into the
    surrounding program)."""
    jax, jnp, lax, pl, pltpu = _mods()
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    payload_shape = tuple(x.shape[1:])
    if n == 1:
        return x.reshape(payload_shape)
    size = int(np.prod(payload_shape)) if payload_shape else 1
    blk = -(-size // n)                # ceil
    padded = blk * n
    inner = _build_all_reduce(n, axis, blk, str(x.dtype), interpret)

    def body(t):                       # t: (1, *S)
        flat = t.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        out = inner(flat.reshape(n, blk))      # (n, blk) reduced
        return out.reshape(-1)[:size].reshape(payload_shape)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))(x)
