"""view-escape — interprocedural borrowed-view escape analysis.

The PR 6 buffer-ownership pass is strictly intraprocedural: it sees
``data, _ = conv.pack_borrow(...)`` stored on ``self`` in the SAME
function, and nothing else.  Three whole bug families slip through:

1. **Helper returns**: ``def _head(self): return self.conv.pack_borrow
   (buf)[0]`` — the helper returns a borrowed view with no name bound,
   so the old pass is silent in the helper AND in every caller that
   stores the "owned-looking" result.
2. **Stored fields / escaping parameters**: passing a borrowed view as
   a call argument is legal (the callee inherits the contract) — unless
   the callee STORES its parameter on ``self`` or queues it on a
   container that outlives the call.  Only a per-function escape
   summary, composed over the call graph, can tell the two apart.
3. **Callback captures**: a borrowed view captured by a lambda or
   nested ``def`` that is registered somewhere (``req.on_complete(...)``,
   stored, returned) executes after the borrow died.

This pass computes per-function summaries over
:mod:`ompi_tpu.analysis.callgraph` —

- ``returns_borrowed``: some return value may be a borrowed
  ``pack_borrow``/``pop_frame`` view (directly or through callees),
- ``returns_staging``: returns a live ``staging_acquire`` checkout
  (an ownership transfer: the caller owns the release),
- ``param_escapes[p]``: parameter ``p`` is stored on ``self``/a global/
  an outliving container (directly or through callees),
- ``param_released[p]``: parameter ``p`` is staging-released on some
  path (so handing a checkout to this callee pairs the acquire),

with a worklist fixpoint, then reports: escapes of helper-returned
borrowed views, borrowed arguments to escaping parameters, borrowed
captures by deferred callbacks, borrowed views returned straight from
the producing call, and helper-acquired staging checkouts that leak.

Findings the intraprocedural buffer-ownership pass already reports
(direct borrow stored/returned/queued in one function) are NOT
duplicated here: this pass only fires where the evidence crosses a
function boundary.
"""
from __future__ import annotations

import ast
from typing import Optional

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               dotted, register_pass)
from ompi_tpu.analysis.passes.buffer_ownership import (
    BORROW_PRODUCERS, MUTATORS, OWNING_METHODS, OWNING_WRAPPERS,
    _is_staging_acquire, _is_staging_release, _root_name)

#: callables that run a passed lambda synchronously — capturing a
#: borrow in their key-function is not a deferred escape
SYNC_CONSUMERS = {"sorted", "min", "max", "map", "filter", "any", "all",
                  "sum", "next"}


def _is_owning_call(call: ast.Call) -> bool:
    name = call_name(call)
    if name in OWNING_WRAPPERS:
        return True
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in OWNING_METHODS:
        return True
    if isinstance(f, ast.Attribute) and f.attr == "array":
        return True          # np.array(x, copy=...)
    return False


class _Summary:
    __slots__ = ("returns_borrowed", "returns_staging", "param_escapes",
                 "param_released")

    def __init__(self):
        self.returns_borrowed: Optional[str] = None   # producing call name
        self.returns_staging = False
        self.param_escapes: dict[str, str] = {}       # param -> where
        self.param_released: set[str] = set()

    def state(self):
        return (self.returns_borrowed, self.returns_staging,
                tuple(sorted(self.param_escapes)),
                tuple(sorted(self.param_released)))


class _Facts:
    """One function's relevant nodes, nested-def bodies excluded (their
    locals are a different frame; they are analyzed separately and
    consulted here only as capture sites)."""

    def __init__(self, info, graph):
        self.info = info
        self.assigns: list = []          # Assign nodes
        self.returns: list = []          # Return nodes
        self.calls: list = []            # (Call, resolved FuncInfo|None)
        self.nested: list = []           # FunctionDef/Lambda nodes
        self.callee_keys: set = set()
        self._walk(info.node, top=True)

    def _walk(self, node, top=False) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                self.nested.append(child)
                continue                 # don't descend: separate frame
            if isinstance(child, ast.Assign):
                self.assigns.append(child)
            elif isinstance(child, ast.Return):
                self.returns.append(child)
            elif isinstance(child, ast.Call):
                self.calls.append((child, None))
            self._walk(child)

    def resolve(self, graph) -> None:
        self.calls = [(c, graph.resolve_call(self.info, c))
                      for c, _ in self.calls]
        # AFTER resolution (the callee slots are None before): these
        # edges drive the fixpoint worklist — a summary change at a
        # callee re-queues every caller
        self.callee_keys = {callee.key for _c, callee in self.calls
                            if callee is not None}


def _argmap(call: ast.Call, callee) -> list:
    """(arg expression, callee param name) pairs for a resolved call."""
    params = list(callee.params)
    if callee.cls is not None and params and params[0] in ("self", "cls"):
        params = params[1:]
    out = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            out.append((arg, params[i]))
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in callee.params:
            out.append((kw.value, kw.arg))
    return out


@register_pass
class ViewEscapePass(AnalysisPass):
    name = "view-escape"
    description = ("interprocedural escape analysis: borrowed views "
                   "tracked through helper returns, stored fields, and "
                   "callback captures; staging checkouts tracked through "
                   "acquire/release helpers")

    # -- driver -----------------------------------------------------------
    def run(self, pkg: Package) -> list[Finding]:
        from ompi_tpu.analysis import callgraph

        graph = callgraph.build(pkg)
        facts: dict[tuple, _Facts] = {}
        for mod in pkg.modules:
            for fn, qual in mod.functions():
                info = graph.function_at(mod, qual)
                if info is None:         # nested def: summarize standalone
                    from ompi_tpu.analysis.callgraph import FuncInfo

                    info = FuncInfo(mod, qual, fn, None)
                f = _Facts(info, graph)
                f.resolve(graph)
                facts[(mod.path, qual)] = f

        summaries = self._fixpoint(facts)
        out: list[Finding] = []
        for key, f in facts.items():
            out.extend(self._check(f, summaries))
        return out

    # -- summaries --------------------------------------------------------
    def _fixpoint(self, facts) -> dict:
        summaries = {k: _Summary() for k in facts}
        # reverse edges: whose summary depends on whom
        dependents: dict[tuple, set] = {}
        for key, f in facts.items():
            for ck in f.callee_keys:
                dependents.setdefault(ck, set()).add(key)
        work = list(facts)
        rounds = 0
        while work and rounds < 20000:
            key = work.pop()
            rounds += 1
            f = facts[key]
            s = summaries[key]
            before = s.state()
            self._summarize(f, s, summaries)
            if s.state() != before:
                work.extend(k for k in dependents.get(key, ())
                            if k not in work)
        return summaries

    def _summarize(self, f: _Facts, s: _Summary, summaries) -> None:
        borrowed = self._borrowed_names(f, summaries)
        staging = self._staging_names(f, summaries)
        params = [p for p in f.info.params if p not in ("self", "cls")]
        for ret in f.returns:
            if ret.value is None:
                continue
            origin = self._borrow_origin(ret.value, borrowed, f, summaries)
            if origin is not None:
                s.returns_borrowed = origin[1]
            if self._staging_origin(ret.value, staging, f, summaries):
                s.returns_staging = True
        # parameter escapes: aliases of params count
        alias: dict[str, str] = {p: p for p in params}
        for a in f.assigns:
            if isinstance(a.value, ast.Name) and a.value.id in alias:
                for t in a.targets:
                    if isinstance(t, ast.Name):
                        alias[t.id] = alias[a.value.id]
        for a in f.assigns:
            names = {alias[n.id] for n in ast.walk(a.value)
                     if isinstance(n, ast.Name) and n.id in alias}
            if not names:
                continue
            for t in a.targets:
                root = _root_name(t)
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and root == "self":
                    for p in names:
                        s.param_escapes.setdefault(
                            p, f"stored on '{dotted(t) or 'self'}'")
        for call, callee in f.calls:
            fattr = call.func
            if isinstance(fattr, ast.Attribute) and fattr.attr in MUTATORS:
                root = _root_name(fattr.value)
                if root == "self":
                    for arg in call.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name) and n.id in alias:
                                s.param_escapes.setdefault(
                                    alias[n.id],
                                    f"queued on "
                                    f"'{dotted(fattr.value) or root}'")
            if _is_staging_release(call):
                for arg in call.args:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in alias:
                            s.param_released.add(alias[n.id])
            if callee is not None:
                cs = summaries.get(callee.key)
                if cs is None:
                    continue
                for arg, pname in _argmap(call, callee):
                    anames = {alias[n.id] for n in ast.walk(arg)
                              if isinstance(n, ast.Name)
                              and n.id in alias}
                    for p in anames:
                        if pname in cs.param_escapes:
                            s.param_escapes.setdefault(
                                p, f"escapes via {callee.qual}() "
                                   f"({cs.param_escapes[pname]})")
                        if pname in cs.param_released:
                            s.param_released.add(p)

    # -- borrow/staging dataflow within one function ----------------------
    def _borrowed_names(self, f: _Facts, summaries) -> dict:
        """name -> ("direct"|"helper", producing call name)."""
        out: dict[str, tuple] = {}
        for _ in range(4):
            changed = False
            for a in f.assigns:
                origin = self._borrow_origin(a.value, out, f, summaries)
                if origin is None:
                    continue
                tgt = a.targets[0]
                names = []
                if isinstance(tgt, ast.Name):
                    names = [tgt.id]
                elif isinstance(tgt, ast.Tuple) and tgt.elts \
                        and isinstance(tgt.elts[0], ast.Name):
                    names = [tgt.elts[0].id]    # data, flag = pack_borrow
                for n in names:
                    if n not in out:
                        out[n] = origin
                        changed = True
            if not changed:
                break
        return out

    def _borrow_origin(self, e, borrowed, f: _Facts,
                       summaries) -> Optional[tuple]:
        while isinstance(e, (ast.Subscript, ast.Starred)):
            e = e.value
        if isinstance(e, ast.Call):
            if _is_owning_call(e):
                return None
            fn = e.func
            if isinstance(fn, ast.Attribute) and fn.attr in BORROW_PRODUCERS:
                return ("direct", fn.attr)
            callee = self._callee_of(e, f)
            if callee is not None:
                cs = summaries.get(callee.key)
                if cs is not None and cs.returns_borrowed is not None:
                    return ("helper", callee.qual)
            return None
        if isinstance(e, ast.Name):
            return borrowed.get(e.id)
        if isinstance(e, ast.Attribute):
            return self._borrow_origin(e.value, borrowed, f, summaries)
        if isinstance(e, ast.Tuple):
            for elt in e.elts:
                o = self._borrow_origin(elt, borrowed, f, summaries)
                if o is not None:
                    return o
        if isinstance(e, ast.IfExp):
            return (self._borrow_origin(e.body, borrowed, f, summaries)
                    or self._borrow_origin(e.orelse, borrowed, f,
                                           summaries))
        return None

    def _staging_names(self, f: _Facts, summaries) -> dict:
        """name -> ("direct"|"helper", producing call description).
        Direct acquires feed the summary only — their local pairing is
        the buffer-ownership pass's job; leak findings here are for
        helper-acquired checkouts."""
        out: dict[str, tuple] = {}
        for a in f.assigns:
            if not isinstance(a.value, ast.Call) \
                    or not isinstance(a.targets[0], ast.Name):
                continue
            if _is_staging_acquire(a.value):
                out[a.targets[0].id] = ("direct", "staging_acquire")
                continue
            callee = self._callee_of(a.value, f)
            if callee is None:
                continue
            cs = summaries.get(callee.key)
            if cs is not None and cs.returns_staging:
                out[a.targets[0].id] = ("helper", callee.qual)
        return out

    def _staging_origin(self, e, staging, f: _Facts, summaries) -> bool:
        while isinstance(e, (ast.Subscript, ast.Starred)):
            e = e.value
        if isinstance(e, ast.Call):
            if _is_staging_acquire(e):
                return True
            callee = self._callee_of(e, f)
            if callee is not None:
                cs = summaries.get(callee.key)
                return cs is not None and cs.returns_staging
            return False
        if isinstance(e, ast.Name):
            return e.id in staging
        if isinstance(e, ast.Tuple):
            return any(self._staging_origin(x, staging, f, summaries)
                       for x in e.elts)
        return False

    def _callee_of(self, call: ast.Call, f: _Facts):
        for c, callee in f.calls:
            if c is call:
                return callee
        return None

    # -- findings ---------------------------------------------------------
    def _check(self, f: _Facts, summaries) -> list:
        out: list[Finding] = []
        mod, qual = f.info.mod, f.info.qual
        borrowed = self._borrowed_names(f, summaries)
        staging = self._staging_names(f, summaries)
        helper_borrowed = {n: o for n, o in borrowed.items()
                           if o[0] == "helper"}
        seen: set = set()

        def flag(node, msg):
            mark = (node.lineno, node.col_offset, msg[:40])
            if mark in seen:
                return
            seen.add(mark)
            out.append(Finding(self.name, mod.path, node.lineno,
                               node.col_offset, msg, qual))

        params = set(f.info.params) - {"self", "cls"}

        # 1. escapes of helper-returned borrowed views (the shapes the
        #    intraprocedural pass checks, for names it cannot see)
        for ret in f.returns:
            if ret.value is None:
                continue
            e = ret.value
            while isinstance(e, (ast.Subscript, ast.Starred)):
                e = e.value
            if isinstance(e, ast.Call) and not _is_owning_call(e):
                fn = e.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in BORROW_PRODUCERS:
                    flag(ret, f"returns a borrowed view straight from "
                              f"'{fn.attr}()' — the view dies with this "
                              "call; copy (bytes()/.tobytes()) or keep "
                              "the consumption inside this function")
                    continue
            for n in ast.walk(ret.value):
                if isinstance(n, ast.Name) and n.id in helper_borrowed \
                        and not self._owned_in(ret.value, n):
                    flag(ret, f"borrowed view '{n.id}' (from "
                              f"{helper_borrowed[n.id][1]}()) is "
                              "returned without an owning copy — the "
                              "helper's borrow contract rides through "
                              "this return")
        for a in f.assigns:
            vals = [n for n in ast.walk(a.value)
                    if isinstance(n, ast.Name) and n.id in helper_borrowed
                    and not self._owned_in(a.value, n)]
            if vals:
                for t in a.targets:
                    root = _root_name(t)
                    if isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and (root == "self" or root in params):
                        flag(a, f"borrowed view '{vals[0].id}' (from "
                                f"{helper_borrowed[vals[0].id][1]}()) is "
                                f"stored on '{root}' without an owning "
                                "copy")
        for call, callee in f.calls:
            fattr = call.func
            if isinstance(fattr, ast.Attribute) and fattr.attr in MUTATORS:
                root = _root_name(fattr.value)
                if root == "self" or root in params:
                    for arg in call.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name) \
                                    and n.id in helper_borrowed \
                                    and not self._owned_in(arg, n):
                                flag(call,
                                     f"borrowed view '{n.id}' (from "
                                     f"{helper_borrowed[n.id][1]}()) is "
                                     "queued on "
                                     f"'{dotted(fattr.value) or root}'")

        # 2. borrowed argument to an escaping parameter (any origin)
        for call, callee in f.calls:
            if callee is None:
                continue
            cs = summaries.get(callee.key)
            if cs is None or not cs.param_escapes:
                continue
            for arg, pname in _argmap(call, callee):
                if pname not in cs.param_escapes:
                    continue
                for n in ast.walk(arg):
                    if isinstance(n, ast.Name) and n.id in borrowed \
                            and not self._owned_in(arg, n):
                        flag(call,
                             f"borrowed view '{n.id}' passed to "
                             f"{callee.qual}() whose parameter "
                             f"'{pname}' escapes "
                             f"({cs.param_escapes[pname]}) — the view "
                             "outlives its producing call")

        # 3. borrowed captured by a deferred callback (any origin)
        out.extend(self._check_captures(f, borrowed))

        # 4. helper-acquired staging checkouts must pair
        out.extend(self._check_staging_leaks(f, staging, summaries))
        return out

    @staticmethod
    def _owned_in(tree, name_node) -> bool:
        """Is ``name_node`` consumed by an owning wrapper inside tree?"""
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_owning_call(node):
                for sub in ast.walk(node):
                    if sub is name_node:
                        return True
            if isinstance(node, ast.Attribute) \
                    and node.attr in OWNING_METHODS:
                for sub in ast.walk(node):
                    if sub is name_node:
                        return True
        return False

    def _check_captures(self, f: _Facts, borrowed) -> list:
        out = []
        mod, qual = f.info.mod, f.info.qual
        for nested in f.nested:
            body = nested.body if isinstance(nested, ast.Lambda) \
                else nested
            local = {a.arg for a in nested.args.args
                     + nested.args.kwonlyargs + nested.args.posonlyargs}
            captured = sorted({n.id for n in ast.walk(
                body if isinstance(body, ast.AST) else nested)
                if isinstance(n, ast.Name) and n.id in borrowed
                and n.id not in local})
            if not captured:
                continue
            if not self._nested_escapes(f, nested):
                continue
            kind = "lambda" if isinstance(nested, ast.Lambda) \
                else f"'{nested.name}'"
            out.append(Finding(
                self.name, mod.path, nested.lineno, nested.col_offset,
                f"borrowed view '{captured[0]}' is captured by deferred "
                f"callback {kind} that outlives this call — it will run "
                "after the borrow died; copy first", qual))
        return out

    def _nested_escapes(self, f: _Facts, nested) -> bool:
        """Does the nested def/lambda outlive the call?  Stored,
        returned, or passed to any call except known-synchronous
        consumers."""
        name = getattr(nested, "name", None)
        # a lambda handed straight to a synchronous consumer (sorted
        # key=, max, map...) runs inside that call — never deferred,
        # wherever the consumer call itself appears
        for call, _callee in f.calls:
            args = list(call.args) + [kw.value for kw in call.keywords]
            if nested in args and call_name(call).rsplit(
                    ".", 1)[-1] in SYNC_CONSUMERS:
                return False

        def mentions(tree) -> bool:
            for n in ast.walk(tree):
                if n is nested:
                    return True
                if name and isinstance(n, ast.Name) and n.id == name:
                    return True
            return False

        for ret in f.returns:
            if ret.value is not None and mentions(ret.value):
                return True
        for a in f.assigns:
            if mentions(a.value):
                for t in a.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return True
        for call, _callee in f.calls:
            cname = call_name(call)
            if cname.rsplit(".", 1)[-1] in SYNC_CONSUMERS:
                continue
            for arg in list(call.args) + [kw.value for kw in
                                          call.keywords]:
                if mentions(arg):
                    return True
        return False

    def _check_staging_leaks(self, f: _Facts, staging, summaries) -> list:
        out = []
        if not staging:
            return out
        mod, qual = f.info.mod, f.info.qual
        released: set = set()
        transferred: set = set()
        for call, callee in f.calls:
            if _is_staging_release(call):
                for arg in call.args:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            released.add(n.id)
            elif callee is not None:
                cs = summaries.get(callee.key)
                if cs is None:
                    continue
                for arg, pname in _argmap(call, callee):
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            if pname in cs.param_released:
                                released.add(n.id)
                            if pname in cs.param_escapes:
                                transferred.add(n.id)
        for ret in f.returns:
            if ret.value is not None:
                transferred.update(n.id for n in ast.walk(ret.value)
                                   if isinstance(n, ast.Name))
        for a in f.assigns:
            for t in a.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    transferred.update(n.id for n in ast.walk(a.value)
                                       if isinstance(n, ast.Name))
        for name, (origin, producer) in staging.items():
            if origin != "helper":
                continue         # direct pairing: buffer-ownership pass
            if name in released or name in transferred:
                continue
            # find the producing assign for the location
            node = f.info.node
            for a in f.assigns:
                if isinstance(a.targets[0], ast.Name) \
                        and a.targets[0].id == name:
                    node = a
                    break
            out.append(Finding(
                self.name, mod.path, node.lineno,
                getattr(node, "col_offset", 0),
                f"staging checkout '{name}' (acquired through "
                f"{producer}()) is never released, returned, or stored "
                "— pool accounting leaks on every call", qual))
        return out
