"""hot-path — functions tagged ``@hot_path`` keep their allocation budget.

The ``@hot_path`` decorator (``ompi_tpu.runtime.hotpath``) is identity at
runtime; its value is this pass.  Tagged functions — progress-loop drain,
btl send/recv, convertor pack, coll dispatch — run per message or per
progress tick, so per-call allocation sugar is a measurable tax:

- ``pickle.dumps``/``loads`` (serialize on the data path — the fast
  header exists so the common frames never pay this),
- f-strings / ``str.format`` / ``"%" % args`` (string building),
- list-literal concatenation (``x + [y]`` allocates twice).

Error paths are cold: nodes inside ``raise`` statements and ``except``
handler bodies are exempt.  Separately, a tagged function must not
``raise struct.error`` — wire-framing failures go through the loud
``show_help`` guard (the frame-too-large convention), not a bare struct
exception the caller cannot attribute.
"""
from __future__ import annotations

import ast

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               register_pass)


def _is_hot(fn) -> bool:
    for dec in fn.decorator_list:
        name = dec.id if isinstance(dec, ast.Name) else \
            dec.attr if isinstance(dec, ast.Attribute) else None
        if name == "hot_path":
            return True
    return False


def _cold_nodes(fn) -> set:
    """ids of nodes inside raise statements, except handler bodies, and
    ``sanitizer.fail(...)`` calls (fail raises by contract)."""
    cold: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Raise, ast.ExceptHandler)) or (
                isinstance(node, ast.Call)
                and call_name(node) == "sanitizer.fail"):
            for sub in ast.walk(node):
                cold.add(id(sub))
    return cold


@register_pass
class HotPathPass(AnalysisPass):
    name = "hot-path"
    description = ("@hot_path functions may not allocate via pickle / "
                   "format-string / list-concat, nor raise bare "
                   "struct.error instead of the show_help guard")

    def run(self, pkg: Package) -> list[Finding]:
        out: list[Finding] = []
        for mod in pkg.modules:
            for fn, qual in mod.functions():
                if _is_hot(fn):
                    out.extend(self._check(mod, fn, qual))
        return out

    def _check(self, mod, fn, qual) -> list[Finding]:
        cold = _cold_nodes(fn)
        out = []

        def flag(node, what):
            out.append(Finding(
                self.name, mod.path, node.lineno, node.col_offset,
                f"@hot_path function allocates via {what} — this runs "
                "per message/tick; hoist it, use the fast-header/"
                "preallocated path, or drop the @hot_path tag", qual))

        for node in ast.walk(fn):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = call_name(exc) if isinstance(exc, ast.Call) \
                    else (exc.attr if isinstance(exc, ast.Attribute)
                          else getattr(exc, "id", ""))
                if name and (name == "struct.error"
                             or name.endswith(".error") and
                             name.split(".")[0] == "struct"):
                    out.append(Finding(
                        self.name, mod.path, node.lineno, node.col_offset,
                        "@hot_path function raises bare struct.error — "
                        "route wire-framing failures through the "
                        "show_help guard so the user sees an "
                        "attributable diagnostic", qual))
                continue
            if id(node) in cold:
                continue
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name.startswith("pickle."):
                    flag(node, f"{name}()")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "format" \
                        and isinstance(node.func.value, ast.Constant) \
                        and isinstance(node.func.value.value, str):
                    flag(node, "str.format()")
            elif isinstance(node, ast.JoinedStr):
                flag(node, "an f-string")
            elif isinstance(node, ast.BinOp):
                if isinstance(node.op, ast.Mod) \
                        and isinstance(node.left, ast.Constant) \
                        and isinstance(node.left.value, str):
                    flag(node, "'%'-formatting")
                elif isinstance(node.op, ast.Add) \
                        and (isinstance(node.left, ast.List)
                             or isinstance(node.right, ast.List)):
                    flag(node, "list concatenation")
        return out
