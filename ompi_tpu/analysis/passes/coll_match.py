"""collective-matching — rank-divergent collectives are deadlocks.

The classic MPI hang: a collective call reachable under a
rank-conditional branch with no matching call on the other arm.  Rank 0
enters ``comm.gather``; every other rank took the else-branch and is
already three statements ahead — the job stops making progress with no
error anywhere.

Matching rules (tuned against this package's own collectives — the
basic/han modules are a zoo of *legal* rank-conditional shapes):

1. An ``if`` whose test reads a rank splits execution; the two sides
   are the explicit arms, or — when the body ends in ``return`` with no
   ``else`` — the body vs the *continuation* (the statements the
   non-returning ranks fall through to, accumulated through enclosing
   blocks).  ``reduce-to-root + if rank==0: return bcast(...)  /
   return bcast(...)`` therefore matches.
2. Calls are matched per **communicator identity**, not just per
   method: the identity is the call receiver, or the first argument
   when the receiver is a module-style collective provider
   (``self.bcast(comm, ...)``/``_basic.bcast(comm, ...)``).
3. Only identities the branch test actually ranks over are matched:
   ``if low.rank == 0: self._leaders.allreduce(...)`` is the
   hierarchical-collective shape — ``_leaders`` exists only on the
   ranks that took the branch, so it has no matching obligation.  A
   bare ``rank`` name is resolved through ``rank = comm.rank``
   assignments; when it cannot be resolved, every identity must match
   (conservative).
4. Arms that ``raise`` (or call a ``*abort*`` helper) are exempt: an
   erroring rank is torn down by the errhandler, not matched.

Point-to-point calls (send/recv/isend...) are deliberately NOT
matched: asymmetry is their normal shape.  Receivers that are numerics
namespaces (``np``/``jax``/``functools``/...) never count, so
``functools.reduce`` and ``np.add.reduce`` are not collectives.
"""
from __future__ import annotations

import ast
from typing import Optional

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, dotted,
                               register_pass)

#: blocking + nonblocking collective method names (the nonblocking ones
#: diverge at their wait, but the call itself must still be symmetric)
COLLECTIVES = {
    "allreduce", "reduce", "bcast", "barrier", "allgather", "allgatherv",
    "gather", "gatherv", "scatter", "scatterv", "alltoall", "alltoallv",
    "alltoallw", "reduce_scatter", "reduce_scatter_block", "scan",
    "exscan",
    "iallreduce", "ireduce", "ibcast", "ibarrier", "iallgather",
    "igather", "iscatter", "ialltoall", "iscan", "iexscan",
}

#: receivers that are numerics/utility namespaces, never communicators
NON_COMM_RECEIVERS = {"np", "numpy", "jnp", "jax", "lax", "functools",
                      "operator", "math", "itertools", "torch", "plt"}

RANK_NAMES = ("rank", "myrank", "my_rank")


def _rank_aliases(fn) -> dict:
    """bare name -> comm dotted name, from ``rank = comm.rank`` /
    ``rank = comm.rank()`` assignments."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Name)):
            continue
        v = node.value
        if isinstance(v, ast.Call):
            v = v.func
        if isinstance(v, ast.Attribute) and v.attr in RANK_NAMES:
            base = dotted(v.value)
            if base:
                out[node.targets[0].id] = base
    return out


def _tested_identities(test, aliases) -> Optional[set]:
    """Dotted names of the comms whose rank the test reads; None when a
    bare rank name cannot be resolved (then everything must match)."""
    out: set[str] = set()
    unresolved = False
    found = False
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in RANK_NAMES:
            found = True
            base = dotted(node.value)
            if base:
                out.add(base)
            else:
                unresolved = True
        elif isinstance(node, ast.Name) and node.id in RANK_NAMES:
            found = True
            base = aliases.get(node.id)
            if base:
                out.add(base)
            else:
                unresolved = True
    if not found:
        return set()
    return None if unresolved else out


def _collective_calls(stmts) -> list:
    """(method, identity receiver, first-arg dotted, node) for every
    collective call in the statement list, nested defs excluded."""
    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr in COLLECTIVES:
                recv = dotted(child.func.value) or ""
                root = recv.split(".")[0] if recv else ""
                if root not in NON_COMM_RECEIVERS:
                    arg0 = dotted(child.args[0]) if child.args else None
                    out.append((child.func.attr, recv, arg0 or "", child))
            walk(child)

    for stmt in stmts:
        walk(stmt)
    return out


def _arm_exits_with_error(stmts) -> bool:
    """An arm that raises (or aborts) is an error path, not a matching
    obligation — the errhandler tears the rank down."""
    for stmt in stmts:
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            name = dotted(stmt.value.func) or ""
            if "abort" in name.rsplit(".", 1)[-1].lower():
                return True
    return False


def _terminal_return(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


@register_pass
class CollectiveMatchingPass(AnalysisPass):
    name = "collective-matching"
    description = ("collectives reachable under rank-conditional "
                   "branches must have a matching call on the other "
                   "arm (or the fall-through continuation) on the "
                   "same communicator")

    def run(self, pkg: Package) -> list[Finding]:
        out: list[Finding] = []
        for mod in pkg.modules:
            for fn, qual in mod.functions():
                aliases = _rank_aliases(fn)
                self._scan_block(mod, fn.body, [], aliases, qual, out,
                                 set())
        return out

    def _scan_block(self, mod, stmts, rest_outer, aliases, qual, out,
                    handled) -> None:
        for i, stmt in enumerate(stmts):
            rest_here = stmts[i + 1:] + rest_outer
            if isinstance(stmt, ast.If) and id(stmt) not in handled \
                    and _tested_identities(stmt.test, aliases) != set():
                self._check_chain(mod, stmt, rest_here, aliases, qual,
                                  out, handled)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    # a `return` inside any nested block exits the
                    # function, so the continuation carries through
                    self._scan_block(mod, sub, rest_here, aliases,
                                     qual, out, handled)
            for h in getattr(stmt, "handlers", ()) or ():
                self._scan_block(mod, h.body, rest_here, aliases,
                                 qual, out, handled)

    @staticmethod
    def _flatten_chain(ifnode, continuation):
        """An if/elif/.../else ladder as a flat arm list.  Returns
        (arms, tests, via): the final implicit arm is the fall-through
        continuation when every explicit arm terminal-returns (then
        ``via`` carries the chain's line for the message), the empty
        arm otherwise."""
        arms, tests = [], []
        node = ifnode
        while True:
            tests.append(node.test)
            arms.append(node.body)
            if len(node.orelse) == 1 and isinstance(node.orelse[0],
                                                    ast.If):
                node = node.orelse[0]
                continue
            break
        via = None
        if node.orelse:
            arms.append(node.orelse)
        elif all(_terminal_return(a) for a in arms):
            arms.append(continuation)
            via = ifnode.lineno
        else:
            arms.append([])
        return arms, tests, via

    def _check_chain(self, mod, ifnode, continuation, aliases, qual,
                     out, handled) -> None:
        """Compare every arm of the (possibly elif-laddered) chain: a
        rank-role ladder where each rank calls the same collectives is
        legal; a call with no counterpart on some sibling arm is the
        deadlock."""
        arms, tests, via = self._flatten_chain(ifnode, continuation)
        # the whole ladder is handled here: the nested elif Ifs must
        # not be re-compared arm-vs-tail by the block scan
        node = ifnode
        while len(node.orelse) == 1 and isinstance(node.orelse[0],
                                                   ast.If):
            node = node.orelse[0]
            handled.add(id(node))
        arms = [a for a in arms if not _arm_exits_with_error(a)]
        if len(arms) < 2:
            return
        tested: Optional[set] = set()
        for t in tests:
            ids = _tested_identities(t, aliases)
            if ids is None:
                tested = None
                break
            tested |= ids

        def key(call) -> Optional[tuple]:
            name, recv, arg0, _node = call
            if tested is None:
                return (name, recv or arg0)
            if recv in tested:
                return (name, recv)
            if arg0 in tested:
                return (name, arg0)
            return None          # membership-scoped sub-communicator

        calls = [_collective_calls(a) for a in arms]
        sets = []
        for arm_calls in calls:
            counts: dict[tuple, int] = {}
            for c in arm_calls:
                k = key(c)
                if k is not None:
                    counts[k] = counts.get(k, 0) + 1
            sets.append(counts)
        if all(s == sets[0] for s in sets[1:]):
            return
        last = len(arms) - 1
        for i, arm_calls in enumerate(calls):
            flagged: dict[tuple, int] = {}
            for call in arm_calls:
                k = key(call)
                if k is None:
                    continue
                floor = min(s.get(k, 0)
                            for j, s in enumerate(sets) if j != i)
                excess = sets[i].get(k, 0) - floor
                if excess <= 0 or flagged.get(k, 0) >= excess:
                    continue
                flagged[k] = flagged.get(k, 0) + 1
                name, _recv, _arg0, node = call
                comm = k[1]
                where = f"on '{comm}'" if comm else ""
                if i == last and via is not None:
                    msg = (f"collective '{name}' {where} is skipped by "
                           f"the rank-conditional return at line {via} "
                           "— only a subset of ranks reaches it: "
                           "deadlock unless every rank takes the same "
                           "path")
                else:
                    msg = (f"collective '{name}' {where} is reachable "
                           "on only some arms of a rank-conditional "
                           f"branch (line {ifnode.lineno}) with no "
                           f"matching '{name}' on every other arm — "
                           "ranks taking another path never enter it: "
                           "deadlock")
                out.append(Finding(self.name, mod.path, node.lineno,
                                   node.col_offset, msg, qual))
