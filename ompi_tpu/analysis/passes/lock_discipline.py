"""lock-discipline — declared guards hold, no blocking under them, no cycles.

Three checks over the ``_guarded_by`` annotation convention:

1. **Guarded mutations**: a class declaring
   ``_guarded_by = {"outq": "send_lock"}`` (or a module declaring
   ``_GUARDED_BY = {"_callbacks": "_lock"}``) promises that every
   mutation of that structure happens inside ``with <base>.<lock>:`` on
   the *same base object*.  Methods whose name ends in ``_locked`` (and
   ``__init__``, where the object is not yet shared) are assumed to run
   with the lock held by contract.  Reads are deliberately not checked —
   the codebase uses GIL-atomic snapshot reads throughout.

2. **No blocking calls under a declared lock**: ``time.sleep``, blocking
   socket ops (``sendall``/``connect``/``accept``/``create_connection``/
   ``recv``), and module-local helpers that contain one (depth-1
   closure — how ``coord._send_frame`` is known to block) must not run
   while a declared guard lock is held; a stalled peer would freeze
   every other thread contending on the structure.  ``.wait``/
   ``.wait_for`` are exempt (Condition.wait releases the lock), as are
   the nonblocking-by-contract ``sendmsg``/``recv_into``.

3. **Lock-order acyclicity**: lexically nested ``with`` acquisitions of
   declared locks form a package-wide edge set; a cycle is a deadlock
   waiting for the right interleaving.
"""
from __future__ import annotations

import ast
from typing import Optional

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               const_str, dotted, register_pass)

MUTATORS = {"append", "appendleft", "extend", "insert", "remove", "pop",
            "popleft", "popitem", "clear", "add", "discard", "update",
            "setdefault", "push", "move_to_end"}

BLOCKING_ATTRS = {"sleep", "sendall", "accept", "connect",
                  "create_connection", "create_server", "getaddrinfo",
                  "recv"}
EXEMPT_ATTRS = {"wait", "wait_for", "sendmsg", "recv_into"}


def _guard_maps(mod):
    """(attr->lock merged across classes, global->lock, declared lock
    names, conflict findings).

    The attr map is module-wide ON PURPOSE: guarded structures are
    mutated through any base object (``conn.outq`` from TcpBtl
    methods), so the attribute name is the contract key.  That makes
    two classes declaring the SAME attr under DIFFERENT locks ambiguous
    — the pass reports the collision instead of silently letting the
    later declaration win (which would check the first class's
    mutations against the wrong lock)."""
    attr_guards: dict[str, str] = {}
    global_guards: dict[str, str] = {}
    conflicts: list[Finding] = []

    def read_dict(node) -> dict:
        out = {}
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                ks, vs = const_str(k), const_str(v)
                if ks and vs:
                    out[ks] = vs
        return out

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == "_guarded_by"
                                for t in stmt.targets):
                    for attr, lock in read_dict(stmt.value).items():
                        have = attr_guards.get(attr)
                        if have is not None and have != lock:
                            conflicts.append(Finding(
                                "lock-discipline", mod.path, stmt.lineno,
                                stmt.col_offset,
                                f"ambiguous _guarded_by: attribute "
                                f"'{attr}' is declared guarded by "
                                f"'{have}' elsewhere in this module and "
                                f"by '{lock}' in class '{node.name}' — "
                                "guard keys are module-wide, rename one "
                                "attribute", node.name))
                        attr_guards[attr] = lock
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                        for t in stmt.targets):
            global_guards.update(read_dict(stmt.value))
    locks = set(attr_guards.values()) | set(global_guards.values())
    return attr_guards, global_guards, locks, conflicts


def _blocking_helpers(mod) -> set:
    """Module-level functions that (directly) make a blocking call."""
    helpers = set()
    for stmt in mod.tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in BLOCKING_ATTRS:
                helpers.add(stmt.name)
                break
    return helpers


def _lock_pairs(withstmt) -> list:
    """(base, lockname) pairs a With statement acquires."""
    pairs = []
    for item in withstmt.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name):
            pairs.append((ctx.value.id, ctx.attr))
        elif isinstance(ctx, ast.Name):
            pairs.append((None, ctx.id))
    return pairs


@register_pass
class LockDisciplinePass(AnalysisPass):
    name = "lock-discipline"
    description = ("_guarded_by structures mutate only under their lock, "
                   "no blocking call while a declared lock is held, "
                   "package lock-order graph is acyclic")

    def run(self, pkg: Package) -> list[Finding]:
        out: list[Finding] = []
        edges: dict[tuple, tuple] = {}   # (from, to) -> (mod, line)
        for mod in pkg.modules:
            attr_guards, global_guards, locks, conflicts = _guard_maps(mod)
            out.extend(conflicts)
            blockers = _blocking_helpers(mod) if locks else set()
            for fn, qual in mod.functions():
                exempt = (fn.name.endswith("_locked")
                          or fn.name == "__init__")
                ctx = _FnChecker(self.name, mod, qual, attr_guards,
                                 global_guards, locks, blockers, exempt)
                ctx.visit_body(fn.body, frozenset())
                out.extend(ctx.findings)
                for edge, where in ctx.edges.items():
                    edges.setdefault(edge, where)
        out.extend(self._check_cycles(edges))
        return out

    def _check_cycles(self, edges) -> list:
        graph: dict[str, set] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        out, state = [], {}

        def dfs(node, stack):
            state[node] = 1
            for nxt in graph.get(node, ()):
                if state.get(nxt) == 1:
                    cyc = stack[stack.index(nxt):] + [nxt] \
                        if nxt in stack else [node, nxt]
                    mod, line = edges[(node, nxt)]
                    out.append(Finding(
                        self.name, mod.path, line, 0,
                        "lock-acquisition-order cycle: "
                        + " -> ".join(cyc)
                        + " (deadlock under the right interleaving)",
                        ""))
                elif state.get(nxt) is None:
                    dfs(nxt, stack + [nxt])
            state[node] = 2

        for node in sorted(graph):
            if state.get(node) is None:
                dfs(node, [node])
        return out


class _FnChecker:
    """Walks one function body carrying the lexically-held lock set."""

    def __init__(self, rule, mod, qual, attr_guards, global_guards,
                 locks, blockers, exempt):
        self.rule = rule
        self.mod = mod
        self.qual = qual
        self.attr_guards = attr_guards
        self.global_guards = global_guards
        self.locks = locks
        self.blockers = blockers
        self.exempt = exempt
        self.aliases: dict[str, tuple] = {}   # local -> (base, attr)
        self.findings: list[Finding] = []
        self.edges: dict[tuple, tuple] = {}
        self.seen: set = set()

    # -- walk -------------------------------------------------------------
    def visit_body(self, body, held: frozenset) -> None:
        for stmt in body:
            self.visit_stmt(stmt, held)

    def visit_stmt(self, stmt, held: frozenset) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pairs = _lock_pairs(stmt)
            for base, lock in pairs:
                if lock in self.locks:
                    for hb, hl in held:
                        if hl in self.locks and hl != lock:
                            self.edges.setdefault(
                                (hl, lock), (self.mod, stmt.lineno))
            self.visit_body(stmt.body, held | set(pairs))
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return   # nested defs execute later, not under these locks
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Attribute) \
                and isinstance(stmt.value.value, ast.Name):
            # alias: q = conn.outq — later q.popleft() is conn.outq's
            self.aliases[stmt.targets[0].id] = (
                stmt.value.value.id, stmt.value.attr)
        self.check_stmt(stmt, held)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.excepthandler)):
                self.visit_stmt(child, held)
            elif isinstance(child, ast.expr):
                self.check_expr(child, held)

    # -- checks -----------------------------------------------------------
    def check_stmt(self, stmt, held) -> None:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for tgt in targets:
                self.check_mutation_target(tgt, held, stmt)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self.check_mutation_target(tgt, held, stmt)

    def check_expr(self, expr, held) -> None:
        # prune lambda bodies: they execute later, not under these locks
        deferred: set = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node.body):
                    deferred.add(id(sub))
        for node in ast.walk(expr):
            if id(node) in deferred or not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                key = self.resolve(f.value)
                if key is not None:
                    self.require(key, held, node,
                                 f"{dotted(f.value) or key[1]}.{f.attr}()")
            self.check_blocking(node, held)

    def check_mutation_target(self, tgt, held, stmt) -> None:
        if isinstance(tgt, ast.Name) and isinstance(stmt, ast.Assign):
            # a plain Assign to a bare name rebinds a local (or, for a
            # guarded module global, rewrites module state — only that
            # case is a mutation; alias rebinding is not)
            if tgt.id in self.global_guards:
                self.require((None, tgt.id, self.global_guards[tgt.id]),
                             held, stmt, tgt.id)
            return
        key = self.resolve(tgt)
        if key is not None:
            self.require(key, held, stmt, dotted(tgt) or key[1])

    def resolve(self, node) -> Optional[tuple]:
        """(base, attr, lock) for a guarded attr chain, (None, name, lock)
        for a guarded module global, else None."""
        n = node
        while isinstance(n, (ast.Attribute, ast.Subscript, ast.Call)):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.attr in self.attr_guards:
                return (n.value.id, n.attr, self.attr_guards[n.attr])
            n = n.func if isinstance(n, ast.Call) else n.value
        if isinstance(n, ast.Name):
            if n.id in self.global_guards:
                return (None, n.id, self.global_guards[n.id])
            alias = self.aliases.get(n.id)
            if alias is not None and alias[1] in self.attr_guards:
                return (alias[0], alias[1], self.attr_guards[alias[1]])
        return None

    def require(self, key, held, node, what) -> None:
        if self.exempt:
            return
        base, name, lock = key
        if (base, lock) in held or (None, lock) in held:
            return
        mark = (node.lineno, node.col_offset, name)
        if mark in self.seen:
            return
        self.seen.add(mark)
        owner = f"{base}." if base else ""
        self.findings.append(Finding(
            self.rule, self.mod.path, node.lineno, node.col_offset,
            f"'{what}' mutates '{name}' (declared guarded by "
            f"'{lock}') outside 'with {owner}{lock}:'", self.qual))

    def check_blocking(self, call, held) -> None:
        declared_held = [l for _b, l in held if l in self.locks]
        if not declared_held:
            return
        f = call.func
        name = None
        if isinstance(f, ast.Attribute):
            if f.attr in EXEMPT_ATTRS:
                return
            if f.attr in BLOCKING_ATTRS:
                name = call_name(call) or f.attr
        elif isinstance(f, ast.Name) and f.id in self.blockers:
            name = f.id
        if name is None:
            return
        mark = (call.lineno, call.col_offset, "blocking")
        if mark in self.seen:
            return
        self.seen.add(mark)
        self.findings.append(Finding(
            self.rule, self.mod.path, call.lineno, call.col_offset,
            f"blocking call '{name}' while holding declared lock(s) "
            f"{', '.join(sorted(set(declared_held)))} — a stalled peer "
            "freezes every thread contending on the guarded structure",
            self.qual))
