"""mpi-typestate — MPI object lifecycles as checkable automata.

MPI objects carry protocol state the type system cannot see: a
persistent request is ``inactive -> (start) -> active -> (wait/test) ->
inactive -> ... -> (free)``, ``Pready`` is legal only on an *active
partitioned send* request, a passive-target epoch opened by
``Win.lock`` must close with ``Win.unlock``, and every
``instance.acquire``/``Session.init`` must pair with its release.  The
runtime raises on SOME of these (loud ERR_REQUEST on a bad Pready), but
leaks — a started request nobody waits on, an epoch nobody closes — are
silent until the hang.

This pass encodes the automata and walks every function, tracking
locals whose creation it can see.  The automata themselves are
**declared in the API modules** (``_TYPESTATE`` dicts in
``api/request.py`` and ``api/win.py``) so the contract lives next to
the code it describes; built-in defaults cover runs over trees that
don't carry the annotation.

Checks:

- **request lifecycle**: double free, use-after-free, double start
  without an intervening completion, ``Pready`` on recv-side /
  non-partitioned / inactive requests, ``Parrived`` on the send side,
  started-but-never-completed and never-escaping requests (leaks),
  nonblocking requests that are never completed.
- **win epochs**: ``unlock``/``unlock_all`` with no open epoch,
  ``lock`` left open at function exit, ``flush`` outside a
  passive-target epoch, PSCW ``start``/``complete`` + ``post``/``wait``
  pairing.
- **refcount pairing**: ``instance.acquire()`` without a comparable
  ``instance.release()`` (and ``Session.init`` without ``finalize``)
  when the handle does not escape the function.
- **guarded handoff** (the PR 6 staging-checkout family): a value
  popped from a ``_guarded_by``-declared structure under its lock must
  be re-registered into its destination structure *inside the same
  critical section*.  Re-registering in a later ``with`` block — or
  with no lock at all — leaves a window where the object is observable
  as neither free nor checked out, which is exactly how the staging
  pool double-release aliased live checkouts.  The re-registration is
  tracked **through helper calls** (``self._checkout(raw, ...)``) via
  per-function stores-param-into-guarded summaries.

State tracking is deliberately conservative: ops are sequenced only
when they are loop-consistent and on lexically comparable paths (one
branch arm is never sequenced against its sibling), and any escape —
return, store, yield, or passing the object to a call the resolver
can't prove harmless — ends lifecycle tracking for that local.
"""
from __future__ import annotations

import ast
from typing import Optional

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               const_str, register_pass)
from ompi_tpu.analysis.passes.lock_discipline import _guard_maps, _lock_pairs

#: request automaton defaults (overridden by api/request.py _TYPESTATE)
REQUEST_DEFAULTS = {
    "create_inactive": ["send_init", "recv_init", "psend_init",
                        "precv_init", "pallreduce_init"],
    "create_active": ["isend", "irecv"],
    "send_side": ["send_init", "psend_init", "isend", "pallreduce_init"],
    "partitioned": ["psend_init", "precv_init", "pallreduce_init"],
    "start": ["start"],
    "start_many": ["start_all", "startall"],
    # on_complete registers a completion callback: the caller IS
    # observing completion, just asynchronously
    "complete": ["wait", "test", "get_status", "on_complete"],
    "complete_many": ["waitall", "waitany", "waitsome", "testall",
                      "testany", "testsome"],
    "free": ["free"],
    "pready": ["pready", "pready_range", "pready_list"],
    "parrived": ["parrived", "parrived_range"],
}

#: win automaton defaults (overridden by api/win.py _TYPESTATE)
WIN_DEFAULTS = {
    "create": ["Win.create", "Win.allocate", "Win.allocate_shared",
               "Win.create_dynamic"],
    "passive_open": ["lock", "lock_all"],
    "passive_close": ["unlock", "unlock_all"],
    "pscw": {"start": "complete", "post": "wait"},
    "in_passive": ["flush", "flush_all"],
}

#: refcount pairs: acquire-call suffix -> (release suffix, is_method)
REFCOUNT_PAIRS = {
    "instance.acquire": ("instance.release", False),
    "Session.init": ("finalize", True),
}

POPPERS = {"pop", "popleft", "popitem"}


def _propagate_derived(fn, seeds) -> dict:
    """name -> root-seed map: seeds plus every local assigned from an
    expression mentioning a seed (``view = raw[:n].view(d)`` makes
    ``view`` carry ``raw``'s obligation).  Bounded fixpoint — chains in
    real code are 1-2 assignments deep."""
    derived = {s: s for s in seeds}
    for _ in range(3):
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Name):
                src = {derived[n.id] for n in ast.walk(node.value)
                       if isinstance(n, ast.Name) and n.id in derived}
                t = node.targets[0].id
                if src and t not in derived:
                    derived[t] = sorted(src)[0]
                    changed = True
        if not changed:
            break
    return derived


def _load_typestate(pkg: Package, suffix: str, defaults: dict) -> dict:
    """Read a ``_TYPESTATE`` dict literal from the module whose path ends
    with ``suffix``; fall back to the built-in defaults."""
    mod = pkg.find(suffix)
    if mod is None:
        return defaults
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_TYPESTATE"
                        for t in stmt.targets) \
                and isinstance(stmt.value, ast.Dict):
            out = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                key = const_str(k)
                if key is None:
                    continue
                if isinstance(v, (ast.List, ast.Tuple)):
                    out[key] = [s for s in map(const_str, v.elts) if s]
                elif isinstance(v, ast.Dict):
                    out[key] = {const_str(dk): const_str(dv)
                                for dk, dv in zip(v.keys, v.values)
                                if const_str(dk) and const_str(dv)}
            merged = dict(defaults)
            merged.update(out)
            return merged
    return defaults


# ---------------------------------------------------------------------------
# lexical path structure: arm paths + loop membership
# ---------------------------------------------------------------------------

class _PathMap:
    """id(node) -> (armpath tuple, frozenset of enclosing loop ids)."""

    def __init__(self, fn):
        self.arm: dict[int, tuple] = {}
        self.loops: dict[int, frozenset] = {}
        self._walk(fn, (), frozenset())

    def _walk(self, node, path, loops) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue               # different frame
            cpath, cloops = path, loops
            if isinstance(node, ast.If):
                arm = 0 if child in node.body else \
                    (1 if child in node.orelse else None)
                if arm is not None:
                    cpath = path + ((id(node), arm),)
            elif isinstance(node, ast.Try):
                arm = 0 if child in node.body else \
                    (1 if child in node.handlers else None)
                if arm is not None:
                    cpath = path + ((id(node), arm),)
            elif isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                if child in node.body:
                    cloops = loops | {id(node)}
            self.arm[id(child)] = cpath
            self.loops[id(child)] = cloops
            self._walk(child, cpath, cloops)

    def comparable(self, a, b) -> bool:
        pa = self.arm.get(id(a), ())
        pb = self.arm.get(id(b), ())
        n = min(len(pa), len(pb))
        return pa[:n] == pb[:n]

    def same_loops(self, a, b) -> bool:
        return self.loops.get(id(a), frozenset()) \
            == self.loops.get(id(b), frozenset())


class _Op:
    __slots__ = ("kind", "node", "attr")

    def __init__(self, kind, node, attr=""):
        self.kind = kind
        self.node = node
        self.attr = attr


@register_pass
class TypestatePass(AnalysisPass):
    name = "mpi-typestate"
    description = ("MPI object lifecycle automata: request "
                   "init/start/wait/free states, Pready/Parrived "
                   "side rules, win epoch nesting, session/instance "
                   "refcount pairing, guarded pop->re-register handoffs")

    def run(self, pkg: Package) -> list[Finding]:
        from ompi_tpu.analysis import callgraph

        graph = callgraph.build(pkg)
        req = _load_typestate(pkg, "request.py", REQUEST_DEFAULTS)
        win = _load_typestate(pkg, "win.py", WIN_DEFAULTS)
        store_summaries = self._guarded_store_summaries(pkg, graph)
        out: list[Finding] = []
        for mod in pkg.modules:
            attr_guards, _g, locks, _c = _guard_maps(mod)
            for fn, qual in mod.functions():
                paths = _PathMap(fn)
                out.extend(self._check_requests(mod, fn, qual, req, paths))
                out.extend(self._check_wins(mod, fn, qual, win, paths))
                out.extend(self._check_refcounts(mod, fn, qual, paths))
                if attr_guards:
                    out.extend(self._check_handoffs(
                        mod, fn, qual, attr_guards, graph, paths,
                        store_summaries))
        return out

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------
    def _creators(self, fn, names_inactive, names_active) -> dict:
        created: dict[str, tuple] = {}    # name -> (creator attr, node)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            f = node.value.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr is None:
                continue
            if attr in names_inactive or attr in names_active:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    created[tgt.id] = (attr, node)
        return created

    def _request_ops(self, fn, name, ts) -> list:
        ops: list[_Op] = []
        kinds = {}
        for cat in ("start", "complete", "free", "pready", "parrived"):
            for opname in ts[cat]:
                kinds[opname] = cat
        many = {}
        for opname in ts["start_many"]:
            many[opname] = "start"
        for opname in ts["complete_many"]:
            many[opname] = "complete"
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == name:
                    cat = kinds.get(f.attr)
                    ops.append(_Op(cat or "method", node, f.attr))
                    continue
                short = call_name(node).rsplit(".", 1)[-1]
                # keyword arguments count too: waitall(requests=[r]) is
                # a completion, registry.add(req=r) is an escape
                argexprs = list(node.args) + [kw.value
                                              for kw in node.keywords]
                in_args = any(isinstance(n, ast.Name) and n.id == name
                              for a in argexprs for n in ast.walk(a))
                if in_args:
                    if short in many:
                        ops.append(_Op(many[short], node, short))
                    else:
                        ops.append(_Op("escape", node, short))
            elif isinstance(node, ast.Return) and node.value is not None:
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node.value)):
                    ops.append(_Op("escape", node, "return"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = node.value
                uses = value is not None and any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(value))
                if not uses:
                    continue
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        ops.append(_Op("escape", node, "store"))
                    elif isinstance(t, ast.Name) and t.id != name:
                        ops.append(_Op("escape", node, "alias"))
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node.value)):
                    ops.append(_Op("escape", node, "yield"))
        ops.sort(key=lambda o: (o.node.lineno, o.node.col_offset))
        return ops

    def _check_requests(self, mod, fn, qual, ts, paths) -> list:
        out = []
        # a nonblocking request DISCARDED at the statement level never
        # binds a name: its completion — and any error it carries — is
        # structurally unobservable (MPI_Send is isend + wait, not
        # isend + hope)
        active_creators = set(ts["create_active"])
        for node in ast.walk(fn):
            if isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in active_creators:
                out.append(Finding(
                    self.name, mod.path, node.lineno, node.col_offset,
                    f"'{node.value.func.attr}()' request is discarded — "
                    "its completion (and any error it carries) is "
                    "unobservable; wait()/test() the request or hand "
                    "it to a wait family", qual))
        created = self._creators(fn, set(ts["create_inactive"]),
                                 active_creators)
        if not created:
            return out
        send_side = set(ts["send_side"])
        partitioned = set(ts["partitioned"])
        inactive = set(ts["create_inactive"])

        def flag(node, msg):
            out.append(Finding(self.name, mod.path, node.lineno,
                               node.col_offset, msg, qual))

        for name, (creator, cnode) in created.items():
            ops = [o for o in self._request_ops(fn, name, ts)
                   if o.node.lineno > cnode.lineno
                   or (o.node.lineno == cnode.lineno
                       and o.node.col_offset >= cnode.col_offset)]
            escaped = any(o.kind == "escape" for o in ops)
            freed: Optional[_Op] = None
            started = creator not in inactive
            completed = False
            active = started
            for op in ops:
                if op.kind == "escape":
                    break                  # caller owns the rest
                if freed is not None and op.kind in (
                        "start", "complete", "pready", "parrived") \
                        and paths.comparable(freed.node, op.node) \
                        and paths.same_loops(freed.node, op.node):
                    flag(op.node,
                         f"request '{name}' used after free() (freed at "
                         f"line {freed.node.lineno}) — the freed request "
                         "is no longer startable/waitable")
                    continue
                if op.kind == "free":
                    if freed is not None \
                            and paths.comparable(freed.node, op.node) \
                            and paths.same_loops(freed.node, op.node):
                        flag(op.node,
                             f"request '{name}' freed twice (first at "
                             f"line {freed.node.lineno})")
                    freed = op
                elif op.kind == "start":
                    if creator not in inactive:
                        flag(op.node,
                             f"start() on '{name}' created by "
                             f"{creator}() — only persistent (_init) "
                             "requests are startable")
                    elif active and not completed \
                            and any(o.kind == "start" and o is not op
                                    and paths.comparable(o.node, op.node)
                                    and paths.same_loops(o.node, op.node)
                                    and o.node.lineno < op.node.lineno
                                    for o in ops):
                        flag(op.node,
                             f"request '{name}' started twice with no "
                             "intervening wait/test — the runtime "
                             "raises ERR_REQUEST on the second start")
                    started, active = True, True
                elif op.kind == "complete":
                    completed = True
                    active = False
                elif op.kind == "pready":
                    if creator not in partitioned:
                        flag(op.node,
                             f"{op.attr}() on '{name}' created by "
                             f"{creator}() — Pready needs a partitioned "
                             "send request (psend_init)")
                    elif creator not in send_side:
                        flag(op.node,
                             f"{op.attr}() on the receive-side request "
                             f"'{name}' ({creator}()) — Pready is "
                             "send-side only; the receiver tests "
                             "Parrived")
                    elif not started:
                        flag(op.node,
                             f"{op.attr}() on inactive request '{name}' "
                             "— partitions can be marked ready only "
                             "between start() and completion")
                elif op.kind == "parrived":
                    if creator not in partitioned:
                        flag(op.node,
                             f"{op.attr}() on '{name}' created by "
                             f"{creator}() — Parrived needs a "
                             "partitioned receive request (precv_init)")
                    elif creator in send_side:
                        flag(op.node,
                             f"{op.attr}() on the send-side request "
                             f"'{name}' ({creator}()) — arrival is "
                             "observable on the receive side only")
            if escaped or freed is not None:
                continue
            if creator in inactive and started and not completed:
                flag(cnode,
                     f"persistent request '{name}' is started but never "
                     "waited/tested or freed in this function and never "
                     "escapes — its completion is unobservable and the "
                     "request leaks")
            elif creator not in inactive and not completed:
                flag(cnode,
                     f"nonblocking request '{name}' ({creator}()) is "
                     "never waited/tested in this function and never "
                     "escapes — completion (and any error) is silently "
                     "dropped")
        return out

    # ------------------------------------------------------------------
    # win epochs
    # ------------------------------------------------------------------
    def _check_wins(self, mod, fn, qual, ts, paths) -> list:
        creators = set(ts["create"])
        created: dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            name = call_name(node.value)
            tail2 = ".".join(name.split(".")[-2:])
            if tail2 in creators or name in creators:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    created[tgt.id] = node
                elif isinstance(tgt, ast.Tuple) and tgt.elts \
                        and isinstance(tgt.elts[0], ast.Name):
                    created[tgt.elts[0].id] = node   # win, buf = allocate
        if not created:
            return []
        out = []
        p_open = set(ts["passive_open"])
        p_close = set(ts["passive_close"])
        pscw = dict(ts["pscw"])
        pscw_close = {v: k for k, v in pscw.items()}
        in_passive = set(ts["in_passive"])

        def flag(node, msg):
            out.append(Finding(self.name, mod.path, node.lineno,
                               node.col_offset, msg, qual))

        for name, cnode in created.items():
            calls = []
            escaped = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id == name:
                        calls.append((f.attr, node))
                elif isinstance(node, ast.Return) \
                        and node.value is not None:
                    if any(isinstance(n, ast.Name) and n.id == name
                           for n in ast.walk(node.value)):
                        escaped = True
                elif isinstance(node, ast.Assign):
                    if any(isinstance(n, ast.Name) and n.id == name
                           for n in ast.walk(node.value)) \
                            and any(isinstance(t, (ast.Attribute,
                                                   ast.Subscript))
                                    for t in node.targets):
                        escaped = True
            calls.sort(key=lambda c: (c[1].lineno, c[1].col_offset))
            depth = 0
            open_node = None
            pscw_opened: dict[str, ast.AST] = {}
            for attr, node in calls:
                if attr in p_open:
                    if depth == 0:
                        open_node = node
                    depth += 1
                elif attr in p_close:
                    if depth == 0:
                        flag(node,
                             f"'{name}.{attr}()' closes a passive-target "
                             "epoch that was never opened in this "
                             "function — unlock without lock raises "
                             "ERR_RMA_SYNC at the target")
                    else:
                        depth -= 1
                        if depth == 0:
                            open_node = None
                elif attr in in_passive and depth == 0:
                    flag(node,
                         f"'{name}.{attr}()' outside a passive-target "
                         "epoch — flush only orders operations issued "
                         "under lock/lock_all")
                elif attr in pscw:
                    pscw_opened[attr] = node
                elif attr in pscw_close:
                    opener = pscw_close[attr]
                    if opener not in pscw_opened:
                        flag(node,
                             f"'{name}.{attr}()' without a preceding "
                             f"'{name}.{opener}()' — PSCW epochs pair "
                             f"{opener}/{attr}")
                    else:
                        pscw_opened.pop(opener, None)
            if escaped:
                continue
            if depth > 0 and open_node is not None:
                flag(open_node,
                     f"passive-target epoch on '{name}' is opened here "
                     "but never closed in this function — the target "
                     "stays locked (every later accessor hangs)")
            for opener, node in pscw_opened.items():
                flag(node,
                     f"PSCW '{name}.{opener}()' epoch is never closed "
                     f"with '{ts['pscw'][opener]}()' in this function")
        return out

    # ------------------------------------------------------------------
    # session/instance refcount pairing
    # ------------------------------------------------------------------
    def _check_refcounts(self, mod, fn, qual, paths) -> list:
        out = []
        globals_declared: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
        acquires = []        # (suffix, node, bound name | None)
        releases = []        # (suffix, node)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            for acq, (rel, is_method) in REFCOUNT_PAIRS.items():
                if name.endswith(acq):
                    acquires.append((acq, node, None))
                elif not is_method and name.endswith(rel):
                    releases.append((acq, node))
        if not acquires:
            return out
        # bind acquire results to names; method-released pairs look for
        # <name>.<release>() on the bound name
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call) \
                    and isinstance(stmt.targets[0], ast.Name):
                for i, (acq, node, bound) in enumerate(acquires):
                    if stmt.value is node:
                        acquires[i] = (acq, node, stmt.targets[0].id)
        for acq, node, bound in acquires:
            rel, is_method = REFCOUNT_PAIRS[acq]
            if bound is not None and bound in globals_declared:
                continue        # stored module-wide: released elsewhere
            paired = False
            if is_method:
                if bound is None:
                    continue    # result unbound: not trackable
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == rel \
                            and isinstance(sub.func.value, ast.Name) \
                            and sub.func.value.id == bound:
                        paired = True
                escaped = False
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Return) \
                            and sub.value is not None \
                            and any(isinstance(n, ast.Name)
                                    and n.id == bound
                                    for n in ast.walk(sub.value)):
                        escaped = True
                    elif isinstance(sub, ast.Assign) \
                            and any(isinstance(t, (ast.Attribute,
                                                   ast.Subscript))
                                    for t in sub.targets) \
                            and any(isinstance(n, ast.Name)
                                    and n.id == bound
                                    for n in ast.walk(sub.value)):
                        escaped = True
                if escaped:
                    continue
            else:
                paired = any(a == acq and r.lineno > node.lineno
                             for a, r in releases)
                # escape of the returned handle also transfers the ref
                if bound is not None:
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.Return) \
                                and sub.value is not None \
                                and any(isinstance(n, ast.Name)
                                        and n.id == bound
                                        for n in ast.walk(sub.value)):
                            paired = True
                        elif isinstance(sub, ast.Assign) \
                                and any(isinstance(t, (ast.Attribute,
                                                       ast.Subscript))
                                        for t in sub.targets) \
                                and any(isinstance(n, ast.Name)
                                        and n.id == bound
                                        for n in ast.walk(sub.value)):
                            paired = True
            if not paired:
                out.append(Finding(
                    self.name, mod.path, node.lineno, node.col_offset,
                    f"'{acq}()' has no paired '{rel}' in this function "
                    "and its handle never escapes — the refcount can "
                    "only grow (teardown never runs)", qual))
        return out

    # ------------------------------------------------------------------
    # guarded handoff (the staging checkout-outside-lock family)
    # ------------------------------------------------------------------
    def _guarded_store_summaries(self, pkg, graph) -> dict:
        """(mod.path, qual) -> {param -> (guarded attr, lock)} for
        functions that store a parameter (or a value derived from it)
        into a _guarded_by-declared structure."""
        out: dict[tuple, dict] = {}
        for mod in pkg.modules:
            attr_guards, _g, _l, _c = _guard_maps(mod)
            if not attr_guards:
                continue
            for fn, qual in mod.functions():
                params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                          + fn.args.posonlyargs} - {"self", "cls"}
                if not params:
                    continue
                derived = _propagate_derived(fn, params)
                stores: dict[str, tuple] = {}
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    vals = {derived[n.id] for n in ast.walk(node.value)
                            if isinstance(n, ast.Name)
                            and n.id in derived}
                    if not vals:
                        continue
                    for t in node.targets:
                        n = t
                        while isinstance(n, ast.Subscript):
                            n = n.value
                        if isinstance(n, ast.Attribute) \
                                and isinstance(n.value, ast.Name) \
                                and n.value.id == "self" \
                                and n.attr in attr_guards:
                            for p in vals:
                                stores.setdefault(
                                    p, (n.attr, attr_guards[n.attr]))
                if stores:
                    out[(mod.path, qual)] = stores
        return out

    def _check_handoffs(self, mod, fn, qual, attr_guards, graph, paths,
                        store_summaries) -> list:
        out = []
        info = graph.function_at(mod, qual)
        # alias map: dq = self._free.get(cls) -> dq means _free
        aliases: dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Name):
                v = node.value
                while isinstance(v, (ast.Call, ast.Subscript,
                                     ast.Attribute)):
                    if isinstance(v, ast.Attribute) \
                            and v.attr in attr_guards:
                        aliases[node.targets[0].id] = v.attr
                        break
                    v = v.func if isinstance(v, ast.Call) else v.value
        # With blocks acquiring declared locks, with their body node ids
        lock_bodies: list[tuple] = []    # (lock, with-node, set of ids)
        declared = set(attr_guards.values())
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for _base, lock in _lock_pairs(node):
                    if lock in declared:
                        ids = set()
                        for stmt in node.body:
                            ids.update(id(s) for s in ast.walk(stmt))
                        lock_bodies.append((lock, node, ids))
        if not lock_bodies:
            return out
        # pops of guarded structures under a declared lock
        popped: dict[str, tuple] = {}    # name -> (attr, lock, node)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in POPPERS
                    and isinstance(node.targets[0], ast.Name)):
                continue
            recv = node.value.func.value
            attr = None
            n = recv
            while isinstance(n, (ast.Attribute, ast.Subscript, ast.Call)):
                if isinstance(n, ast.Attribute) and n.attr in attr_guards:
                    attr = n.attr
                    break
                n = n.func if isinstance(n, ast.Call) else n.value
            if attr is None and isinstance(recv, ast.Name):
                attr = aliases.get(recv.id)
            if attr is None:
                continue
            for lock, wnode, ids in lock_bodies:
                if id(node) in ids and lock == attr_guards[attr]:
                    popped[node.targets[0].id] = (attr, lock, node, ids)
        if not popped:
            return out
        # derived names (view = raw[:n].view(...)) carry the handoff
        derived = _propagate_derived(fn, popped)

        def window_finding(node, root, dst_attr, src_attr, lock, how):
            out.append(Finding(
                self.name, mod.path, node.lineno, node.col_offset,
                f"guarded handoff: '{root}' popped from '{src_attr}' "
                f"under '{lock}' is re-registered into '{dst_attr}' "
                f"{how} — in the window the object is observable as "
                "neither free nor checked out, so a concurrent "
                "double-release/re-acquire passes every guard (the "
                "staging-pool aliasing family); move the "
                "re-registration into the same critical section", qual))

        for node in ast.walk(fn):
            # direct re-register: self._out[...] = <derived>
            if isinstance(node, ast.Assign):
                vals = {derived[n.id] for n in ast.walk(node.value)
                        if isinstance(n, ast.Name) and n.id in derived}
                if not vals:
                    continue
                for t in node.targets:
                    n = t
                    while isinstance(n, ast.Subscript):
                        n = n.value
                    if not (isinstance(n, ast.Attribute)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == "self"
                            and n.attr in attr_guards):
                        continue
                    for root in vals:
                        src_attr, lock, pnode, ids = popped[root]
                        if n.attr == src_attr:
                            continue
                        if attr_guards[n.attr] != lock:
                            continue
                        if id(node) not in ids \
                                and paths.comparable(pnode, node):
                            window_finding(
                                node, root, n.attr, src_attr, lock,
                                "outside the popping critical section")
            # helper re-register: self._checkout(raw, ...) where the
            # callee stores that parameter into a guarded structure
            elif isinstance(node, ast.Call) and info is not None:
                callee = graph.resolve_call(info, node)
                if callee is None:
                    continue
                summary = store_summaries.get(callee.key)
                if not summary:
                    continue
                cparams = list(callee.params)
                if callee.cls is not None and cparams \
                        and cparams[0] in ("self", "cls"):
                    cparams = cparams[1:]
                for i, arg in enumerate(node.args):
                    if i >= len(cparams):
                        break
                    pstore = summary.get(cparams[i])
                    if pstore is None:
                        continue
                    roots = {derived[n.id] for n in ast.walk(arg)
                             if isinstance(n, ast.Name)
                             and n.id in derived}
                    for root in roots:
                        src_attr, lock, pnode, ids = popped[root]
                        dst_attr, dst_lock = pstore
                        if dst_attr == src_attr or dst_lock != lock:
                            continue
                        if id(node) not in ids \
                                and paths.comparable(pnode, node):
                            window_finding(
                                node, root, dst_attr, src_attr, lock,
                                f"by {callee.qual}() called outside "
                                "the popping critical section")
        return out
