"""Built-in otpu-lint passes.  Importing this package registers them all
(the registry order here is the report order)."""
from ompi_tpu.analysis.passes import (  # noqa: F401
    buffer_ownership,
    lock_discipline,
    hot_path,
    observability,
    mca_conformance,
    view_escape,
    typestate,
    coll_match,
)
