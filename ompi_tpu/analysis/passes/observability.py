"""observability — diagnostics and counters resolve to declared sinks.

Three contracts the observability stack depends on:

1. **show_help keys register**: every ``show_help(topic, key, ...)`` with
   literal arguments must have a matching ``register_help(topic, key,
   template)`` somewhere in the package — otherwise the user sees the
   raw ``[topic:key] k=v`` fallback instead of the written diagnostic.
   (``register_help`` import aliases like ``_rh`` are followed.)

2. **SPC counters declare**: every literal name passed to
   ``spc.record``/``spc.read`` must appear in the ``_COUNTERS`` tuple of
   ``runtime/spc.py`` — a typo'd counter silently counts into nothing
   (record() drops unknown names by design).

3. **Trace span begins close**: a ``t0 = trace.now()`` begin must be
   consumed by a ``trace.span(...)``/``trace.hist_record(...)`` in the
   same function on some path — an unconsumed begin is a span that never
   closes (the PR 1 family: the timeline silently loses the operation).

4. **Telemetry keys come from the schema**: every literal name passed to
   ``telemetry.register_source`` must be a key of the ``SCHEMA``
   constant in ``runtime/telemetry.py`` — an undeclared source key
   would publish samples ``otpu_top``/``otpu_analyze`` cannot interpret
   (and the runtime rejects it loudly; this catches it before it runs).

5. **Flight-recorder reasons register**: every literal reason passed to
   ``flight.dump`` must have a registered ``help-flight`` template —
   the dump announcement IS the user-facing diagnostic, and an
   unregistered reason would crash-dump with the raw fallback.

6. **Profile stages come from the stage table**: every literal name
   passed to ``profile.stage_span``/``profile.stage_mark`` must be a
   key of the ``STAGES`` table in ``runtime/profile.py`` — the stage
   vocabulary is closed so otpu_analyze's pack/queue/wire/parse/deliver
   decomposition keeps a stable meaning (and the runtime rejects an
   undeclared stage loudly; this catches it before it runs).

7. **Flow-key categories come from the declared registry**: every
   literal category passed to ``trace.flow_start``/``trace.flow_finish``
   must be a key of the ``FLOW_CATEGORIES`` table in
   ``runtime/trace.py`` — each category documents its id format, and
   ``otpu_analyze`` parses flow ids by category, so an undeclared
   category would emit arrows the critical-path graph silently drops.
"""
from __future__ import annotations

import ast

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               const_str, register_pass)


def _register_aliases(mod) -> set:
    """Names that mean base.output.register_help in this module."""
    names = {"register_help"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("output"):
            for alias in node.names:
                if alias.name == "register_help":
                    names.add(alias.asname or alias.name)
    return names


@register_pass
class ObservabilityPass(AnalysisPass):
    name = "observability"
    description = ("show_help keys resolve to registered templates, SPC "
                   "counter names are declared in runtime/spc.py, "
                   "trace.now() begins are consumed by a span, "
                   "telemetry source names come from the declared "
                   "SCHEMA, flight-recorder dump reasons are "
                   "help-flight-registered, profile stage names come "
                   "from the declared STAGES table, flow-key categories "
                   "come from the declared FLOW_CATEGORIES registry")

    def run(self, pkg: Package) -> list[Finding]:
        registered: set[tuple] = set()
        counters: set[str] = set()
        counters_declared = False
        schema: set[str] = set()
        schema_declared = False
        stages: set[str] = set()
        stages_declared = False
        flows: set[str] = set()
        flows_declared = False
        for mod in pkg.modules:
            aliases = _register_aliases(mod)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    fname = call_name(node)
                    short = fname.rsplit(".", 1)[-1]
                    if short in aliases and len(node.args) >= 2:
                        topic = const_str(node.args[0])
                        key = const_str(node.args[1])
                        if topic and key:
                            registered.add((topic, key))
            if mod.path.replace("\\", "/").endswith("spc.py"):
                for stmt in mod.tree.body:
                    if isinstance(stmt, ast.Assign) \
                            and any(isinstance(t, ast.Name)
                                    and t.id == "_COUNTERS"
                                    for t in stmt.targets) \
                            and isinstance(stmt.value, (ast.Tuple, ast.List)):
                        counters_declared = True
                        for elt in stmt.value.elts:
                            s = const_str(elt)
                            if s:
                                counters.add(s)
            if mod.path.replace("\\", "/").endswith("profile.py"):
                for stmt in mod.tree.body:
                    if isinstance(stmt, ast.Assign) \
                            and any(isinstance(t, ast.Name)
                                    and t.id == "STAGES"
                                    for t in stmt.targets) \
                            and isinstance(stmt.value, ast.Dict):
                        stages_declared = True
                        for k in stmt.value.keys:
                            s = const_str(k)
                            if s:
                                stages.add(s)
            if mod.path.replace("\\", "/").endswith("trace.py"):
                for stmt in mod.tree.body:
                    if isinstance(stmt, ast.Assign) \
                            and any(isinstance(t, ast.Name)
                                    and t.id == "FLOW_CATEGORIES"
                                    for t in stmt.targets) \
                            and isinstance(stmt.value, ast.Dict):
                        flows_declared = True
                        for k in stmt.value.keys:
                            s = const_str(k)
                            if s:
                                flows.add(s)
            if mod.path.replace("\\", "/").endswith("telemetry.py"):
                for stmt in mod.tree.body:
                    if isinstance(stmt, ast.Assign) \
                            and any(isinstance(t, ast.Name)
                                    and t.id in ("SCHEMA", "_SCHEMA")
                                    for t in stmt.targets):
                        if isinstance(stmt.value, ast.Dict):
                            schema_declared = True
                            for k in stmt.value.keys:
                                s = const_str(k)
                                if s:
                                    schema.add(s)
                        elif isinstance(stmt.value,
                                        (ast.Tuple, ast.List)):
                            schema_declared = True
                            for elt in stmt.value.elts:
                                s = const_str(elt)
                                if s:
                                    schema.add(s)
        out: list[Finding] = []
        for mod in pkg.modules:
            for fn, qual in mod.functions():
                out.extend(self._check_fn(mod, fn, qual, registered,
                                          counters, counters_declared,
                                          schema, schema_declared,
                                          stages, stages_declared,
                                          flows, flows_declared))
        return out

    def _check_fn(self, mod, fn, qual, registered, counters,
                  counters_declared, schema, schema_declared,
                  stages, stages_declared, flows,
                  flows_declared) -> list:
        out = []
        begins: dict[str, ast.AST] = {}
        consumed: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and call_name(node.value).endswith("trace.now") \
                        and isinstance(node.targets[0], ast.Name):
                    begins[node.targets[0].id] = node
                continue
            name = call_name(node)
            short = name.rsplit(".", 1)[-1]
            if short == "show_help" and len(node.args) >= 2:
                topic, key = const_str(node.args[0]), const_str(node.args[1])
                if topic and key and (topic, key) not in registered:
                    out.append(Finding(
                        self.name, mod.path, node.lineno, node.col_offset,
                        f"show_help('{topic}', '{key}') has no matching "
                        "register_help — the user would see the raw "
                        "fallback instead of the written diagnostic",
                        qual))
            elif name in ("spc.record", "spc.read") and node.args \
                    and counters_declared:
                cname = const_str(node.args[0])
                if cname and cname not in counters:
                    out.append(Finding(
                        self.name, mod.path, node.lineno, node.col_offset,
                        f"SPC counter '{cname}' is not declared in "
                        "runtime/spc.py _COUNTERS — record() silently "
                        "drops unknown names", qual))
            elif short == "register_source" and node.args \
                    and schema_declared:
                sname = const_str(node.args[0])
                if sname and sname not in schema:
                    out.append(Finding(
                        self.name, mod.path, node.lineno,
                        node.col_offset,
                        f"telemetry source {sname!r} is not a key of "
                        "runtime/telemetry.py SCHEMA — published sample "
                        "keys must come from the declared schema",
                        qual))
            elif (name.endswith("flight.dump")
                  or (short == "dump"
                      and mod.path.replace("\\", "/")
                      .endswith("flight.py"))) and node.args:
                reason = const_str(node.args[0])
                if reason and ("help-flight", reason) not in registered:
                    out.append(Finding(
                        self.name, mod.path, node.lineno,
                        node.col_offset,
                        f"flight-recorder dump reason {reason!r} has no "
                        "registered help-flight template — the crash "
                        "announcement would be the raw fallback",
                        qual))
            elif short in ("stage_span", "stage_mark") and node.args \
                    and stages_declared:
                sname = const_str(node.args[0])
                if sname and sname not in stages:
                    out.append(Finding(
                        self.name, mod.path, node.lineno,
                        node.col_offset,
                        f"profile stage {sname!r} is not declared in "
                        "runtime/profile.py STAGES — stage clocks must "
                        "aggregate into the declared stage table",
                        qual))
                # a stage_span consumes its t0 like span/hist_record
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            consumed.add(sub.id)
            elif short in ("flow_start", "flow_finish") and node.args \
                    and flows_declared:
                fname_lit = const_str(node.args[0])
                if fname_lit and fname_lit not in flows:
                    out.append(Finding(
                        self.name, mod.path, node.lineno,
                        node.col_offset,
                        f"flow category {fname_lit!r} is not declared "
                        "in runtime/trace.py FLOW_CATEGORIES — flow "
                        "ids are parsed per declared category, an "
                        "undeclared one emits arrows the critical-path "
                        "graph silently drops", qual))
            elif short in ("span", "hist_record"):
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            consumed.add(sub.id)
        # a begin consumed anywhere in the function (incl. inside a
        # lambda's span call) closes; otherwise the span never ends
        for tname, node in begins.items():
            if tname not in consumed:
                out.append(Finding(
                    self.name, mod.path, node.lineno, node.col_offset,
                    f"'{tname} = trace.now()' is never consumed by a "
                    "trace.span/hist_record in this function — the span "
                    "begins but never closes", qual))
        return out
