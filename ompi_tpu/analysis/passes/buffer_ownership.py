"""buffer-ownership — borrowed views must not escape; staging pairs close.

The invariant family behind PR 4's worst review bugs:

1. A borrowed view (``Convertor.pack_borrow``'s zero-copy slice of the
   user buffer, ``_Ring.pop_frame``'s view of reused ring scratch) is
   valid only within the call that produced it.  Storing it on ``self``,
   a parameter's attribute, or a global — or returning it — without an
   explicit owning copy (``bytes()``/``bytearray()``/``.tobytes()``/
   ``np.array(x, copy=True)``/``.toreadonly()``) aliases transient
   memory.  Passing it onward as a *call argument* is allowed: the
   callee inherits the same contract (that is how pack_borrow's chunks
   legitimately ride into ``btl.send``).

2. ``staging_acquire``/``staging_release`` must pair on all paths: an
   acquired buffer that is neither released, returned, nor stored (an
   ownership transfer) leaks pool accounting; a ``return`` between
   acquire and release skips the release on that path (the fix is a
   ``try/finally``, exactly like ``algorithms.allreduce_ring``).
"""
from __future__ import annotations

import ast

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               dotted, register_pass)

#: call attr names that produce a borrowed view
BORROW_PRODUCERS = {"pack_borrow", "pop_frame"}

#: call names whose result is an owned copy of their argument
OWNING_WRAPPERS = {"bytes", "bytearray"}
OWNING_METHODS = {"tobytes", "toreadonly"}

MUTATORS = {"append", "appendleft", "extend", "insert", "add", "push",
            "setdefault", "update"}


def _is_owned_use(parents: dict, node: ast.Name) -> bool:
    """True when ``node`` is consumed by an owning copy wrapper."""
    parent = parents.get(id(node))
    if isinstance(parent, ast.Call):
        if call_name(parent) in OWNING_WRAPPERS and parent.args \
                and parent.args[0] is node:
            return True
        fn = parent.func
        if isinstance(fn, ast.Attribute) and fn.attr == "array" \
                and parent.args and parent.args[0] is node:
            return True        # np.array(x, ...)
    if isinstance(parent, ast.Attribute) and parent.attr in OWNING_METHODS:
        return True            # x.tobytes() / x.toreadonly()
    return False


def _parent_map(fn: ast.AST) -> dict:
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _root_name(node: ast.AST):
    """Leftmost Name of an attribute/subscript/call chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_staging_acquire(call: ast.Call) -> bool:
    name = call_name(call)
    return name.endswith("staging_acquire") or name.endswith("staging.acquire")


def _is_staging_release(call: ast.Call) -> bool:
    name = call_name(call)
    return name.endswith("staging_release") or name.endswith("staging.release")


@register_pass
class BufferOwnershipPass(AnalysisPass):
    name = "buffer-ownership"
    description = ("borrowed pack_borrow/pop_frame views must not escape "
                   "without an owning copy; staging acquire/release pair "
                   "on all paths")

    def run(self, pkg: Package) -> list[Finding]:
        out: list[Finding] = []
        for mod in pkg.modules:
            for fn, qual in mod.functions():
                out.extend(self._check_borrows(mod, fn, qual))
                out.extend(self._check_staging(mod, fn, qual))
        return out

    # -- borrowed-view escapes -------------------------------------------
    def _borrowed_names(self, fn) -> dict[str, int]:
        borrowed: dict[str, int] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            f = node.value.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in BORROW_PRODUCERS):
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Tuple) and tgt.elts \
                    and isinstance(tgt.elts[0], ast.Name):
                # data, borrowed = conv.pack_borrow(...)
                borrowed[tgt.elts[0].id] = node.lineno
            elif isinstance(tgt, ast.Name):
                borrowed[tgt.id] = node.lineno
        return borrowed

    def _check_borrows(self, mod, fn, qual) -> list[Finding]:
        borrowed = self._borrowed_names(fn)
        if not borrowed:
            return []
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  + fn.args.posonlyargs}
        params.discard("self")
        parents = _parent_map(fn)
        out = []

        def escapes(name_node: ast.Name, how: str, node) -> None:
            out.append(Finding(
                self.name, mod.path, node.lineno, node.col_offset,
                f"borrowed view '{name_node.id}' (line "
                f"{borrowed[name_node.id]}) {how} without an owning "
                "copy (bytes()/.tobytes()/np.array(copy=True)); borrowed "
                "views die with the producing call", qual))

        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id in borrowed \
                            and not _is_owned_use(parents, n):
                        escapes(n, "is returned", node)
            elif isinstance(node, ast.Assign):
                vals = [n for n in ast.walk(node.value)
                        if isinstance(n, ast.Name) and n.id in borrowed
                        and not _is_owned_use(parents, n)]
                if not vals:
                    continue
                for tgt in node.targets:
                    root = _root_name(tgt)
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                            and (root == "self" or root in params):
                        escapes(vals[0], f"is stored on '{root}'", node)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                    root = _root_name(f.value)
                    if root != "self" and root not in params:
                        continue
                    for arg in node.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name) and n.id in borrowed \
                                    and not _is_owned_use(parents, n):
                                escapes(n, "is queued on "
                                        f"'{dotted(f.value) or root}'", node)
        return out

    # -- staging acquire/release pairing ---------------------------------
    def _check_staging(self, mod, fn, qual) -> list[Finding]:
        acquires: dict[str, ast.Assign] = {}
        releases: dict[str, ast.Call] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _is_staging_acquire(node.value) \
                    and isinstance(node.targets[0], ast.Name):
                acquires[node.targets[0].id] = node
            elif isinstance(node, ast.Call) and _is_staging_release(node):
                for arg in node.args:
                    for n in _names_in(arg):
                        releases.setdefault(n, node)
        if not acquires:
            return []
        out = []
        for name, acq in acquires.items():
            rel = releases.get(name)
            if rel is None:
                if self._ownership_transferred(fn, name):
                    continue
                out.append(Finding(
                    self.name, mod.path, acq.lineno, acq.col_offset,
                    f"staging buffer '{name}' is acquired but never "
                    "released, returned, or stored — pool accounting "
                    "leaks on every call", qual))
                continue
            # early return strictly between acquire and release skips
            # the release on that path — pair them with try/finally
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and acq.lineno < node.lineno < rel.lineno \
                        and not self._release_in_finally(fn, rel):
                    out.append(Finding(
                        self.name, mod.path, node.lineno, node.col_offset,
                        f"return between staging_acquire('{name}', line "
                        f"{acq.lineno}) and its release (line "
                        f"{rel.lineno}) skips the release on this path — "
                        "use try/finally", qual))
                    break
        return out

    @staticmethod
    def _ownership_transferred(fn, name: str) -> bool:
        """Returned or stored on self = ownership moved out of the frame."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None \
                    and name in _names_in(node.value):
                return True
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == name:
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                            and _root_name(tgt) == "self":
                        return True
        return False

    @staticmethod
    def _release_in_finally(fn, rel: ast.Call) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if sub is rel:
                            return True
        return False
