"""mca-conformance — components honor their framework's contract.

The MCA discovery machinery (``base/mca.py``) imports every module under
``ompi_tpu.mca.<fw>`` and looks for a ``COMPONENT`` export; selection then
calls framework-specific slots.  A component that half-implements the
contract fails at selection time on whatever host first exercises it —
this pass moves that to lint time:

- a module under ``mca/<fw>/`` defining a Component subclass must export
  ``COMPONENT`` (or discovery silently skips it — the bug class the PR 2
  dynamic-framework-scan satellite fixed for otpu_info),
- the component class must declare a non-empty ``name`` (the selection
  var namespace key),
- frameworks with a required query slot (btl ``send``, coll
  ``comm_query``, pml ``get_module``) must implement it — in the class
  or a same-module base,
- variables register through ``base/var.py``: ``register_vars`` bodies
  must not read ``os.environ`` directly, and module-level
  ``registry.register(group, ...)`` calls must use their own framework
  name as the group (a mismatched group hides the var from
  ``otpu_info --param <fw>``).
"""
from __future__ import annotations

import ast

from ompi_tpu.analysis import (AnalysisPass, Finding, Package, call_name,
                               const_str, register_pass)

#: slots every component of the framework must provide
REQUIRED_SLOTS = {
    "btl": ("send",),
    "coll": ("comm_query",),
    "pml": ("get_module",),
}

#: modules never holding components (helpers, the framework base itself)
EXEMPT_FILES = {"__init__.py", "base.py", "algorithms.py"}


def _mca_framework(path: str):
    parts = path.replace("\\", "/").split("/")
    if "mca" in parts:
        i = parts.index("mca")
        if i + 2 < len(parts) or (i + 2 == len(parts)
                                  and parts[-1].endswith(".py")):
            try:
                return parts[i + 1], parts[-1]
            except IndexError:
                return None
    return None


def _base_names(cls: ast.ClassDef) -> set:
    out = set()
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.add(b.id)
        elif isinstance(b, ast.Attribute):
            out.add(b.attr)
    return out


def _is_component_class(cls: ast.ClassDef) -> bool:
    bases = _base_names(cls)
    return any(b == "Btl" or b.endswith("Component") or b == "Component"
               for b in bases)


def _class_members(cls: ast.ClassDef):
    methods, attrs = set(), {}
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            methods.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    attrs[t.id] = stmt.value
    return methods, attrs


@register_pass
class McaConformancePass(AnalysisPass):
    name = "mca-conformance"
    description = ("mca/* components export COMPONENT, declare a name, "
                   "implement their framework's required slots, and "
                   "register variables through base/var.py")

    def run(self, pkg: Package) -> list[Finding]:
        out: list[Finding] = []
        for mod in pkg.modules:
            loc = _mca_framework(mod.path)
            if loc is None:
                continue
            fw, fname = loc
            if fname in EXEMPT_FILES or fname.startswith("_"):
                continue
            out.extend(self._check_module(mod, fw))
        return out

    def _check_module(self, mod, fw) -> list:
        out = []
        classes = {c.name: c for c in mod.classes()}
        comp_classes = [c for c in classes.values()
                        if _is_component_class(c)]
        has_component_export = any(
            isinstance(stmt, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "COMPONENT"
                    for t in stmt.targets)
            for stmt in mod.tree.body)
        if comp_classes and not has_component_export:
            c = comp_classes[0]
            out.append(Finding(
                self.name, mod.path, c.lineno, c.col_offset,
                f"module defines component class '{c.name}' but exports "
                "no module-level COMPONENT — framework discovery "
                "silently skips it", c.name))
        for cls in comp_classes:
            methods, attrs = _class_members(cls)
            # fold in same-module bases (template/base inheritance)
            for b in _base_names(cls):
                base = classes.get(b)
                if base is not None:
                    bm, ba = _class_members(base)
                    methods |= bm
                    for k, v in ba.items():
                        attrs.setdefault(k, v)
            name_val = attrs.get("name")
            if name_val is None or not const_str(name_val):
                out.append(Finding(
                    self.name, mod.path, cls.lineno, cls.col_offset,
                    f"component class '{cls.name}' declares no non-empty "
                    "'name' class attribute — it cannot be addressed by "
                    "the selection vars", cls.name))
            for slot in REQUIRED_SLOTS.get(fw, ()):
                if slot not in methods:
                    out.append(Finding(
                        self.name, mod.path, cls.lineno, cls.col_offset,
                        f"'{cls.name}' does not implement required "
                        f"{fw}-framework slot '{slot}'", cls.name))
            for stmt in cls.body:
                if isinstance(stmt, ast.FunctionDef) \
                        and stmt.name == "register_vars":
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Attribute) \
                                and node.attr == "environ":
                            out.append(Finding(
                                self.name, mod.path, node.lineno,
                                node.col_offset,
                                "register_vars reads os.environ directly "
                                "— declare an MCA var through "
                                "base/var.py so the value is typed, "
                                "sourced, and visible to otpu_info",
                                f"{cls.name}.register_vars"))
        # module-level registry.register(group, ...) must use this fw
        for stmt in mod.tree.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and call_name(node).endswith("registry.register") \
                        and node.args:
                    group = const_str(node.args[0])
                    if group is not None and group != fw:
                        out.append(Finding(
                            self.name, mod.path, node.lineno,
                            node.col_offset,
                            f"module in mca/{fw}/ registers a variable "
                            f"under group '{group}' — otpu_info --param "
                            f"{fw} will not list it", ""))
        return out
