"""Package-wide call graph + symbol resolver for the otpu-verify passes.

The PR 6 passes were strictly intraprocedural: a borrowed view escaping
through a helper return, a request started in one method and leaked in
another, or a pop/re-register pair split across ``_checkout`` were all
invisible.  This module gives every pass the same whole-program view:

- :class:`SymbolTable` — module names, imports, top-level functions,
  classes with their methods and (package-local) base classes.
- :class:`CallGraph` — resolves a call expression inside a function to
  the package function(s) it names.  Resolution is deliberately
  *under*-approximate (a call we cannot resolve resolves to nothing):
  passes built on it stay precise, they just don't see through dynamic
  dispatch.  Resolved forms:

  * ``f(...)``              — same-module function or from-import
  * ``Cls(...)``            — ``Cls.__init__`` (constructor edge)
  * ``self.m(...)``         — enclosing class's method, walking
    package-local bases (single inheritance chain, name-based)
  * ``mod.f(...)``/``pkg.sub.f(...)`` — imported module's function
  * ``obj.m(...)``          — when ``obj`` is a local assigned from
    ``Cls(...)`` in the same function, or a ``self._x`` attribute
    assigned from ``Cls(...)`` in the class's ``__init__``

Shared by all passes through :meth:`Package.callgraph`-style caching in
the pass driver (built once per lint run; the AST cache already makes
re-parsing free, this makes re-resolving free too).
"""
from __future__ import annotations

import ast
import os
from typing import Optional

from ompi_tpu.analysis import Module, Package, dotted

__all__ = ["CallGraph", "FuncInfo", "build"]


def module_name(path: str) -> str:
    """Dotted module name for a source path (``ompi_tpu.mca.btl.tcp``).

    Files outside a recognizable package root key by their stem, so
    fixture trees still resolve same-directory imports."""
    norm = path.replace(os.sep, "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = norm.split("/")
    if "ompi_tpu" in parts:
        parts = parts[parts.index("ompi_tpu"):]
    else:
        parts = parts[-1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FuncInfo:
    """One package function/method: its AST, location, and parameters."""

    __slots__ = ("mod", "qual", "node", "params", "cls")

    def __init__(self, mod: Module, qual: str, node, cls: Optional[str]):
        self.mod = mod
        self.qual = qual            # "f" or "Cls.m" (module-local)
        self.node = node
        self.cls = cls              # enclosing class name or None
        a = node.args
        self.params = [p.arg for p in a.posonlyargs + a.args]

    @property
    def key(self) -> tuple:
        return (self.mod.path, self.qual)


class _ModTable:
    """Per-module symbol info the resolver consults."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.name = module_name(mod.path)
        self.functions: dict[str, FuncInfo] = {}    # local qual -> info
        self.classes: dict[str, dict] = {}          # Cls -> {methods, bases}
        self.imports: dict[str, str] = {}           # alias -> dotted target
        self._scan()

    def _scan(self) -> None:
        for stmt in self.mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = FuncInfo(
                    self.mod, stmt.name, stmt, None)
            elif isinstance(stmt, ast.ClassDef):
                methods = {}
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qual = f"{stmt.name}.{sub.name}"
                        info = FuncInfo(self.mod, qual, sub, stmt.name)
                        methods[sub.name] = info
                        self.functions[qual] = info
                bases = [dotted(b) for b in stmt.bases]
                self.classes[stmt.name] = {
                    "methods": methods,
                    "bases": [b for b in bases if b],
                }
            elif isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    if alias.asname:
                        self.imports[alias.asname] = alias.name
                    else:
                        # ``import a.b`` binds ``a``; attribute chains
                        # walk from there
                        self.imports[alias.name.split(".")[0]] = \
                            alias.name.split(".")[0]
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:      # relative: resolve against this module
                    base = self.name.split(".")
                    base = base[:len(base) - stmt.level]
                    prefix = ".".join(base + ([stmt.module]
                                              if stmt.module else []))
                else:
                    prefix = stmt.module or ""
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    self.imports[alias.asname or alias.name] = \
                        f"{prefix}.{alias.name}" if prefix else alias.name


class CallGraph:
    """Whole-package resolver.  Build once with :func:`build`."""

    def __init__(self, pkg: Package):
        self.pkg = pkg
        self.tables: dict[str, _ModTable] = {}      # module name -> table
        self.by_path: dict[str, _ModTable] = {}
        for mod in pkg.modules:
            t = _ModTable(mod)
            # first one wins on duplicate names (fixture trees may shadow
            # package modules; the package loads first in a normal run)
            self.tables.setdefault(t.name, t)
            self.by_path[mod.path] = t
        #: (mod.path, qual) -> FuncInfo for direct lookups
        self.functions: dict[tuple, FuncInfo] = {}
        for t in self.tables.values():
            for info in t.functions.values():
                self.functions[info.key] = info
        # local-variable / self-attr class types, lazily built per module
        self._attr_types: dict[str, dict] = {}
        self._local_type_cache: dict[tuple, dict] = {}

    # -- symbol lookup ----------------------------------------------------
    def _module(self, name: str) -> Optional[_ModTable]:
        t = self.tables.get(name)
        if t is not None:
            return t
        # ``a.b`` may be a package whose symbols live in a/b/__init__.py;
        # module_name already folded __init__ away, so plain get covers it
        return None

    def _lookup_dotted(self, target: str,
                       _seen: Optional[set] = None) -> Optional[FuncInfo]:
        """Resolve a fully-dotted ``a.b.c`` to a function/Cls.__init__."""
        # longest module prefix wins: a.b.c = module a.b, symbol c,
        # or module a.b.c itself (not callable), or module a, Cls .b, m .c
        if _seen is None:
            _seen = set()
        if target in _seen:     # circular re-export (compat shims):
            return None         # unresolvable, not a crash
        _seen.add(target)
        parts = target.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            t = self._module(".".join(parts[:cut]))
            if t is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                sym = rest[0]
                info = t.functions.get(sym)
                if info is not None:
                    return info
                if sym in t.classes:
                    return t.classes[sym]["methods"].get("__init__")
                # re-exported: follow the from-import hop (cycle-safe)
                tgt = t.imports.get(sym)
                if tgt is not None and tgt != target:
                    return self._lookup_dotted(tgt, _seen)
            elif len(rest) == 2 and rest[0] in t.classes:
                return self._method(t, rest[0], rest[1])
        return None

    def _method(self, table: _ModTable, cls: str,
                name: str) -> Optional[FuncInfo]:
        """Method lookup walking package-local bases."""
        seen = set()
        queue = [(table, cls)]
        while queue:
            t, c = queue.pop(0)
            if (t.name, c) in seen or c not in t.classes:
                continue
            seen.add((t.name, c))
            info = t.classes[c]["methods"].get(name)
            if info is not None:
                return info
            for base in t.classes[c]["bases"]:
                bt, bc = self._resolve_class(t, base)
                if bt is not None:
                    queue.append((bt, bc))
        return None

    def _resolve_class(self, table: _ModTable,
                       name: str) -> tuple[Optional[_ModTable], str]:
        """(_ModTable, ClassName) for a possibly-imported class name."""
        if name in table.classes:
            return table, name
        head, _, rest = name.partition(".")
        tgt = table.imports.get(head)
        if tgt is None:
            return None, name
        full = f"{tgt}.{rest}" if rest else tgt
        parts = full.split(".")
        for cut in range(len(parts), 0, -1):
            t = self._module(".".join(parts[:cut]))
            if t is not None and len(parts) - cut == 1 \
                    and parts[cut] in t.classes:
                return t, parts[cut]
        return None, name

    # -- per-function local type environments -----------------------------
    def _self_attr_types(self, table: _ModTable, cls: str) -> dict:
        """attr -> (table, Cls) learned from ``self._x = Cls(...)`` in
        __init__ (and other methods of the same class)."""
        key = f"{table.name}:{cls}"
        hit = self._attr_types.get(key)
        if hit is not None:
            return hit
        out: dict[str, tuple] = {}
        meta = table.classes.get(cls)
        if meta:
            for info in meta["methods"].values():
                for node in ast.walk(info.node):
                    if not (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        continue
                    ctor = dotted(node.value.func)
                    if ctor is None:
                        continue
                    ct, cn = self._resolve_class(table, ctor)
                    if ct is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            out[tgt.attr] = (ct, cn)
        self._attr_types[key] = out
        return out

    def _local_types(self, info: FuncInfo) -> dict:
        """local name -> (table, Cls) from ``x = Cls(...)`` assigns."""
        hit = self._local_type_cache.get(info.key)
        if hit is not None:
            return hit
        table = self.by_path[info.mod.path]
        out: dict[str, tuple] = {}
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and node.targets
                    and isinstance(node.targets[0], ast.Name)):
                continue
            ctor = dotted(node.value.func)
            if ctor is None:
                continue
            ct, cn = self._resolve_class(table, ctor)
            if ct is not None:
                out[node.targets[0].id] = (ct, cn)
        self._local_type_cache[info.key] = out
        return out

    # -- the resolver ------------------------------------------------------
    def resolve_call(self, info: FuncInfo,
                     call: ast.Call) -> Optional[FuncInfo]:
        """The package function ``call`` inside ``info`` names, or None."""
        table = self.by_path.get(info.mod.path)
        if table is None:
            return None
        f = call.func
        name = dotted(f)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if not rest:
            # bare name: local function, local class ctor, or from-import
            local = table.functions.get(name)
            if local is not None and local.cls is None:
                return local
            if name in table.classes:
                return table.classes[name]["methods"].get("__init__")
            tgt = table.imports.get(name)
            return self._lookup_dotted(tgt) if tgt else None
        if head == "self" and info.cls is not None:
            parts = rest.split(".")
            if len(parts) == 1:
                return self._method(table, info.cls, parts[0])
            # self._x.m(): typed attribute hop
            attrs = self._self_attr_types(table, info.cls)
            hop = attrs.get(parts[0])
            if hop is not None and len(parts) == 2:
                return self._method(hop[0], hop[1], parts[1])
            return None
        # imported module/class chain
        tgt = table.imports.get(head)
        if tgt is not None:
            return self._lookup_dotted(f"{tgt}.{rest}")
        # typed local: x = Cls(...); x.m()
        parts = rest.split(".")
        if len(parts) == 1:
            hop = self._local_types(info).get(head)
            if hop is not None:
                return self._method(hop[0], hop[1], parts[0])
        # same-module class: Cls.m(...) static style
        if head in table.classes and len(parts) == 1:
            return self._method(table, head, parts[0])
        return None

    def function_at(self, mod: Module, qual: str) -> Optional[FuncInfo]:
        return self.functions.get((mod.path, qual))


_graphs: dict[int, CallGraph] = {}


def build(pkg: Package) -> CallGraph:
    """Build (or reuse) the call graph for ``pkg``.  Keyed on the Package
    object: every pass in one lint run shares one resolver."""
    g = _graphs.get(id(pkg))
    if g is None or g.pkg is not pkg:
        g = CallGraph(pkg)
        _graphs.clear()         # one live package at a time is plenty
        _graphs[id(pkg)] = g
    return g
