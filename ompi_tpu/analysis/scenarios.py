"""Weave scenarios for the three PR 6 race sites — with the fixes
REVERTED, so the explorer proves it re-finds each bug deterministically.

Every scenario here exists in two flavors:

- the **reverted** scenario carries a faithful reimplementation of the
  pre-fix code shape (the exact window the fix closed), with
  ``weave.pause()`` planted at the instants the original unlocked code
  could be preempted.  ``explore()`` must FAIL it and print a replayable
  schedule string.
- the **fixed twin** drives the same threads through the real (fixed)
  classes under weave-instrumented ``_guarded_by`` locks.  ``explore()``
  must exhaust the bounded schedule space with no failure.

The reverted classes are *deliberately buggy*: the otpu-verify static
layer flags them too (lock-discipline on the naked guarded mutations,
mpi-typestate guarded-handoff on the pop -> re-register window), which
is the point — each shape is re-detected both statically and
dynamically.  Their findings are carried in ``lint_suppressions.txt``
with per-entry justifications; everything else in this module is clean.

Run them all::

    python -m ompi_tpu.analysis.scenarios          # expects revert=FAIL,
                                                   # fixed twin=PASS
    python -m ompi_tpu.analysis.scenarios staging-checkout --replay \
        'staging-checkout@pb2:0.0.1.1.1.0'         # one exact schedule
"""
from __future__ import annotations

import weakref

import numpy as np

from ompi_tpu.analysis import weave
from ompi_tpu.mca.accelerator.jax_acc import _StagingPool
from ompi_tpu.mca.btl.tcp import TcpBtl, _Conn
from ompi_tpu.runtime.sanitizer import SanitizeError


# ---------------------------------------------------------------------------
# 1. staging checkout window (PR 6 fix #1 reverted)
# ---------------------------------------------------------------------------

class _RevertedCheckoutPool(_StagingPool):
    """PR 6 fix #1 reverted: the checkout registration runs OUTSIDE the
    critical section that popped the owner from its free bin.  In the
    window the owner is observable as neither free nor checked out, so a
    stale concurrent release of the same owner passes the double-release
    guard and repools bytes that are in use (the PR 4 aliasing family).
    """

    # same contract as the parent — redeclared so the static passes see
    # this module's (deliberately violated) guard declarations
    _guarded_by = {"_free": "_lock", "_out": "_lock",
                   "_adopted": "_lock", "_bytes": "_lock"}

    def acquire(self, shape, dtype):            # pre-fix shape
        shape = (int(shape),) if isinstance(shape, (int, np.integer)) \
            else tuple(int(s) for s in shape)
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape \
            else dtype.itemsize
        cls = self._class_of(nbytes)
        raw = None
        with self._lock:
            dq = self._free.get(cls)
            if dq:
                raw = dq.pop()
                if not dq:
                    del self._free[cls]
                if raw.base is not None:
                    self._adopted.discard(id(raw.base))
                self._bytes -= raw.nbytes
        if raw is None:
            raw = np.empty(cls, np.uint8)
        weave.pause("staging.checkout-window")  # the revert's window
        return self._checkout_window(raw, shape, dtype)

    def _checkout_window(self, raw, shape, dtype):
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize \
            if shape else np.dtype(dtype).itemsize
        view = raw[:nbytes].view(dtype).reshape(shape)
        token = id(view)
        # naked guarded mutation: the pre-fix bug under test
        self._out[token] = (
            weakref.ref(view, lambda _r, t=token: self._purge(t)), raw)
        return view


class _PoolState:
    __slots__ = ("pool", "owner", "view")


def _staging_setup(pool_cls):
    def setup():
        s = _PoolState()
        s.pool = weave.instrument(pool_cls(max_bytes=1 << 20,
                                           enabled=True))
        s.owner = np.empty(4096, np.uint8)
        s.pool.release(s.owner)          # adopt into the free bin
        s.view = None
        return s
    return setup


def _staging_acquirer(s):
    s.view = s.pool.acquire(4096, np.uint8)
    s.view[:] = 7


def _staging_stale_release(s):
    try:
        s.pool.release(s.owner)          # the stale double release
    except SanitizeError:
        pass    # guard caught it — that is CORRECT behavior; only a
                # schedule where it slips through should fail


def _staging_check(s):
    other = s.pool.acquire(4096, np.uint8)
    other[:] = 0
    assert s.view is not None and int(s.view.sum()) == 7 * 4096, \
        "stale double release aliased the live checkout"


# ---------------------------------------------------------------------------
# 2. tcp rail lists without _conns_lock (PR 6 fix #2 reverted)
# ---------------------------------------------------------------------------

class _RevertedDropBtl(TcpBtl):
    """PR 6 fix #2 reverted: ``_drop_conn`` mutates the per-rank rail
    list with no common lock.  Two threads dropping rails for one peer
    race the membership check against the remove: the loser's
    ``list.remove`` raises ValueError (or the rank-bin pop KeyErrors),
    exactly the corruption the ``_conns_lock`` fix closed."""

    _guarded_by = {"_by_rank": "_conns_lock", "_suspects": "_conns_lock"}

    def _drop_conn(self, conn):                 # pre-fix shape
        if conn.rank is None:
            return
        conns = self._by_rank.get(conn.rank)
        weave.pause("tcp.drop-check")           # check...
        if conns and conn in conns:
            weave.pause("tcp.drop-remove")      # ...then act
            conns.remove(conn)
            if not conns:
                self._by_rank.pop(conn.rank, None)
        self._suspects.append(conn.rank)


class _BtlState:
    __slots__ = ("btl", "conn")


def _tcp_setup(btl_cls):
    def setup():
        s = _BtlState()
        btl = btl_cls.__new__(btl_cls)
        btl_cls.__init__(btl)
        s.btl = weave.instrument(btl)
        conn = _Conn.__new__(_Conn)
        conn.rank = 3
        s.conn = conn
        with s.btl._conns_lock:
            s.btl._by_rank.setdefault(3, []).append(conn)
        return s
    return setup


def _tcp_dropper(s):
    s.btl._drop_conn(s.conn)


def _tcp_check(s):
    assert 3 not in s.btl._by_rank, "dropped rail list survived"


# ---------------------------------------------------------------------------
# 3. coord fence reply under _fence_cond (PR 6 fix #3 reverted)
# ---------------------------------------------------------------------------

class _FenceModel:
    """The one-shot-fence late-arrival path, modeled with weave
    primitives: the reply to a slow-reading client is a blocking
    ``sendall`` that returns only when the client reads
    (``block('client0-reads')``), and the slow client reads only after
    its app-level dependency on rank 1's fence resolves — the cycle one
    lock-holder closes."""

    __slots__ = ("cond_lock", "fence_done", "arrived", "reverted")

    def __init__(self, reverted: bool):
        self.cond_lock = weave.make_lock("fence-cond")
        self.fence_done = set()
        self.arrived = set()
        self.reverted = reverted


def _fence_setup(reverted):
    def setup():
        return _FenceModel(reverted)
    return setup


def _fence_late_reply(s):
    # server: late arrival of rank 0 to a completed one-shot fence
    if s.reverted:
        with s.cond_lock:                       # pre-fix: reply rides
            s.fence_done.add("shutdown")        # under the cond
            weave.block("client0-reads")        # blocking sendall
    else:
        with s.cond_lock:                       # fixed: bookkeeping
            s.fence_done.add("shutdown")        # under the cond,
        weave.block("client0-reads")            # reply after release


def _fence_enter(s):
    # server: rank 1's fence arrival needs the cond
    with s.cond_lock:
        s.arrived.add(1)
    weave.signal("rank1-fenced")


def _fence_slow_client(s):
    # client 0 drains its socket only after rank 1's fence resolves
    weave.block("rank1-fenced")
    weave.signal("client0-reads")


def _fence_check(s):
    assert "shutdown" in s.fence_done and 1 in s.arrived


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def _build() -> dict:
    return {
        "staging-checkout": weave.Scenario(
            "staging-checkout",
            _staging_setup(_RevertedCheckoutPool),
            [_staging_acquirer, _staging_stale_release],
            check=_staging_check, preemption_bound=2,
            description="PR 6 staging fix reverted: checkout "
                        "registration outside the popping critical "
                        "section"),
        "staging-checkout-fixed": weave.Scenario(
            "staging-checkout-fixed",
            _staging_setup(_StagingPool),
            [_staging_acquirer, _staging_stale_release],
            check=_staging_check, preemption_bound=2,
            description="same threads on the real pool: no schedule "
                        "fails"),
        "tcp-conns": weave.Scenario(
            "tcp-conns",
            _tcp_setup(_RevertedDropBtl),
            [_tcp_dropper, _tcp_dropper],
            check=_tcp_check, preemption_bound=2,
            description="PR 6 tcp fix reverted: rail-list drop with no "
                        "_conns_lock"),
        "tcp-conns-fixed": weave.Scenario(
            "tcp-conns-fixed",
            _tcp_setup(TcpBtl),
            [_tcp_dropper, _tcp_dropper],
            check=_tcp_check, preemption_bound=2,
            description="same double drop on the real btl: no schedule "
                        "fails"),
        "coord-fence": weave.Scenario(
            "coord-fence",
            _fence_setup(True),
            [_fence_late_reply, _fence_enter, _fence_slow_client],
            check=_fence_check, preemption_bound=1,
            description="PR 6 coord fix reverted: blocking reply under "
                        "_fence_cond"),
        "coord-fence-fixed": weave.Scenario(
            "coord-fence-fixed",
            _fence_setup(False),
            [_fence_late_reply, _fence_enter, _fence_slow_client],
            check=_fence_check, preemption_bound=2,
            description="reply sent after the cond is released: no "
                        "schedule deadlocks"),
    }


SCENARIOS = _build()


def get(name: str) -> weave.Scenario:
    return SCENARIOS[name]


def expected_to_fail(name: str) -> bool:
    """Reverted scenarios must fail; their fixed twins must not."""
    return not name.endswith("-fixed")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.analysis.scenarios",
        description="Explore (or replay) the PR 6 reverted-race weave "
                    "scenarios")
    ap.add_argument("names", nargs="*", default=None,
                    help="Scenario names (default: all)")
    ap.add_argument("--replay", metavar="SCHEDULE",
                    help="Replay one exact schedule string instead of "
                         "exploring")
    ap.add_argument("--list", action="store_true",
                    help="List scenarios and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name, sc in SCENARIOS.items():
            expect = "expect FAIL" if expected_to_fail(name) \
                else "expect pass"
            print(f"{name + ':':<26} [{expect}] {sc.description}")
        return 0
    names = args.names or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s): {', '.join(unknown)} "
                 f"(--list shows the catalog)")
    if args.replay:
        try:
            sname, _b, _c = weave.parse_schedule(args.replay)
        except ValueError as exc:
            ap.error(str(exc))
        if sname not in SCENARIOS:
            ap.error(f"schedule names unknown scenario {sname!r} "
                     f"(--list shows the catalog)")
        res = weave.replay(SCENARIOS[sname], args.replay)
        print(res.summary())
        return 0 if res.failed == expected_to_fail(sname) else 1
    bad = 0
    for name in names:
        res = weave.explore(SCENARIOS[name])
        ok = res.failed == expected_to_fail(name)
        print(("ok   " if ok else "BAD  ") + res.summary())
        if not ok:
            bad += 1
    return 1 if bad else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
