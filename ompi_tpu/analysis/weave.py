"""weave — a systematic interleaving explorer for the declared-lock layer.

Static passes prove what is provable from source; races live in the
*schedules*.  The three PR 6 concurrency bugs (staging checkout window,
unguarded tcp rail lists, coord reply under the fence condition) were
each found by a reviewer imagining one specific interleaving — weave
enumerates the interleavings instead, CHESS-style:

- Scenario threads run fully **serialized**: exactly one thread executes
  between *yield points* (``pause()``, every :class:`WeaveLock`
  acquire/release, ``block()``/``signal()`` event edges).  With all
  scheduling decisions at yield points, a run is a pure function of its
  choice sequence — the *schedule*.
- The explorer drives a bounded-preemption DFS over schedules: the
  default policy runs each thread until it blocks; every alternative
  choice at a yield point costs one preemption, up to the scenario's
  bound.  Most real races need 1-2 preemptions (the CHESS result), so a
  small bound finds them in tens of schedules, deterministically.
- A failing run — uncaught exception, deadlock among ``must_finish``
  threads, or a failed ``check()`` — reports a **replayable schedule
  string** (``staging-checkout@pb2:0.0.1.1.0``).  :func:`replay` re-runs
  exactly that schedule; because execution is serialized, the failure
  reproduces every time.

Locks come from the same ``_guarded_by`` convention the lock-discipline
pass enforces: :func:`instrument` reads a class's declaration and swaps
the named plain-mutex attributes for :class:`WeaveLock` wrappers **only
while a run is active** (Condition guards are left untouched — model
their wait/notify protocol with ``block()``/``signal()``).  Outside a run every primitive is identity —
``instrument`` returns the object untouched, ``pause`` is an immediate
return, ``make_lock`` hands back a plain ``threading.RLock`` — so the
production hot paths never see a wrapper (pinned next to
``test_sanitizer_off_zero_overhead``).

Runs are wired to ``OTPU_SANITIZE``: the explorer arms
``sanitizer.enabled`` for the duration of every run, so the dynamic
ownership assertions (staging double-release, framing desync) act as
failure oracles inside the exploration; scenario threads that
*deliberately* provoke a guarded error swallow the expected
``SanitizeError`` — a schedule where the guard catches the bug is a
PASSING schedule, a schedule where it slips past is the race.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

__all__ = ["Scenario", "WeaveLock", "WeaveResult", "ReplayMismatch",
           "block", "explore", "format_schedule", "instrument",
           "make_lock", "parse_schedule", "pause", "replay", "signal",
           "active"]

RUNNABLE = "runnable"
DONE = "done"


class ReplayMismatch(Exception):
    """A forced schedule choice named a thread that is not runnable —
    the schedule string does not belong to this scenario/build."""


class _Killed(BaseException):
    """Raised inside leftover scenario threads during run teardown.
    BaseException so scenario code's ``except Exception`` can't eat it."""


@dataclass
class Scenario:
    """One weave-explorable situation.

    ``setup()`` builds the shared state (instrument locks here);
    ``threads`` are callables taking that state, each run as one
    serialized weave thread; ``check(state)`` (optional) asserts the
    invariant after all threads finish; ``must_finish`` names the thread
    indices whose failure to terminate is a deadlock (default: all).
    """

    name: str
    setup: Callable
    threads: Sequence[Callable]
    check: Optional[Callable] = None
    must_finish: Optional[Sequence[int]] = None
    preemption_bound: int = 2
    max_steps: int = 2000
    max_schedules: int = 20000
    description: str = ""

    def required(self) -> set:
        if self.must_finish is None:
            return set(range(len(self.threads)))
        return set(self.must_finish)


@dataclass
class WeaveResult:
    scenario: str
    failed: bool
    schedule: Optional[str] = None      # replayable string when failed
    kind: str = ""                      # exception|deadlock|check|step-limit
    error: Optional[BaseException] = None
    schedules: int = 0                  # schedules executed
    exhausted: bool = True              # full bounded space covered

    def summary(self) -> str:
        if not self.failed:
            return (f"weave[{self.scenario}]: PASS — {self.schedules} "
                    f"schedule(s), no failing interleaving"
                    + ("" if self.exhausted else " (budget hit)"))
        return (f"weave[{self.scenario}]: FAIL ({self.kind}: {self.error!r})"
                f" after {self.schedules} schedule(s)\n"
                f"  replay: {self.schedule}")


# ---------------------------------------------------------------------------
# schedule strings
# ---------------------------------------------------------------------------

def format_schedule(name: str, bound: int, choices: Sequence[int]) -> str:
    return f"{name}@pb{bound}:" + ".".join(str(c) for c in choices)


def parse_schedule(s: str) -> tuple[str, int, list[int]]:
    head, _, tail = s.partition(":")
    name, _, pb = head.partition("@pb")
    if not name or not pb.isdigit():
        raise ValueError(f"bad weave schedule string {s!r} "
                         "(want name@pb<bound>:c0.c1...)")
    choices = [int(c) for c in tail.split(".") if c != ""]
    return name, int(pb), choices


# ---------------------------------------------------------------------------
# the serialized run
# ---------------------------------------------------------------------------

_current: Optional["_Run"] = None


def active() -> Optional["_Run"]:
    """The in-flight run, or None — every public primitive is identity
    when this is None (the zero-overhead-off contract)."""
    return _current


class _WThread:
    __slots__ = ("idx", "fn", "thread", "go", "state", "waiting")

    def __init__(self, idx: int, fn):
        self.idx = idx
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Semaphore(0)
        self.state = RUNNABLE
        self.waiting = None         # ("lock", WeaveLock) | ("event", tag)


class WeaveLock:
    """Deterministic mutex (re-entrant, like the pool's RLock): acquire
    and full release are yield points; a thread waiting on a held lock
    is not runnable until the holder lets go."""

    __slots__ = ("_run", "name", "owner", "depth")

    def __init__(self, run: "_Run", name: str = "lock"):
        self._run = run
        self.name = name
        self.owner = None
        self.depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # non-blocking probes AND timed acquires keep their may-fail
        # contract: both become a choice point followed by
        # take-or-decline, so exploration reaches the real code's
        # timed-out fallback path instead of mis-reporting a deadlock
        if not blocking or (timeout is not None and timeout >= 0):
            return self._run._lock_try_acquire(self)
        self._run._lock_acquire(self)
        return True

    def release(self) -> None:
        self._run._lock_release(self)

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _Run:
    def __init__(self, scenario: Scenario, prefix: Sequence[int]):
        self.scenario = scenario
        self.prefix = list(prefix)
        self.threads: list[_WThread] = []
        self.by_ident: dict[int, _WThread] = {}
        self.events: set = set()
        self.choices: list[int] = []
        self.options: list[list[int]] = []
        self.errors: list = []
        self.failure: Optional[tuple] = None     # (kind, error)
        self.ctl = threading.Semaphore(0)
        self.current: Optional[_WThread] = None
        self.killing = False
        self.state_obj = None

    # -- worker-side primitives ------------------------------------------
    def _me(self) -> Optional[_WThread]:
        return self.by_ident.get(threading.get_ident())

    def _yield(self, t: _WThread) -> None:
        if self.killing:
            # teardown already woke this thread once; a re-entry (e.g.
            # WeaveLock.__exit__ running while _Killed unwinds a with
            # block) must NOT park again — nobody will wake it
            raise _Killed()
        self.ctl.release()
        t.go.acquire()
        if self.killing:
            raise _Killed()

    def _yield_runnable(self, t: _WThread) -> None:
        """A pure choice point: the thread stays runnable."""
        t.waiting = None
        self._yield(t)

    def _lock_acquire(self, lock: WeaveLock) -> None:
        t = self._me()
        if t is None:                    # controller (setup/check phase)
            if lock.owner is None or lock.owner == "controller":
                lock.owner = "controller"
                lock.depth += 1
                return
            raise RuntimeError(
                f"weave lock '{lock.name}' still held by a scenario "
                "thread at check time")
        if lock.owner is t:
            lock.depth += 1              # re-entrant
            return
        t.waiting = ("lock", lock)
        self._yield(t)                   # scheduled only when free
        lock.owner = t
        lock.depth = 1

    def _lock_try_acquire(self, lock: WeaveLock) -> bool:
        """Non-blocking probe (``acquire(blocking=False)``): a choice
        point, then take-or-decline — never a wait.  Preserves the
        try-acquire semantics of instrumented code instead of silently
        turning the probe into a blocking wait."""
        t = self._me()
        if t is None:
            if lock.owner is None or lock.owner == "controller":
                lock.owner = "controller"
                lock.depth += 1
                return True
            return False
        if lock.owner is t:
            lock.depth += 1
            return True
        self._yield_runnable(t)          # let contenders race the probe
        if lock.owner is None:
            lock.owner = t
            lock.depth = 1
            return True
        return False

    def _lock_release(self, lock: WeaveLock) -> None:
        t = self._me()
        if t is None:
            lock.depth -= 1
            if lock.depth == 0:
                lock.owner = None
            return
        if lock.owner is not t:
            raise RuntimeError(
                f"weave lock '{lock.name}' released by thread "
                f"{t.idx} which does not hold it")
        lock.depth -= 1
        if lock.depth > 0:
            return
        lock.owner = None
        # full release is a yield point: the first instant a waiter
        # could jump in (the _HookLock family of races lives here)
        self._yield(t)

    # -- scheduling -------------------------------------------------------
    def _runnable(self, t: _WThread) -> bool:
        if t.state == DONE:
            return False
        if t.waiting is None:
            return True
        kind, what = t.waiting
        if kind == "lock":
            return what.owner is None
        return what in self.events       # ("event", tag)

    def _decide(self, runnable: list) -> _WThread:
        step = len(self.choices)
        if step < len(self.prefix):
            want = self.prefix[step]
            for t in runnable:
                if t.idx == want:
                    return t
            raise ReplayMismatch(
                f"schedule step {step} wants thread {want}, but only "
                f"{[t.idx for t in runnable]} are runnable — the "
                "schedule string does not match this scenario/build")
        if self.current is not None and self.current in runnable:
            return self.current          # default: run until blocked
        return runnable[0]

    def _worker(self, t: _WThread) -> None:
        t.go.acquire()
        if self.killing:
            t.state = DONE
            self.ctl.release()
            return
        try:
            t.fn(self.state_obj)
        except _Killed:
            pass
        except BaseException as exc:     # the failure oracle
            self.errors.append((t.idx, exc))
        finally:
            t.state = DONE
            self.ctl.release()

    def execute(self) -> None:
        global _current
        from ompi_tpu.runtime import sanitizer

        prev_current, _current = _current, self
        prev_sanitize = sanitizer.enabled
        sanitizer.enabled = True         # OTPU_SANITIZE oracles armed
        try:
            self.state_obj = self.scenario.setup()
            for i, fn in enumerate(self.scenario.threads):
                t = _WThread(i, fn)
                t.thread = threading.Thread(
                    target=self._worker, args=(t,),
                    name=f"weave-{self.scenario.name}-{i}", daemon=True)
                self.threads.append(t)
            for t in self.threads:
                t.thread.start()
                self.by_ident[t.thread.ident] = t
            self._schedule_loop()
            self._teardown()
            if self.failure is None and self.scenario.check is not None:
                try:
                    self.scenario.check(self.state_obj)
                except BaseException as exc:
                    self.failure = ("check", exc)
        finally:
            sanitizer.enabled = prev_sanitize
            _current = prev_current

    def _schedule_loop(self) -> None:
        required = self.scenario.required()
        while True:
            undone = [t for t in self.threads if t.state != DONE]
            if not undone:
                break
            runnable = [t for t in undone if self._runnable(t)]
            if not runnable:
                stuck = sorted(t.idx for t in undone
                               if t.idx in required)
                if stuck:
                    self.failure = ("deadlock", RuntimeError(
                        f"threads {stuck} blocked with no runnable "
                        "thread: "
                        + ", ".join(self._describe(t) for t in undone)))
                break                    # optional threads may stay parked
            if len(self.choices) >= self.scenario.max_steps:
                self.failure = ("step-limit", RuntimeError(
                    f"run exceeded {self.scenario.max_steps} yield "
                    "points — livelock or unbounded loop"))
                break
            try:
                choice = self._decide(runnable)
            except ReplayMismatch as exc:
                self.failure = ("replay-mismatch", exc)
                break
            self.options.append(sorted(t.idx for t in runnable))
            self.choices.append(choice.idx)
            choice.waiting = None
            self.current = choice
            choice.go.release()
            self.ctl.acquire()
            if self.errors and self.failure is None:
                idx, exc = self.errors[0]
                self.failure = ("exception", exc)
                break

    def _describe(self, t: _WThread) -> str:
        if t.waiting is None:
            return f"t{t.idx}:runnable"
        kind, what = t.waiting
        label = what.name if kind == "lock" else what
        return f"t{t.idx}:waiting-{kind}({label})"

    def _teardown(self) -> None:
        self.killing = True
        for t in self.threads:
            if t.state != DONE:
                t.go.release()
                self.ctl.acquire()
        for t in self.threads:
            if t.thread is not None:
                t.thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# public primitives (identity when no run is active)
# ---------------------------------------------------------------------------

def pause(tag: str = "") -> None:
    """A pure yield point (plant at the instants a preempted thread
    would expose a window — the documented revert sites)."""
    run = _current
    if run is None:
        return
    t = run._me()
    if t is not None:
        run._yield(t)


def block(tag: str) -> None:
    """Park until :func:`signal` publishes ``tag`` (models externally
    gated blocking ops: a peer's read, a socket drain)."""
    run = _current
    if run is None:
        return
    t = run._me()
    if t is None:
        return
    while tag not in run.events:
        t.waiting = ("event", tag)
        run._yield(t)


def signal(tag: str) -> None:
    """Publish ``tag`` (and yield: waiters race the signaller's
    continuation)."""
    run = _current
    if run is None:
        return
    run.events.add(tag)
    t = run._me()
    if t is not None:
        run._yield(t)


def make_lock(name: str = "lock"):
    """A lock for scenario-local state: a :class:`WeaveLock` inside a
    run, a plain ``threading.RLock`` outside (identity-off)."""
    run = _current
    if run is None:
        return threading.RLock()
    return WeaveLock(run, name)


def instrument(obj):
    """Swap ``obj``'s ``_guarded_by``-declared lock attributes for
    :class:`WeaveLock` wrappers — ONLY while a run is active.  Outside a
    run this returns ``obj`` untouched (no wrapper on any Lock acquire:
    the zero-overhead-off pin)."""
    run = _current
    if run is None:
        return obj
    declared = getattr(type(obj), "_guarded_by", None)
    if not declared:
        return obj
    for lock_attr in sorted(set(declared.values())):
        cur = getattr(obj, lock_attr, None)
        if cur is None or isinstance(cur, WeaveLock):
            continue
        if hasattr(cur, "notify"):
            # a Condition guard (CoordServer's _kv_cond/_fence_cond
            # family): WeaveLock has no wait()/notify() — clobbering it
            # would crash the first wait mid-schedule.  Left untouched;
            # model condition protocols with block()/signal() instead
            # (the coord-fence scenario is the worked example).
            continue
        setattr(obj, lock_attr,
                WeaveLock(run, f"{type(obj).__name__}.{lock_attr}"))
    return obj


# ---------------------------------------------------------------------------
# exploration + replay
# ---------------------------------------------------------------------------

def _preemptions(choices: Sequence[int],
                 options: Sequence[Sequence[int]]) -> int:
    """Schedule cost: switching away from a still-runnable thread."""
    count = 0
    for i in range(1, len(choices)):
        if choices[i] != choices[i - 1] and choices[i - 1] in options[i]:
            count += 1
    return count


def _execute(scenario: Scenario, prefix: Sequence[int]) -> _Run:
    run = _Run(scenario, prefix)
    run.execute()
    return run


def explore(scenario: Scenario) -> WeaveResult:
    """Bounded-preemption DFS over schedules.  Returns on the FIRST
    failing schedule (with its replay string) or after covering the
    bounded space."""
    stack: list[tuple] = [()]
    executed = 0
    while stack:
        if executed >= scenario.max_schedules:
            return WeaveResult(scenario.name, False, schedules=executed,
                               exhausted=False)
        prefix = stack.pop()
        run = _execute(scenario, list(prefix))
        executed += 1
        if run.failure is not None:
            kind, error = run.failure
            return WeaveResult(
                scenario.name, True,
                schedule=format_schedule(scenario.name,
                                         scenario.preemption_bound,
                                         run.choices),
                kind=kind, error=error, schedules=executed)
        # branch: alternatives beyond the forced prefix, innermost last
        # so the DFS extends the deepest divergence first
        for i in range(len(prefix), len(run.choices)):
            opts = run.options[i]
            for alt in opts:
                if alt == run.choices[i]:
                    continue
                cand = tuple(run.choices[:i]) + (alt,)
                if _preemptions(cand, run.options[:i + 1]) \
                        <= scenario.preemption_bound:
                    stack.append(cand)
    return WeaveResult(scenario.name, False, schedules=executed)


def replay(scenario: Scenario, schedule: str) -> WeaveResult:
    """Re-run one exact schedule from its printed string.  The run is
    serialized, so a failing schedule fails identically every time."""
    name, bound, choices = parse_schedule(schedule)
    if name != scenario.name:
        raise ValueError(f"schedule is for scenario {name!r}, "
                         f"not {scenario.name!r}")
    run = _execute(scenario, choices)
    if run.failure is not None:
        kind, error = run.failure
        return WeaveResult(
            scenario.name, True,
            schedule=format_schedule(scenario.name, bound, run.choices),
            kind=kind, error=error, schedules=1)
    return WeaveResult(scenario.name, False, schedules=1)
