"""otpu-lint — invariant-encoding static analysis for the runtime hot paths.

Every review pass so far has caught the same bug families by hand: borrowed
views escaping their btl.send call, staging acquire/release pairs broken on
one path, guarded structures mutated outside their lock, show_help keys
nobody registered.  These are *encodable* invariants — this package encodes
them as AST passes (stdlib ``ast``, no new deps) the way the reference OMPI
leans on valgrind/memchecker wiring rather than reviewer vigilance.

Architecture:

- :class:`Module` / :class:`Package` — parsed source units.  ASTs are
  parsed once per (path, mtime, size) and shared by every pass
  (the module-level cache is what keeps a whole-package run under the
  tier-1 budget).
- :class:`AnalysisPass` — one invariant family; registered via
  :func:`register_pass`, enumerated by :func:`all_passes` (the CLI and
  ``otpu_info --lint`` both read the registry).
- :class:`Suppressions` — the checked-in baseline: grandfathered findings
  live in a reviewable file, one justified entry per line.
- :func:`lint` — front door: load, run, partition into kept/suppressed.

Annotation conventions the passes understand (see README "static
analysis & sanitizer"):

- ``_guarded_by = {"attr": "lock_attr"}`` on a class (or module-level
  ``_GUARDED_BY``) declares which lock serializes mutations of a
  structure; methods whose name ends in ``_locked`` are assumed called
  with the lock already held.
- ``@hot_path`` (``ompi_tpu.runtime.hotpath``) tags allocation-budgeted
  functions; the decorator itself is identity at runtime.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

__all__ = [
    "AnalysisPass", "Finding", "Module", "Package", "Suppressions",
    "all_passes", "get_pass", "lint", "load_package", "register_pass",
]


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str          # path as given to the linter (repo-relative in CI)
    line: int
    col: int
    message: str
    symbol: str = ""   # enclosing function/class qualname, "" at module level

    def format(self, parsable: bool = False) -> str:
        if parsable:
            return (f"{self.path}:{self.line}:{self.col}:{self.rule}:"
                    f"{self.symbol}:{self.message}")
        where = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}]{where} {self.message}")


class Module:
    """One parsed source file plus the derived tables passes share."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.source = source
        self.tree = tree
        self._qualnames: Optional[dict[int, str]] = None

    def functions(self) -> Iterator[tuple[ast.AST, str]]:
        """Yield every (Function/AsyncFunctionDef, qualname), nested ones
        included (``Class.method``, ``outer.<locals>.inner``)."""
        if self._qualnames is None:
            self._qualnames = {}
            self._walk_quals(self.tree, "")
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, self._qualnames.get(id(node), node.name)

    def _walk_quals(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                self._qualnames[id(child)] = qual
                self._walk_quals(child, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                self._walk_quals(child, f"{prefix}{child.name}.")
            else:
                self._walk_quals(child, prefix)

    def classes(self) -> Iterator[ast.ClassDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node


class Package:
    """The whole lint target: every module, plus parse errors."""

    def __init__(self) -> None:
        self.modules: list[Module] = []
        self.errors: list[Finding] = []

    def find(self, suffix: str) -> Optional[Module]:
        """Module whose (slash-normalized) path ends with ``suffix``."""
        for mod in self.modules:
            if mod.path.replace(os.sep, "/").endswith(suffix):
                return mod
        return None


# AST cache: abspath -> (mtime_ns, size, Module).  Every pass in a run —
# and repeated runs in one process (tests) — reuse the same parse.
_ast_cache: dict[str, tuple[int, int, Module]] = {}


def _load_file(path: str, pkg: Package) -> None:
    apath = os.path.abspath(path)
    try:
        st = os.stat(apath)
    except OSError as exc:
        pkg.errors.append(Finding("parse-error", path, 0, 0, str(exc)))
        return
    hit = _ast_cache.get(apath)
    if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
        mod = hit[2]
        if mod.path != path:   # same file reached via a different CWD
            mod = Module(path, mod.source, mod.tree)
            _ast_cache[apath] = (st.st_mtime_ns, st.st_size, mod)
        pkg.modules.append(mod)
        return
    try:
        with open(apath, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as exc:
        pkg.errors.append(Finding(
            "parse-error", path, getattr(exc, "lineno", 0) or 0, 0,
            f"cannot parse: {exc}"))
        return
    mod = Module(path, source, tree)
    _ast_cache[apath] = (st.st_mtime_ns, st.st_size, mod)
    pkg.modules.append(mod)


def load_package(paths) -> Package:
    """Parse ``paths`` (files or directories, recursively) into a Package."""
    pkg = Package()
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        _load_file(os.path.join(root, fname), pkg)
        else:
            _load_file(p, pkg)
    return pkg


# ---------------------------------------------------------------------------
# shared AST helpers (passes import these)
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str:
    """Dotted name of a call target ("" when not a plain name chain)."""
    return dotted(call.func) or ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

class AnalysisPass:
    """One invariant family.  Subclasses set ``name``/``description`` and
    implement :meth:`run` over the whole package (cross-file invariants —
    help-key registration, lock-order graphs — need the package view; a
    per-file pass just iterates ``pkg.modules``)."""

    name = ""
    description = ""

    def run(self, pkg: Package) -> list[Finding]:
        raise NotImplementedError


_registry: dict[str, AnalysisPass] = {}


def register_pass(cls):
    inst = cls()
    _registry[inst.name] = inst
    return cls


def _load_builtin() -> None:
    from ompi_tpu.analysis import passes  # noqa: F401  (registration side effect)


def all_passes() -> list[AnalysisPass]:
    _load_builtin()
    return list(_registry.values())


def get_pass(name: str) -> AnalysisPass:
    _load_builtin()
    return _registry[name]


# ---------------------------------------------------------------------------
# suppressions (the checked-in baseline)
# ---------------------------------------------------------------------------

@dataclass
class _Entry:
    rule: str
    path: str            # suffix-matched against finding paths
    symbol: str          # "" matches any symbol
    line_no: int         # line in the suppressions file (diagnostics)
    used: int = 0


@dataclass
class Suppressions:
    """Baseline file: ``<rule> <path>[:<symbol>]  # why`` per line.

    Matching is by rule + path suffix + (optional) enclosing symbol —
    deliberately NOT by line number, so unrelated edits above a
    grandfathered site don't invalidate the baseline.
    """

    entries: list = field(default_factory=list)
    path: str = ""

    @classmethod
    def parse(cls, text: str, path: str = "<string>") -> "Suppressions":
        sup = cls(path=path)
        for i, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(
                    f"{path}:{i}: bad suppression {raw!r} "
                    "(want: <rule> <path>[:<symbol>])")
            rule, target = parts
            fpath, _, symbol = target.partition(":")
            sup.entries.append(_Entry(rule, fpath, symbol, i))
        return sup

    @classmethod
    def load(cls, path: str) -> "Suppressions":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            return cls.parse(f.read(), path)

    def match(self, f: Finding) -> bool:
        fpath = f.path.replace(os.sep, "/")
        for e in self.entries:
            if (e.rule == f.rule and fpath.endswith(e.path)
                    and (not e.symbol or e.symbol == f.symbol)):
                e.used += 1
                return True
        return False

    def unused(self) -> list:
        return [e for e in self.entries if not e.used]

    @staticmethod
    def render(findings) -> str:
        """Baseline text for ``findings`` (the --write-suppressions path;
        every generated entry still needs a human justification comment)."""
        lines = ["# otpu-lint suppressions — one justified entry per line:",
                 "#   <rule> <path>[:<symbol>]  # why this is deliberate"]
        seen = set()
        for f in findings:
            key = (f.rule, f.path, f.symbol)
            if key in seen:
                continue
            seen.add(key)
            target = f.path.replace(os.sep, "/")
            if f.symbol:
                target += f":{f.symbol}"
            lines.append(f"{f.rule} {target}  # TODO justify: {f.message}")
        return "\n".join(lines) + "\n"


@dataclass
class LintResult:
    findings: list          # kept (unsuppressed) findings, sorted
    suppressed: list        # findings matched by the baseline
    errors: list            # parse errors (never suppressible)
    files: int = 0
    passes: int = 0
    pass_names: list = field(default_factory=list)
    linted_paths: list = field(default_factory=list)   # slash-normalized
    timings: list = field(default_factory=list)        # (pass, seconds)

    def format_timings(self) -> str:
        """Per-pass wall-clock breakdown (the CI gate prints this when
        the run blows its budget, so the slow pass names itself)."""
        total = sum(t for _n, t in self.timings)
        rows = [f"  {n + ':':<22} {t * 1e3:8.1f} ms"
                for n, t in sorted(self.timings,
                                   key=lambda x: -x[1])]
        return "\n".join(rows + [f"  {'total:':<22} {total * 1e3:8.1f} ms"])

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def unused_suppressions(self, sup: "Suppressions") -> list:
        """Baseline entries this run PROVED stale: never matched, their
        rule ran, and their file was among the linted paths.  A partial
        run (subset paths or --select) cannot prove anything about
        entries outside its scope, so those are not reported."""
        return [e for e in sup.unused()
                if e.rule in self.pass_names
                and any(p.endswith(e.path) for p in self.linted_paths)]


def lint(paths, select=None, suppressions: Optional[Suppressions] = None,
         ) -> LintResult:
    """Run ``select`` passes (default: all) over ``paths``."""
    pkg = load_package(paths)
    passes = all_passes()
    if select:
        want = set(select)
        unknown = want - {p.name for p in passes}
        if unknown:
            raise KeyError(f"unknown pass(es): {', '.join(sorted(unknown))}")
        passes = [p for p in passes if p.name in want]
    import time

    findings: list[Finding] = []
    timings: list[tuple] = []
    for p in passes:
        t0 = time.monotonic()
        findings.extend(p.run(pkg))
        timings.append((p.name, time.monotonic() - t0))
    findings = sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.col, f.rule))
    kept, shed = [], []
    for f in findings:
        (shed if suppressions is not None and suppressions.match(f)
         else kept).append(f)
    return LintResult(
        kept, shed, list(pkg.errors),
        files=len(pkg.modules), passes=len(passes),
        pass_names=[p.name for p in passes],
        linted_paths=[m.path.replace(os.sep, "/") for m in pkg.modules],
        timings=timings)
