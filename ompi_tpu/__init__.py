"""ompi_tpu — a TPU-native communication framework with Open MPI's capabilities.

Brand-new design (reference: gcramer23/ompi, Open MPI 5.1.0a1 ULFM branch at
``/root/reference/``): MPI-style API (point-to-point, full collective suite,
one-sided RMA, MPI-IO, communicators/groups/datatypes/ops, dynamic processes,
tools interface), an MCA-style component architecture with priority-based
runtime selection and a typed var registry, a distributed launch/wire-up
runtime, ULFM-style fault tolerance, and an OpenSHMEM-style PGAS layer —
rebuilt idiomatically on JAX/XLA/Pallas/pjit.  The compute path is XLA: device
collectives lower to ``lax.psum`` / ``all_gather`` / ``psum_scatter`` /
``all_to_all`` / ``ppermute`` over the ICI mesh via the ``coll/xla`` component.
"""
from __future__ import annotations

__version__ = "0.1.0"

# Lazy public API: importing ompi_tpu must stay cheap (no jax import) so the
# launcher and tools can use the base layer alone.
_API = {
    "init": "ompi_tpu.runtime.init",
    "finalize": "ompi_tpu.runtime.init",
    "initialized": "ompi_tpu.runtime.init",
    "finalized": "ompi_tpu.runtime.init",
    "init_thread": "ompi_tpu.runtime.init",
    "query_thread": "ompi_tpu.runtime.interlib",
    "is_thread_main": "ompi_tpu.runtime.interlib",
    "THREAD_SINGLE": "ompi_tpu.runtime.interlib",
    "THREAD_FUNNELED": "ompi_tpu.runtime.interlib",
    "THREAD_SERIALIZED": "ompi_tpu.runtime.interlib",
    "THREAD_MULTIPLE": "ompi_tpu.runtime.interlib",
    "wtime": "ompi_tpu.api.env",
    "wtick": "ompi_tpu.api.env",
    "get_processor_name": "ompi_tpu.api.env",
    "get_version": "ompi_tpu.api.env",
    "get_library_version": "ompi_tpu.api.env",
    "alloc_mem": "ompi_tpu.api.env",
    "free_mem": "ompi_tpu.api.env",
    "COMM_WORLD": "ompi_tpu.runtime.init",
    "COMM_SELF": "ompi_tpu.runtime.init",
    "Comm": "ompi_tpu.api.comm",
    "Group": "ompi_tpu.api.group",
    "Session": "ompi_tpu.api.session",
    "Request": "ompi_tpu.api.request",
    "Datatype": "ompi_tpu.datatype",
    "Op": "ompi_tpu.api.op",
    "Info": "ompi_tpu.api.info",
    "Win": "ompi_tpu.api.win",
    "File": "ompi_tpu.api.file",
    "Status": "ompi_tpu.api.status",
    # dynamic process management (MPI_Comm_get_parent / ports)
    "get_parent": "ompi_tpu.dpm",
    "open_port": "ompi_tpu.dpm",
    # built-in reduction operators (MPI_SUM & friends)
    "SUM": "ompi_tpu.api.op",
    "PROD": "ompi_tpu.api.op",
    "MAX": "ompi_tpu.api.op",
    "MIN": "ompi_tpu.api.op",
    "LAND": "ompi_tpu.api.op",
    "LOR": "ompi_tpu.api.op",
    "BAND": "ompi_tpu.api.op",
    "BOR": "ompi_tpu.api.op",
    "BXOR": "ompi_tpu.api.op",
    "MAXLOC": "ompi_tpu.api.op",
    "MINLOC": "ompi_tpu.api.op",
    "REPLACE": "ompi_tpu.api.op",
    "NO_OP": "ompi_tpu.api.op",
}


def __getattr__(name: str):
    mod_name = _API.get(name)
    if mod_name is None:
        raise AttributeError(f"module 'ompi_tpu' has no attribute {name!r}")
    import importlib

    try:
        mod = importlib.import_module(mod_name)
    except ModuleNotFoundError as exc:
        raise AttributeError(
            f"module 'ompi_tpu' attribute {name!r} unavailable: {exc}") from exc
    if name in ("COMM_WORLD", "COMM_SELF"):
        return getattr(mod, name.lower())()
    val = getattr(mod, name)
    globals()[name] = val
    return val
