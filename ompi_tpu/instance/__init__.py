"""ompi_tpu.instance — the runtime instance behind MPI-4 Sessions.

Re-design of ``ompi/instance/instance.c``: Open MPI 5.x made the
*instance* the true owner of runtime boot — ``MPI_Session_init`` and
world-model ``MPI_Init`` both just acquire the one underlying instance,
a refcount tracks how many owners (open sessions + the implicit world)
are alive, and only the LAST release tears the RTE down
(``ompi_mpi_instance_init``/``_finalize`` with ``instance_lock`` +
``ompi_instance_count``).  Consequences this module is careful to keep:

* N sessions and world init share ONE RTE/coord boot (one modex fence,
  one pml selection) — acquiring an already-booted instance is a
  refcount bump, nothing else;
* ``MPI_Init`` after ``MPI_Finalize`` works: when the count hits zero
  the boot state machine returns to ground and the next acquire boots
  fresh (the MPI-4 relaxation of the old once-per-process rule);
* process sets are an instance-level concept that exists BEFORE any
  communicator does: builtin ``mpi://WORLD`` / ``mpi://SELF`` plus
  whatever the coordination service advertises (per-host sets, user
  ``tpurun --pset`` sets, dynamic sets published on spawn/shrink).

TPU hat: the instance also owns the *device world*.  On boot under
``tpurun --device-world`` it initializes ``jax.distributed`` —
coordinator address from the coord service KV, ``process_id`` from the
job rank map — so the global device mesh spans processes and ``coll/
xla`` device collectives finally cross process boundaries (the
PMIx-shaped role of ``ompi_rte.c:568`` worn by the device path).
"""
from __future__ import annotations

import atexit
import os
import threading
from typing import Optional

from ompi_tpu.base import mca
from ompi_tpu.base.var import mark_runtime_initialized, registry

#: MPI-4 builtin process-set names (MPI 4.0 §11.3.2)
PSET_WORLD = "mpi://WORLD"
PSET_SELF = "mpi://SELF"

_lock = threading.RLock()
_refcount = 0
_instance: Optional["Instance"] = None
_atexit_armed = False


class Instance:
    """The booted runtime instance: RTE + selected pml + pset access.

    Never constructed directly — :func:`acquire` boots (or refcounts)
    the process-wide instance; :func:`release` drops one reference and
    tears down on the last.
    """

    def __init__(self) -> None:
        self.rte = None
        self.pml = None
        self._fenced = False
        self._torn_down = False

    # -- boot ------------------------------------------------------------
    def _boot(self, argv=None, devices=None, rte=None) -> None:
        from ompi_tpu.runtime import interlib, spc, trace

        if argv:
            registry.parse_cli(argv)
        t_boot = trace.now()

        # RTE wire-up (ompi_mpi_init.c:516 → PMIx_Init equivalent); a
        # ProcRte constructor is the coord-service connect
        from ompi_tpu.rte import base as rte_base

        t0 = trace.now()
        if rte is not None:
            self.rte = rte
        elif devices is not None:
            self.rte = rte_base.DeviceWorldRte(devices)
        else:
            self.rte = rte_base.detect()
        trace.span("coord_connect", "boot", t0)

        spc.init()
        # otpu-trace (span ring buffer + latency-histogram pvars); the
        # enable cvar was applied at registration from env/file and
        # again from the CLI parse above
        trace.init()

        # a re-boot after a prior teardown may use the work pool again
        from ompi_tpu.mca.threads import base as _threads_reopen

        _threads_reopen.reopen_pool()

        # record the booting thread (MPI_Is_thread_main anchor —
        # overrides any earlier library register() from a worker thread)
        interlib.note_main_thread(force=True)

        # CPU binding + topology modex (hwloc analog; the reference does
        # binding in PRRTE pre-exec, we do it first thing at boot)
        from ompi_tpu.base import hwloc

        if os.environ.get("OTPU_BIND_POLICY") == "core" and \
                hasattr(self.rte, "my_world_rank"):
            local_n = int(os.environ.get("OTPU_LOCAL_NRANKS", "1"))
            cpus = hwloc.compute_binding(
                self.rte.my_world_rank % max(1, local_n), max(1, local_n))
            hwloc.bind_self(cpus)
        if hasattr(self.rte, "modex_put"):
            topo = hwloc.host_topology(refresh=True)
            self.rte.modex_put("cpus", list(topo.cpus_allowed))

        # device-world boot: jax.distributed over the job's processes
        # (before the modex fence, so the fence also orders device boot)
        t0 = trace.now()
        self._boot_device_world()
        trace.span("jax_distributed_init", "boot", t0)

        # pml selection (ompi_mpi_init.c:630)
        pml_fw = mca.framework("pml", "point-to-point messaging layer")
        pml_comp = pml_fw.select()
        if pml_comp is None:
            raise RuntimeError("no pml component available")
        pml_module = pml_comp.get_module(self.rte)

        # pml/monitoring interposition (per-peer traffic matrices)
        from ompi_tpu.runtime import monitoring

        pml_module = monitoring.maybe_wrap_pml(pml_module)

        # vprotocol/pessimist interposition (message-event logging)
        from ompi_tpu.mca.pml import vprotocol

        pml_module = vprotocol.maybe_wrap_pml(pml_module, self.rte)
        self.pml = pml_module

        # modex exchange of endpoints (ompi_mpi_init.c:682-701)
        t0 = trace.now()
        self.rte.fence()
        trace.span("modex_fence", "boot", t0)

        # CIDs 0/1 belong to the predefined WORLD/SELF comms whether or
        # not the world model ever initializes — a session-built comm
        # grabbing cid 0 before a later MPI_Init would alias the
        # revocation key space (the reference likewise pre-reserves the
        # predefined communicators' ids)
        from ompi_tpu.runtime import init as _rt

        _rt.reserve_cid(0)
        _rt.reserve_cid(1)

        mark_runtime_initialized(True)

        # live telemetry plane + crash-time flight recorder: both are
        # no-ops unless their vars/triggers arm them, and both need the
        # coord client this boot just established
        from ompi_tpu.runtime import flight, profile, telemetry

        if getattr(self.rte, "client", None) is not None:
            flight.arm(self.rte)
            telemetry.start(self.rte)
        # otpu-prof needs no coord service: stage clocks are var-armed,
        # the sampling profiler publishes through telemetry if running
        profile.start(self.rte)
        trace.span("instance_boot", "boot", t_boot)

    def _boot_device_world(self) -> None:
        """Initialize ``jax.distributed`` for a multi-process device
        world (opt-in: the launcher sets ``OTPU_DEVICE_WORLD``).

        The coordinator address is read from the coord service KV
        (``__jax_coord__``, published by tpurun) with the env var
        ``OTPU_JAX_COORD`` as fallback; ``process_id`` comes from the
        job rank map (a spawned job would need its own coordinator, so
        only the primary job boots one).  On the CPU backend the gloo
        collectives implementation is selected — the stock CPU client
        rejects multiprocess computations outright.
        """
        rte = self.rte
        if os.environ.get("OTPU_DEVICE_WORLD", "") in ("", "0"):
            return
        if rte.is_device_world or getattr(rte, "job", "0") != "0":
            return
        # env override first: a KV wait would stall 30 s before the
        # documented fallback is even consulted
        addr = os.environ.get("OTPU_JAX_COORD")
        client = getattr(rte, "client", None)
        if not addr and client is not None:
            try:
                addr = client.get(-1, "__jax_coord__", wait=True,
                                  timeout=30.0)
            except Exception:
                addr = None
        if not addr:
            raise RuntimeError(
                "OTPU_DEVICE_WORLD is set but no jax coordinator address "
                "was published (launch with tpurun --device-world)")
        from ompi_tpu.base.jaxenv import apply_platform_env

        apply_platform_env()
        import jax

        if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # older jaxlib without gloo: initialize still works
        procs = list(getattr(rte, "job_ranks", range(rte.world_size)))
        from jax._src import distributed as _jd

        if getattr(_jd.global_state, "client", None) is None:
            jax.distributed.initialize(
                str(addr), num_processes=len(procs),
                process_id=procs.index(rte.my_world_rank))
        rte.device_world_booted = True
        rte.global_devices = jax.devices()
        rte.local_devices = jax.local_devices()

    # -- teardown --------------------------------------------------------
    def _fence_final(self) -> None:
        """Pre-teardown synchronisation (ompi_mpi_finalize's barrier) —
        one-shot: a fast-exiting rank must not unlink shared segments a
        slower peer is still attaching during ITS boot."""
        if self._fenced:
            return
        self._fenced = True
        fence_final = getattr(self.rte, "fence_final", None)
        if fence_final is not None:
            try:
                fence_final()
            except Exception:
                pass   # coord gone / timeout: peers are exiting too

    def _teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        try:
            self._fence_final()
            # trace export needs the coord client (KV publish + clock
            # offset), so it runs before rte.finalize tears it down
            from ompi_tpu.runtime import flight as _flight
            from ompi_tpu.runtime import monitoring as _monitoring
            from ompi_tpu.runtime import telemetry as _telemetry
            from ompi_tpu.runtime import trace as _trace

            try:
                _trace.finalize_export(self.rte)
            except Exception:
                pass   # observability must never break teardown
            try:
                # survivor post-mortem: if this job saw peer failures,
                # the ring now holds the whole recovery — dump it for
                # the launcher's flight bundle
                _flight.maybe_dump_postmortem(self.rte)
            except Exception:
                pass
            try:
                _monitoring.finalize_publish(self.rte)
            except Exception:
                pass
            try:
                _telemetry.stop()
                _flight.disarm()
            except Exception:
                pass
            try:
                from ompi_tpu.runtime import profile as _profile

                _profile.stop()
            except Exception:
                pass
            # release per-comm coll resources of any communicator the
            # user never freed (ompi_mpi_finalize destroys remaining
            # comms the same way) — shared segments must unmap here, not
            # in interpreter-exit GC where exported views race __del__
            from ompi_tpu.api import comm as _comm_mod

            for c in _comm_mod.live_comms():
                if not getattr(c, "freed", False):
                    try:
                        c.release_coll_modules()
                    except Exception:
                        pass
            if self.pml is not None:
                fin = getattr(self.pml, "finalize", None)
                if fin is not None:
                    try:
                        fin()
                    except Exception:
                        pass   # a dead peer/coord must not wedge teardown
            if self.rte is not None:
                try:
                    self.rte.finalize()
                except Exception:
                    pass
        finally:
            # ground state must be restored even if a step above threw:
            # the next boot in this process (tests, re-init) depends on
            # the pool/mca/CID/registry flags being reset
            from ompi_tpu.mca.threads import base as _threads_base

            _threads_base.shutdown_pool(permanent=True)
            mca.close_all()
            from ompi_tpu.runtime import init as _rt
            from ompi_tpu.runtime import progress

            progress.reset_for_testing()
            _rt.clear_cid_space()
            mark_runtime_initialized(False)

    # -- process sets ----------------------------------------------------
    def pset_names(self) -> list:
        """Every process-set name this instance can resolve: the MPI-4
        builtins plus whatever the coord service advertises."""
        names = [PSET_WORLD, PSET_SELF]
        client = getattr(self.rte, "client", None)
        if client is not None:
            try:
                for row in client.pset_list():
                    if row["name"] not in names:
                        names.append(row["name"])
            except Exception:
                pass   # coord gone: the builtins still resolve
        return names

    def pset_members(self, name: str) -> list:
        """World ranks of a named pset (raises on an unknown name)."""
        from ompi_tpu.api.errors import ErrorClass, MpiError

        rte = self.rte
        if name == PSET_WORLD:
            return list(getattr(rte, "job_ranks",
                                range(rte.world_size)))
        if name == PSET_SELF:
            return [rte.my_world_rank]
        client = getattr(rte, "client", None)
        entry = None
        if client is not None:
            try:
                entry = client.pset_get(name)
            except Exception:
                entry = None
        if entry is None:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"unknown process set {name!r}")
        return [int(m) for m in entry["members"]]

    def pset_source(self, name: str) -> str:
        if name in (PSET_WORLD, PSET_SELF):
            return "builtin"
        client = getattr(self.rte, "client", None)
        if client is not None:
            try:
                entry = client.pset_get(name)
                if entry is not None:
                    return str(entry.get("source", "coord"))
            except Exception:
                pass
        return "unknown"

    def pset_info(self, name: str):
        """``MPI_Session_get_pset_info``: at least ``mpi_size`` (MPI-4
        §11.3.3), plus membership and origin for introspection."""
        from ompi_tpu.api.info import Info

        members = self.pset_members(name)
        return Info({
            "mpi_size": str(len(members)),
            "otpu_members": ",".join(str(m) for m in members),
            "otpu_source": self.pset_source(name),
        })


# -- module-level acquire/release (the ompi_instance_count discipline) --

def acquire(argv=None, devices=None, rte=None) -> Instance:
    """Acquire the process-wide instance, booting the RTE on the first
    reference.  ``argv``/``devices``/``rte`` only matter for the boot;
    an already-booted instance ignores them (document over surprise:
    the first owner decides the process model, like the reference)."""
    global _refcount, _instance, _atexit_armed
    with _lock:
        if _instance is None:
            inst = Instance()
            inst._boot(argv=argv, devices=devices, rte=rte)
            _instance = inst
            if not _atexit_armed:
                _atexit_armed = True
                atexit.register(_atexit_teardown)
        _refcount += 1
        return _instance


def release() -> int:
    """Drop one reference; the last release tears the runtime down.
    Returns the remaining reference count."""
    global _refcount, _instance
    with _lock:
        if _instance is None:
            return 0
        _refcount -= 1
        if _refcount > 0:
            return _refcount
        inst, _instance = _instance, None
        _refcount = 0
        inst._teardown()
        return 0


def current() -> Optional[Instance]:
    """The booted instance, or None — never boots as a side effect."""
    return _instance


def refcount() -> int:
    with _lock:
        return _refcount


def _atexit_teardown() -> None:
    """Interpreter exit with sessions still open: drain them (the
    world's own atexit finalize ran first — atexit is LIFO and the world
    registers after the instance boots)."""
    global _refcount, _instance
    with _lock:
        if _instance is None:
            return
        inst, _instance = _instance, None
        _refcount = 0
    try:
        inst._teardown()
    except Exception:
        pass


def reset_for_testing() -> None:
    """Force-release every reference and tear down (tests only)."""
    _atexit_teardown()
