"""serving/router — the request-router rank.

The router owns admission (the continuous-batching scheduler), the
worker table, and the engine clock.  Every :meth:`Router.tick`:

1. **recover** — if the FT layer knows a worker died, revoke the comm
   (so every survivor unblocks with RevokedError), shrink to the
   ``mpi://surviving`` set, re-shard the worker table, and requeue the
   dead worker's in-flight requests — zero admitted requests dropped;
2. **admit** — the scheduler evicts finished sequences and admits
   queued ones into the freed batch space (strict FIFO), each admission
   getting a worker (least-loaded) and a KV slot;
3. **dispatch** — ONE coalesced command message per active worker:
   colocated workers get ``("work", batch, free_rids)``, disaggregated
   stage pairs get ``("prefill", epoch, ...)`` to the prefill rank and
   ``("kv", epoch, ...)`` to its decode peer before the decode work;
4. **collect** — one coalesced result message per dispatched worker;
   each completed sequence is verified (deterministic toy model),
   recorded into the ``serve_request`` otpu-trace latency histogram,
   and marked done so step 2 evicts it next tick;
5. **autoscale** — queue depth above the watermark for
   ``scale_patience`` consecutive ticks triggers ``MPI_Comm_spawn`` of
   ``scale_step`` fresh workers (collective: the workers were told in
   the same tick), verified against the dynamic ``mpi://job/<id>``
   pset, merged parents-first so every rank keeps its rank.

Deployment shapes: ``stages=False`` (default) runs colocated
prefill+decode workers; ``stages=True`` pairs the worker list — first
half prefill, second half decode — and streams KV slabs pair-wise.
After a failure the router always falls back to colocated (a pair may
have lost one side), matching the workers' own recovery.
"""
from __future__ import annotations

import collections
from typing import Optional

from ompi_tpu.api.errhandler import ERRORS_RETURN
from ompi_tpu.api.errors import (ErrorClass, MpiError, ProcFailedError,
                                 RevokedError)
from ompi_tpu.runtime import spc, telemetry, trace
from ompi_tpu.serving import frontdoor as frontdoor_mod
from ompi_tpu.serving import prefix_cache
from ompi_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                        RequestState, ServeRequest)
from ompi_tpu.serving.worker import TAG_CMD, TAG_RES, toy_token

_HIST = "serve_request"
#: per-tenant / per-pool latency-histogram family name prefixes (the
#: driver's per-tenant report and the fleet autoscaler's per-pool p99
#: signal read these; hist_reset per family keeps populations apart)
TENANT_HIST_PREFIX = "serve_tenant_"
POOL_HIST_PREFIX = "serve_pool_"


class Router:
    """Admission + dispatch + recovery for one serving communicator.

    A fleet pool is exactly one Router: ``prefill_ranks`` /
    ``decode_ranks`` size the two stage pools independently (a prefill
    rank streams to every decode rank mapped onto it), ``pool`` names
    the per-pool latency-histogram family, ``prefix_registry`` makes
    routing prefix-cache-aware, and ``manage_recovery=False`` defers
    ULFM recovery to the :class:`~ompi_tpu.serving.fleet.
    FleetController` that owns the shared communicator (several pool
    routers must not each shrink it)."""

    def __init__(self, comm, scheduler: Optional[ContinuousBatchScheduler]
                 = None, stages: bool = False, decode_chunk: int = 4,
                 kv_elems: int = 256,
                 workers: Optional[list] = None,
                 prefill_ranks: Optional[list] = None,
                 decode_ranks: Optional[list] = None,
                 prefix_registry=None,
                 pool: Optional[str] = None,
                 experts: int = 0,
                 manage_recovery: bool = True,
                 scale_watermark: Optional[int] = None,
                 scale_step: int = 1, scale_patience: int = 3,
                 scale_cooldown: int = 50,
                 scale_max_workers: Optional[int] = None,
                 scale_argv: Optional[list] = None) -> None:
        from ompi_tpu import serving as _pkg

        comm.set_errhandler(ERRORS_RETURN)
        self.comm = comm
        self.me, self.workers = _pkg.roles(comm)
        if workers is not None:        # explicit table (tests, subsets)
            self.workers = [int(w) for w in workers if int(w) != self.me]
        if not self.workers:
            raise MpiError(ErrorClass.ERR_ARG,
                           "serving needs at least one worker rank")
        self.sched = scheduler or ContinuousBatchScheduler()
        self.stages = bool(stages) or bool(prefill_ranks)
        self.decode_chunk = int(decode_chunk)
        self.kv_elems = int(kv_elems)
        self.pool = pool
        self.registry = prefix_registry
        #: expert-sharded decode pool (parallel/moe serving): > 0 means
        #: the pool's decode workers each HOME a contiguous expert
        #: range and fresh admissions prefer their expert's home rank
        self.experts = int(experts)
        self.manage_recovery = bool(manage_recovery)
        # explicit stage pools (fleet: sized independently); None means
        # the legacy half-split of the worker list
        self._prefill = [int(w) for w in prefill_ranks] \
            if prefill_ranks else None
        self._decode = [int(w) for w in decode_ranks] \
            if decode_ranks else None
        self.scale_watermark = scale_watermark
        self.scale_step = int(scale_step)
        self.scale_patience = int(scale_patience)
        self.scale_cooldown = int(scale_cooldown)
        # more workers than batch slots can never be busy — the default
        # cap keeps a persistent backlog from spawning an idle fleet
        self.scale_max_workers = (int(scale_max_workers)
                                  if scale_max_workers is not None
                                  else self.sched.max_batch)
        self.scale_argv = list(scale_argv) if scale_argv else None
        self._over_watermark = 0
        self._scale_cooling = 0
        #: (prefill rank, decode rank) -> last KV epoch (per PAIRING,
        #: not per pair index: independent pool sizing means one
        #: prefill rank can hold several slab pairings)
        self._pair_epoch: dict = {}
        self._completed: list = []
        # eviction notices: recently finished rids, re-sent with every
        # work dispatch (worker-side pops are idempotent, so repeats
        # are harmless and no notice can be misrouted across a shrink)
        self._recent_done: collections.deque = collections.deque(
            maxlen=64)
        self._lost_and_requeued = 0
        #: worker-reported full-prefill and prefill-skipped counts,
        #: accumulated ROUTER-side (SPC counters are per process; in a
        #: multi-process job only the reports can tell the router what
        #: the prefix cache actually saved — the acceptance's
        #: "prefill-stage count delta" reads these)
        self.prefill_count = 0
        self.prefix_hit_count = 0
        if self.stages and len(self.workers) < 2:
            raise MpiError(ErrorClass.ERR_ARG,
                           "disaggregated serving needs >= 2 workers "
                           "(prefill + decode)")

    # -- worker table ------------------------------------------------------
    def _stage_split(self) -> tuple:
        """(prefill ranks, decode ranks, extra ranks) — decode rank
        ``i`` streams from prefill rank ``i % P`` (P may differ from D:
        the pools are sized independently); ``extra`` (ranks in neither
        explicit pool, or the odd leftover of the legacy half-split)
        serves colocated, so no rank is silently idle.  Colocated mode
        decodes everywhere."""
        if not self.stages:
            return [], list(self.workers), []
        if self._prefill is not None:
            pre = [w for w in self._prefill if w in self.workers]
            dec = [w for w in (self._decode or []) if w in self.workers]
            extra = [w for w in self.workers
                     if w not in pre and w not in dec]
            return pre, dec, extra
        half = len(self.workers) // 2
        return (self.workers[:half], self.workers[half:half * 2],
                self.workers[half * 2:])

    def _prefill_of(self, decode_rank: int, prefill_ranks,
                    decode_ranks) -> int:
        """The prefill rank paired with ``decode_rank`` (static map:
        decode index i -> prefill index i mod P)."""
        return prefill_ranks[decode_ranks.index(decode_rank)
                             % len(prefill_ranks)]

    def _pick_worker(self, decode_ranks) -> int:
        """Least-loaded decode/colocated rank (running-request count)."""
        load = {w: 0 for w in decode_ranks}
        for r in self.sched.running():
            if r.worker in load:
                load[r.worker] += 1
        return min(decode_ranks, key=lambda w: (load[w], w))

    # -- expert-sharded decode pool (parallel/moe serving) -----------------
    def expert_of(self, req) -> int:
        """Deterministic expert for a request: a rolling integer hash
        of the prompt tokens (the request's content decides its hot
        expert, mirroring MoE gating), rid-based when there is no
        prompt.  Pure modular arithmetic — PYTHONHASHSEED-proof, the
        parallel/moe gating discipline."""
        toks = req.prompt or []
        acc = len(toks)
        for t in toks:
            acc = (acc * 8191 + int(t)) % (1 << 30)
        if not toks:
            acc = int(req.rid or 0)
        return acc % self.experts

    def expert_table(self) -> dict:
        """{expert: home worker rank} over the CURRENT decode ranks —
        contiguous ``partition`` slices, the same one-notion-of-
        sharding the MoE trainer uses, so re-binding after a shrink
        re-shards the experts over the survivors automatically."""
        from ompi_tpu.parallel.elastic import partition

        _pre, dec, extra = self._stage_split()
        homes = dec + extra
        table = {}
        if not self.experts or not homes:
            return table
        for i, w in enumerate(homes):
            lo, hi = partition(i, len(homes), self.experts)
            for e in range(lo, hi):
                table[e] = w
        return table

    def _assign(self, req, decode_ranks, extra_ranks,
                prefill_ranks) -> None:
        """Pick the worker for a fresh admission — prefix-cache-aware
        when a registry is configured and the request carries prompt
        tokens: the deepest registered block's holder wins (for a
        stage pool, the decode rank mapped onto the holding PREFILL
        rank), with the ``(hash, generation)`` hint attached for the
        worker to verify; then the request's EXPERT home rank when the
        pool is expert-sharded (cached KV beats expert-weight affinity
        — a hit skips the prefill outright, re-routing an expert costs
        only locality); everything else, least-loaded."""
        candidates = decode_ranks + extra_ranks
        if self.registry is not None and req.prompt:
            if req.hashes is None:
                req.hashes = prefix_cache.block_hashes(req.prompt)
            hit = self.registry.lookup(req.hashes)
            if hit is not None:
                target = None
                if hit.worker in candidates:
                    target = hit.worker
                elif hit.worker in prefill_ranks and decode_ranks:
                    # holder is a prefill rank: route to the least-
                    # loaded decode rank IT streams to
                    fed = [d for d in decode_ranks
                           if self._prefill_of(d, prefill_ranks,
                                               decode_ranks)
                           == hit.worker]
                    if fed:
                        target = self._pick_worker(fed)
                if target is not None:
                    req.worker = target
                    req.hint = (hit.hash, hit.generation, hit.blocks)
                    return
                # holder no longer routable (retired / re-sharded
                # between insert and lookup): drop the stale entries
                self.registry.invalidate_worker(hit.worker)
            spc.record("serve_prefix_misses")
        if self.experts:
            home = self.expert_table().get(self.expert_of(req))
            if home in candidates:
                req.worker = home
                return
        req.worker = self._pick_worker(candidates)

    # -- public API --------------------------------------------------------
    def submit(self, prompt_len: int, max_new_tokens: int,
               rid: Optional[int] = None, tenant: str = "",
               prompt=None, slo: str = "") -> ServeRequest:
        return self.sched.submit(
            ServeRequest(prompt_len, max_new_tokens, rid=rid,
                         tenant=tenant, model=self.pool or "",
                         prompt=prompt, slo=slo))

    def completed(self) -> list:
        return list(self._completed)

    @property
    def lost_and_requeued(self) -> int:
        """Requests returned to the queue by failure recovery (the
        serve-through-failure tests assert these all complete)."""
        return self._lost_and_requeued

    def tick(self) -> None:
        """One engine tick (see module doc).  Any ULFM error inside the
        tick routes through recovery and the tick retries cleanly on
        the shrunken communicator at the next call; a fleet-owned
        router (``manage_recovery=False``) re-raises instead — the
        fleet controller shrinks the SHARED comm exactly once and
        rebinds every pool."""
        try:
            self._tick_inner()
        except (RevokedError, ProcFailedError):
            if not self.manage_recovery:
                raise
            self._recover()

    def serve_until_drained(self, max_ticks: int = 100000,
                            check_invariants: bool = False) -> list:
        """Tick until every submitted request completed (tests/driver);
        returns the completed list."""
        ticks = 0
        while True:
            with_work = (self.sched.depth() or self.sched.running()
                         or None)
            if with_work is None:
                break
            self.tick()
            if check_invariants:
                self.sched.check_invariants()
            ticks += 1
            if ticks >= max_ticks:
                raise MpiError(ErrorClass.ERR_INTERN,
                               f"serving did not drain in {max_ticks} "
                               "ticks (a request starved)")
        return self.completed()

    def shutdown(self) -> None:
        """Tell every worker to exit its serve loop."""
        for w in list(self.workers):
            try:
                self.comm.send_obj(("stop",), w, TAG_CMD)
            except MpiError:
                pass                   # a dead worker needs no stop

    # -- the tick ----------------------------------------------------------
    def _tick_inner(self) -> None:
        if self._failed_workers():
            raise ProcFailedError("worker failure detected", ())
        admitted, _evicted = self.sched.tick()
        prefill_ranks, decode_ranks, extra_ranks = self._stage_split()

        # worker assignment for fresh admissions (decode pairs + any
        # colocated leftover share the load; prefix-cache hits override
        # least-loaded with affinity)
        for req in admitted:
            self._assign(req, decode_ranks, extra_ranks, prefill_ranks)

        running = self.sched.running()
        if not running:
            self._maybe_autoscale()
            return

        # stage round: stream this tick's new KV blocks pairing-wise;
        # a fresh request on an extra (colocated) rank prefills with
        # its work command instead
        fresh = [r for r in running if not r.prefilled]
        paired = [r for r in fresh if r.worker in decode_ranks] \
            if self.stages else []
        if paired:
            per_pair: dict = {}   # (prefill rank, decode rank) -> reqs
            for r in paired:
                pre = self._prefill_of(r.worker, prefill_ranks,
                                       decode_ranks)
                per_pair.setdefault((pre, r.worker), []).append(r)
            for (pre, dec), reqs in sorted(per_pair.items()):
                # epochs are PER PAIRING: each slab pairing counts its
                # own consecutive rounds (a global counter would desync
                # a pairing that sat out a round)
                epoch = self._pair_epoch.get((pre, dec), -1) + 1
                self._pair_epoch[(pre, dec)] = epoch
                if trace.requests_enabled:
                    # otpu-req hop 0 opens at the dispatch decision
                    # (router -> prefill shard; the prefill rank closes
                    # it at command receipt).  The stamp is written
                    # exactly once and _finish's stage decomposition
                    # reuses it — never a second now() for this instant
                    for r in reqs:
                        r.dispatch_ns = trace.now()
                        trace.flow_start("serve_req", (r.rid, 0),
                                         r.dispatch_ns)
                self.comm.send_obj(
                    ("prefill", dec, epoch,
                     [(r.rid, r.slot, r.prompt_len,
                       self._fresh_hashes(r), r.hint) for r in reqs]),
                    pre, TAG_CMD)
                self.comm.send_obj(
                    ("kv", epoch,
                     [(r.rid, r.slot) for r in reqs]),
                    dec, TAG_CMD)
            # prefill acks, then decode-side kv acks — order-free drain
            for (pre, dec) in sorted(per_pair):
                msg = self._expect(pre, "prefilled")
                self._fold_preport(pre, msg[3])
                self._expect(dec, "kv_ready")
                if trace.requests_enabled:
                    # kv_ready means the decode side holds the slab:
                    # the decode window of every request in this
                    # pairing opens here
                    for r in per_pair[(pre, dec)]:
                        r.decode_ns = trace.now()
        # a fresh COLOCATED request prefills with its first work cmd —
        # that cmd carries the prefix hashes + routing hint (paired
        # requests already streamed theirs above)
        fresh_colocated = {r.rid for r in fresh
                           if not (self.stages
                                   and r.worker in decode_ranks)}
        for r in fresh:
            r.prefilled = True         # paired: streamed above;
        #                                colocated: rides the work cmd

        # decode micro-batches: one coalesced cmd per active worker
        per_worker: dict = {}
        for r in running:
            n = min(self.decode_chunk, r.remaining)
            if n > 0:
                first = r.rid in fresh_colocated
                if first and trace.requests_enabled:
                    # colocated hop 0: the work cmd carries the
                    # prefill, and decode starts in the same dispatch —
                    # both stage stamps coincide by construction
                    r.dispatch_ns = r.decode_ns = trace.now()
                    trace.flow_start("serve_req", (r.rid, 0),
                                     r.dispatch_ns)
                entry = (r.rid, r.prompt_len, len(r.tokens), n,
                         self._fresh_hashes(r) if first else (),
                         r.hint if first else None)
                per_worker.setdefault(r.worker, []).append(entry)
            elif r.state is not RequestState.DONE:
                # fully decoded but never marked (e.g. a recovery replay
                # raced completion): retire it instead of starving
                self._finish(r)
        free_rids = list(self._recent_done)
        for w, batch in sorted(per_worker.items()):
            self.comm.send_obj(("work", batch, free_rids), w, TAG_CMD)
        by_rid = {r.rid: r for r in running}
        for w in sorted(per_worker):
            msg = self._expect(w, "res")
            results = msg[1]
            self._fold_preport(w, msg[2])
            for rid, toks in results:
                req = by_rid.get(rid)
                if req is None:
                    continue           # finished during recovery replay
                base = len(req.tokens)
                for i, tok in enumerate(toks):
                    if tok != toy_token(rid, base + i):
                        raise MpiError(
                            ErrorClass.ERR_INTERN,
                            f"rid {rid} token {base + i} corrupted")
                req.tokens.extend(toks)
                if trace.requests_enabled:
                    req.last_res_ns = trace.now()
                if req.remaining <= 0:
                    self._finish(req)
        self._maybe_autoscale()

    def _fresh_hashes(self, req) -> tuple:
        """The prompt's block-hash chain for a first dispatch (the
        worker installs these in its prefix store), () when prefix
        routing is off or the request carries no tokens."""
        if self.registry is None or not req.prompt:
            return ()
        if req.hashes is None:
            req.hashes = prefix_cache.block_hashes(req.prompt)
        return req.hashes

    def _fold_preport(self, worker: int, report) -> None:
        """Fold a worker's prefix report into the routing registry:
        freshly installed blocks become routable at the worker's
        CURRENT generation, evicted blocks are forgotten (idempotent —
        the report rides every reply like the KV eviction notices)."""
        if report is None:
            return
        self.prefill_count += int(report.get("prefills", 0))
        self.prefix_hit_count += int(report.get("hits", 0))
        if self.registry is None:
            return
        gen = int(report.get("gen", 0))
        installed = report.get("installed") or ()
        if installed:
            self.registry.insert(installed, worker, gen)
        evicted = report.get("evicted") or ()
        if evicted:
            self.registry.forget(evicted, worker)

    def _expect(self, worker: int, kind: str):
        """Receive one reply from ``worker`` and check its kind;
        returns the whole message."""
        msg = self.comm.recv_obj(worker, TAG_RES)
        if msg[0] != kind:
            raise MpiError(ErrorClass.ERR_INTERN,
                           f"expected {kind!r} from worker {worker}, "
                           f"got {msg[0]!r}")
        return msg

    def _finish(self, req: ServeRequest) -> None:
        if req.state is RequestState.DONE:
            return                     # a replay must not double-count
        self.sched.mark_done(req)
        self._completed.append(req)
        self._recent_done.append(req.rid)   # KV eviction notice
        # single-stamp discipline: mark_done stamped done_ns — a second
        # now() here would hand the SLO plane a different e2e than the
        # one the stage spans decompose (the otpu-req audit's
        # double-read family)
        dur = (req.done_ns or trace.now()) - req.arrival_ns
        telemetry.slo_observe(self.pool or "", req.tenant, dur / 1e6)
        if frontdoor_mod.enabled:
            # the admission plane watches the SAME signal the SLO
            # accountant and autoscaler read — one escalation ladder
            frontdoor_mod.observe(self.pool or "", req.slo, dur / 1e6)
        if trace.enabled:
            # request latency (arrival -> last token) into the log2
            # histogram the percentile estimator reads; "size" is the
            # token footprint so the bins separate small/large requests.
            # Tenant and pool get their OWN histogram families — their
            # percentile populations never merge (the driver resets
            # each family per run), which is what per-tenant p99
            # reporting and the per-pool autoscaling signal read.
            trace.hist_record(_HIST, req.cost, dur)
            if req.tenant:
                trace.hist_record(TENANT_HIST_PREFIX + req.tenant,
                                  req.cost, dur)
            if self.pool:
                trace.hist_record(POOL_HIST_PREFIX + self.pool,
                                  req.cost, dur)
        if trace.requests_enabled:
            self._trace_request(req)

    def _trace_request(self, req: ServeRequest) -> None:
        """Emit the router-side otpu-req stage spans and close the
        request's flow chain.  Four spans, all from lifecycle stamps
        written exactly once on the hot path (queue: arrival -> admit;
        dispatch: admit -> first cmd out; decode: decode window open ->
        last token result; stream: last result -> done); the worker
        ranks contribute req_prefill / req_kv, and ``otpu_analyze
        --requests`` folds all six into the per-request decomposition.
        A requeued-and-replayed request may lack pre-failure stamps —
        emit what is known, never invent an interval."""
        args = {"rid": req.rid, "tenant": req.tenant,
                "pool": self.pool or "", "worker": req.worker}
        n = 0
        if req.admit_ns is not None:
            trace.span("req_queue", "serve_req", req.arrival_ns,
                       req.admit_ns, args=args)
            n += 1
            if req.dispatch_ns is not None:
                trace.span("req_dispatch", "serve_req", req.admit_ns,
                           req.dispatch_ns, args=args)
                n += 1
        if req.decode_ns is not None and req.last_res_ns is not None:
            trace.span("req_decode", "serve_req", req.decode_ns,
                       req.last_res_ns, args=args)
            trace.span("req_stream", "serve_req", req.last_res_ns,
                       req.done_ns or req.last_res_ns, args=args)
            n += 2
        trace.flow_finish("serve_req", (req.rid, 2), req.done_ns)
        spc.record("req_traced")
        if n:
            spc.record("req_stages", n)

    # -- failure handling --------------------------------------------------
    def _failed_workers(self) -> list:
        from ompi_tpu.ft import state as ft_state

        out = []
        for w in self.workers:
            if ft_state.is_failed(self.comm.group.world_rank(w)):
                out.append(w)
        return out

    def _recover(self) -> None:
        """Serve-through-failure, router side: revoke (unblocks every
        survivor into its own recovery), shrink, re-shard, requeue."""
        try:
            self.comm.revoke()
        except MpiError:
            pass                       # already revoked is fine
        new = self.comm.shrink()
        from ompi_tpu import serving as _pkg

        workers = _pkg.roles(new)[1]
        self.rebind(new, workers)

    def rebind(self, new_comm, workers, prefill_ranks=None,
               decode_ranks=None) -> None:
        """Re-home this router onto a replacement communicator (the
        tail of both recovery paths: standalone after its own shrink,
        or fleet-driven after the controller shrank the SHARED comm
        once and recomputed every pool's table).  Re-shards the worker
        table, invalidates the prefix registry (comm ranks just
        re-numbered — every routed worker id is suspect), and requeues
        EVERY in-flight request: results in transit on the revoked comm
        are gone, and decode is deterministic so a replay from
        tokens_done is bit-identical."""
        new_comm.set_errhandler(ERRORS_RETURN)
        self.comm = new_comm
        from ompi_tpu import serving as _pkg

        self.me = _pkg.roles(new_comm)[0]
        self.workers = [int(w) for w in workers if int(w) != self.me]
        if prefill_ranks or decode_ranks:
            self._prefill = [int(w) for w in prefill_ranks or ()]
            self._decode = [int(w) for w in decode_ranks or ()]
            self.stages = bool(self._prefill and self._decode)
        else:
            self.stages = False        # pairs may have lost a side
            self._prefill = self._decode = None
        self._pair_epoch.clear()
        if self.registry is not None:
            self.registry.invalidate_all()
        running = self.sched.running()
        self._lost_and_requeued += len(running)
        self.sched.requeue(running)

    # -- autoscaling -------------------------------------------------------
    def _maybe_autoscale(self) -> None:
        if self.scale_watermark is None or self.scale_argv is None:
            return
        if getattr(self.comm.rte, "client", None) is None:
            return
        if self._scale_cooling > 0:    # let the last scale-up absorb
            self._scale_cooling -= 1
            return
        if len(self.workers) >= self.scale_max_workers:
            return
        if self.sched.depth() <= self.scale_watermark:
            self._over_watermark = 0
            return
        self._over_watermark += 1
        if self._over_watermark < self.scale_patience:
            return
        self._over_watermark = 0
        self._scale_cooling = self.scale_cooldown
        self._scale_up(self.scale_step)

    def _scale_up(self, n: int) -> None:
        """Spawn ``n`` fresh worker processes and fold them into the
        serving communicator (collective with the current workers)."""
        for w in self.workers:
            self.comm.send_obj(("scale", self.scale_argv, n), w, TAG_CMD)
        inter = self.comm.spawn(self.scale_argv, n, root=self.me)
        client = getattr(self.comm.rte, "client", None)
        job = getattr(inter, "spawn_job", None)
        if client is not None and job is not None:
            # the dynamic pset IS the membership contract: the children
            # we merge with must be exactly the job's published set
            entry = client.pset_get(f"mpi://job/{job}")
            members = sorted(int(m) for m in entry["members"])
            if members != sorted(inter.remote_group.world_ranks):
                raise MpiError(
                    ErrorClass.ERR_SPAWN,
                    f"mpi://job/{job} pset {members} does not match the "
                    "spawned intercomm")
        full = inter.merge(high=False)  # parents first: router keeps rank
        full.set_errhandler(ERRORS_RETURN)
        self.comm = full
        new_ranks = list(range(full.size - n, full.size))
        self.workers = sorted(set(self.workers) | set(new_ranks))
        spc.record("serve_scaleups")
