"""serving/prefix_cache — prefix-cache-aware routing state, both sides.

Serving traffic is dominated by shared prompt prefixes (system prompts,
few-shot templates, multi-turn histories): the KV blocks of a prefix
already computed by one worker can serve every later request carrying
the same prefix, *if the router sends the request to that worker*.
This module is the pure state machine behind that affinity — no MPI,
no threads of its own, unit-testable in isolation:

* :func:`block_hashes` — hash a prompt's tokens at **KV-block
  granularity** into a chain of cumulative digests (one per full
  block), process-stable (``hashlib.blake2b`` over packed token bytes,
  never Python's salted ``hash()``), so the router, every worker, and
  a restarted replacement all agree on what a prefix is called;
* :class:`PrefixRegistry` — the ROUTER side: an LRU map
  ``prefix-hash → (worker, slab generation)``.  ``lookup`` returns the
  deepest known block of a prompt; the router routes the request to
  that worker and attaches the ``(hash, generation)`` hint.
* :class:`PrefixStore` — the WORKER side: a bounded LRU of the block
  hashes whose KV this worker still holds, stamped with a
  **generation** that bumps every time the store is cleared (failure
  recovery, re-shard, retirement).  ``has(hash, gen)`` is the hint
  check: a hit skips prefill, a mismatch — entry evicted since the
  router learned of it, or a different store generation entirely —
  falls back to a FULL prefill.

The generation check is the correctness story: a stale registry entry
(worker died and respawned, slab re-sharded, LRU evicted the block) is
always a **performance miss, never a correctness bug** — the worker
verifies before skipping anything, and the router's registry is only a
routing heuristic.  Invalidation keeps the registry fresh along the
same channels the KV eviction notices already ride: workers report
evicted hashes with every reply (idempotent ``forget``), and the
shrink / re-shard / retire paths call ``invalidate_worker`` /
``invalidate_all``.
"""
from __future__ import annotations

import struct
import threading
from collections import OrderedDict
from hashlib import blake2b
from typing import Optional

from ompi_tpu.base.var import VarType, registry

_block_var = registry.register(
    "serving", None, "prefix_block", vtype=VarType.INT, default=16,
    help="Prefix-cache block size in prompt tokens: prompts are hashed "
         "at this granularity (one cumulative digest per full block), "
         "matching the KV-block unit the cache can actually reuse.  "
         "Router and workers must agree — it is read once per process "
         "from this var")
_store_cap_var = registry.register(
    "serving", None, "prefix_capacity", vtype=VarType.INT, default=128,
    help="Worker-side prefix store capacity (block entries).  The "
         "oldest entry is evicted LRU; evictions ride the next reply "
         "to the router so its registry forgets the entry too")
_registry_cap_var = registry.register(
    "serving", None, "registry_capacity", vtype=VarType.INT,
    default=1024,
    help="Router-side prefix registry capacity (block entries across "
         "all workers of one pool), evicted LRU")


def block_size() -> int:
    """The configured prefix block size (tokens per hashed block)."""
    return max(1, int(_block_var.value or 16))


def block_hashes(tokens, block: Optional[int] = None) -> tuple:
    """Cumulative block digests of a prompt: entry ``i`` names the
    prefix ``tokens[:(i + 1) * block]`` (full blocks only — a partial
    tail block is never cacheable).  Digests chain (``h_i = H(h_{i-1}
    || block_i)``) so two prompts share entry ``i`` iff they share the
    whole prefix up to it, and they are **process-stable**: blake2b
    over packed token bytes, usable across router, workers, and
    respawned replacements."""
    b = int(block) if block else block_size()
    toks = tuple(int(t) for t in tokens)
    out = []
    prev = b"\x00"
    for i in range(len(toks) // b):
        blk = toks[i * b:(i + 1) * b]
        h = blake2b(prev, digest_size=8)
        h.update(struct.pack(f"!{b}q", *blk))
        digest = h.hexdigest()
        out.append(digest)
        prev = digest.encode("ascii")
    return tuple(out)


class PrefixHit:
    """One registry lookup result: the deepest known block of a
    prompt.  ``blocks`` counts the matched full blocks (the prefill
    the hit can skip covers ``blocks * block_size()`` tokens)."""

    __slots__ = ("hash", "worker", "generation", "blocks")

    def __init__(self, h: str, worker: int, generation: int,
                 blocks: int) -> None:
        self.hash = h
        self.worker = int(worker)
        self.generation = int(generation)
        self.blocks = int(blocks)

    def __repr__(self) -> str:
        return (f"PrefixHit({self.hash}, worker={self.worker}, "
                f"gen={self.generation}, blocks={self.blocks})")


class PrefixRegistry:
    """Router-side prefix → (worker, generation) map (see module doc).

    Mutated by the router tick thread, snapshotted by the telemetry
    sampler thread through :meth:`stats` — every structure is under
    the registry lock."""

    _guarded_by = {"_entries": "_lock", "_hits": "_lock",
                   "_misses": "_lock", "_invalidated": "_lock"}

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = max(1, int(capacity) if capacity is not None
                            else int(_registry_cap_var.value or 1024))
        self._lock = threading.Lock()
        #: hash -> (worker, generation), LRU order (oldest first)
        self._entries: OrderedDict = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._invalidated = 0

    def lookup(self, hashes) -> Optional[PrefixHit]:
        """Deepest registered block of the prompt whose cumulative
        digests are ``hashes`` (longest-prefix match), or None.  Counts
        a hit or a miss — the hit/miss ratio IS the routing-quality
        signal the telemetry plane publishes."""
        with self._lock:
            for i in range(len(hashes) - 1, -1, -1):
                ent = self._entries.get(hashes[i])
                if ent is not None:
                    self._entries.move_to_end(hashes[i])
                    self._hits += 1
                    return PrefixHit(hashes[i], ent[0], ent[1], i + 1)
            if hashes:
                self._misses += 1
        return None

    def insert(self, hashes, worker: int, generation: int) -> None:
        """Register every cumulative block of a freshly prefilled
        prompt as held by ``worker`` at ``generation`` (called from the
        router when a worker reports the blocks it installed)."""
        with self._lock:
            for h in hashes:
                self._entries[h] = (int(worker), int(generation))
                self._entries.move_to_end(h)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def forget(self, hashes, worker: Optional[int] = None) -> None:
        """Drop entries (worker-reported evictions).  Idempotent — the
        eviction notices ride every reply like the KV free_rids deque,
        so repeats are harmless; with ``worker`` given only entries
        still owned by that worker are dropped (a fresh entry from a
        different worker under the same hash must survive a late
        notice)."""
        with self._lock:
            for h in hashes:
                ent = self._entries.get(h)
                if ent is None:
                    continue
                if worker is not None and ent[0] != int(worker):
                    continue
                del self._entries[h]

    def invalidate_worker(self, worker: int) -> int:
        """Drop every entry routed at ``worker`` — the re-shard /
        retire path (the worker's slabs are gone or about to be)."""
        with self._lock:
            dead = [h for h, ent in self._entries.items()
                    if ent[0] == int(worker)]
            for h in dead:
                del self._entries[h]
            self._invalidated += len(dead)
            return len(dead)

    def invalidate_all(self) -> None:
        """Drop everything — the shrink path: comm ranks just
        re-numbered, so every routed worker id is suspect.  Stale
        entries would only be perf misses, but a wholesale re-rank
        makes them all dead weight."""
        with self._lock:
            self._invalidated += len(self._entries)
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        """Hit/miss/occupancy snapshot (telemetry ``fleet`` source)."""
        with self._lock:
            total = self._hits + self._misses
            return {"entries": len(self._entries),
                    "hits": self._hits, "misses": self._misses,
                    "invalidated": self._invalidated,
                    "hit_rate": round(self._hits / total, 4)
                    if total else 0.0}


class PrefixStore:
    """Worker-side record of which prefix blocks this worker still
    holds, with the generation stamp the hint check verifies (see
    module doc).  Single-threaded (the worker's serve loop), so no
    lock — but bounded and loud about what it evicts, because every
    eviction must reach the router's registry."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = max(1, int(capacity) if capacity is not None
                            else int(_store_cap_var.value or 128))
        self.generation = 0
        self._codec = ""
        self._lru: OrderedDict = OrderedDict()

    def has(self, h: str, generation: int) -> bool:
        """THE hint check: is this exact block still held, and was the
        router's registry entry minted against this store lifetime?
        Any mismatch means full prefill — stale hints are perf misses,
        never wrong KV."""
        if int(generation) != self.generation:
            return False
        if h not in self._lru:
            return False
        self._lru.move_to_end(h)
        return True

    def add_all(self, hashes) -> list:
        """Install freshly prefilled blocks; returns the hashes LRU
        eviction pushed out (the caller reports them to the router so
        the registry forgets them too)."""
        evicted = []
        for h in hashes:
            self._lru[h] = True
            self._lru.move_to_end(h)
        while len(self._lru) > self.capacity:
            old, _ = self._lru.popitem(last=False)
            evicted.append(old)
        return evicted

    def clear(self) -> None:
        """Drop everything and bump the generation — recovery /
        re-shard / retirement: hints minted against the old lifetime
        must never match again."""
        self.generation += 1
        self._lru.clear()

    def set_codec(self, codec: str) -> None:
        """Record the KV slab codec this store's blocks are held under.
        A codec CHANGE invalidates every held block the way a recovery
        does — the bytes a hint promised no longer exist in that
        encoding — so the generation bumps and hints minted against
        the old codec can never verify again: the stale-hint guarantee
        ("perf miss, never wrong KV") survives the reconfiguration.
        An idempotent re-set, or the first set over an empty store, is
        free (no hint was ever minted against another encoding)."""
        codec = str(codec or "")
        if codec == self._codec:
            return
        had_blocks = bool(self._lru)
        self._codec = codec
        if had_blocks:
            self.clear()

    def __len__(self) -> int:
        return len(self._lru)
