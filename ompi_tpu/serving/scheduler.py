"""serving/scheduler — the continuous-batching admission scheduler.

One object owns the request lifecycle: QUEUED (submitted, waiting) →
RUNNING (admitted into the in-flight batch, holding a KV slot) → DONE.
Every engine tick the router calls :meth:`ContinuousBatchScheduler.tick`,
which first *evicts* sequences that finished since the last tick (their
KV slots return to the free list immediately — the batch is never
drained) and then *admits* queued requests strictly in arrival order
while three budgets hold: batch width (``max_batch``), reserved token
budget (``max_batch_tokens``, counting ``prompt_len + max_new_tokens``
per admitted request), and free KV slots.

Strict-FIFO admission is the no-starvation guarantee the tests pin: a
request is admitted only when it is the OLDEST queued request, so a
stream of short requests can never overtake a long one indefinitely.

Thread discipline: ``submit`` may be called from a driver thread while
the router thread ticks, so every queue/batch structure is declared
``_guarded_by`` the scheduler lock (otpu-lint's lock-discipline pass
enforces the annotation); :meth:`tick` is tagged ``@hot_path`` — it
runs once per engine tick and stays inside the allocation budget the
hot-path pass checks (no pickle, no string formatting, no list concat).
"""
from __future__ import annotations

import enum
import itertools
import threading
from typing import Optional

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.runtime import spc, trace
from ompi_tpu.runtime.hotpath import hot_path

_rid_counter = itertools.count()


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"


class ServeRequest:
    """One inference request travelling through the serving engine."""

    __slots__ = ("rid", "prompt_len", "max_new_tokens", "arrival_ns",
                 "state", "tokens", "slot", "worker", "prefilled",
                 "admit_ns", "done_ns")

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 rid: Optional[int] = None) -> None:
        if prompt_len <= 0 or max_new_tokens <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"request needs positive prompt/decode "
                           f"lengths, got ({prompt_len}, {max_new_tokens})")
        self.rid = next(_rid_counter) if rid is None else int(rid)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.arrival_ns = trace.now()
        self.state = RequestState.QUEUED
        self.tokens: list = []           # decoded tokens, router-collected
        self.slot: Optional[int] = None  # KV slot while RUNNING
        self.worker: Optional[int] = None
        self.prefilled = False
        self.admit_ns: Optional[int] = None
        self.done_ns: Optional[int] = None

    @property
    def cost(self) -> int:
        """Reserved token budget: prompt + the full decode allowance
        (the batch must never exceed budget even if every admitted
        sequence runs to its cap)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def __repr__(self) -> str:
        return (f"ServeRequest(rid={self.rid}, {self.state.value}, "
                f"prompt={self.prompt_len}, "
                f"decoded={len(self.tokens)}/{self.max_new_tokens})")


class ContinuousBatchScheduler:
    """Admission control for the continuous batch (see module doc)."""

    _guarded_by = {
        "_sq": "_slock", "_running": "_slock", "_done": "_slock",
        "_free_slots": "_slock",
    }

    def __init__(self, max_batch: int = 8,
                 max_batch_tokens: int = 1 << 14,
                 slots: Optional[int] = None) -> None:
        if max_batch <= 0 or max_batch_tokens <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           "scheduler budgets must be positive")
        self.max_batch = int(max_batch)
        self.max_batch_tokens = int(max_batch_tokens)
        self.slots = int(slots) if slots is not None else self.max_batch
        if self.slots < self.max_batch:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"{self.slots} KV slots cannot back a batch "
                           f"of {self.max_batch}")
        self._slock = threading.Lock()
        self._sq: list = []             # FIFO admission queue
        self._running: list = []
        self._done: list = []
        self._free_slots = list(range(self.slots - 1, -1, -1))
        self._used_tokens = 0
        # scheduler depth for otpu_top (latest-constructed scheduler
        # wins the slot; the provider runs on the sampler thread only)
        from ompi_tpu.runtime import telemetry

        telemetry.register_source("serving", self.stats)

    def stats(self) -> dict:
        """Queue/batch depth snapshot (the telemetry ``serving`` source
        and the autoscaler's richer sibling of :meth:`depth`)."""
        with self._slock:
            return {"queued": len(self._sq),
                    "running": len(self._running),
                    "done": len(self._done),
                    "used_tokens": self._used_tokens,
                    "free_slots": len(self._free_slots)}

    # -- submission (any thread) -----------------------------------------
    def submit(self, req: ServeRequest) -> ServeRequest:
        if req.cost > self.max_batch_tokens:
            raise MpiError(
                ErrorClass.ERR_ARG,
                f"request {req.rid} reserves {req.cost} tokens, above "
                f"the whole-batch budget {self.max_batch_tokens} — it "
                "could never be admitted")
        spc.record("serve_requests")
        with self._slock:
            self._sq.append(req)
        return req

    def depth(self) -> int:
        """Queued (not yet admitted) request count — the autoscaling
        watermark signal."""
        with self._slock:
            return len(self._sq)

    def running(self) -> list:
        with self._slock:
            return list(self._running)

    def done_count(self) -> int:
        with self._slock:
            return len(self._done)

    def used_tokens(self) -> int:
        with self._slock:
            return self._used_tokens

    # -- engine tick (router thread) -------------------------------------
    @hot_path
    def tick(self) -> tuple:
        """One admission round: (admitted, evicted) lists.

        Eviction first — a sequence that finished last tick frees its
        slot and token reservation for this tick's admissions, which is
        what keeps the batch continuously full instead of draining.
        """
        spc.record("serve_ticks")
        admitted: list = []
        evicted: list = []
        with self._slock:
            keep: list = []
            for r in self._running:
                if r.state is RequestState.DONE:
                    evicted.append(r)
                    self._done.append(r)
                    self._used_tokens -= r.cost
                    if r.slot is not None:
                        self._free_slots.append(r.slot)
                        r.slot = None
                else:
                    keep.append(r)
            self._running = keep
            while self._sq:
                head = self._sq[0]
                if len(self._running) >= self.max_batch:
                    break
                if self._used_tokens + head.cost > self.max_batch_tokens:
                    break
                if not self._free_slots:
                    break
                self._sq.pop(0)
                head.slot = self._free_slots.pop()
                head.state = RequestState.RUNNING
                head.admit_ns = trace.now()
                self._used_tokens += head.cost
                self._running.append(head)
                admitted.append(head)
        if admitted:
            spc.record("serve_admitted", len(admitted))
        if evicted:
            spc.record("serve_evicted", len(evicted))
        return admitted, evicted

    def mark_done(self, req: ServeRequest) -> None:
        """Sequence finished decoding: it leaves the batch at the NEXT
        tick's eviction sweep (state flip only — callable from the
        result-drain path without the lock because state is a single
        attribute store and eviction happens on the tick thread)."""
        req.done_ns = trace.now()
        req.state = RequestState.DONE

    # -- failure recovery -------------------------------------------------
    def requeue(self, reqs) -> None:
        """Serve-through-failure: push RUNNING requests back to the
        head of the queue (arrival order preserved) after their worker
        died.  Decoded tokens survive — decode is deterministic, so a
        replacement worker continues from ``len(tokens)``."""
        back = sorted(reqs, key=lambda r: r.arrival_ns)
        with self._slock:
            for r in reversed(back):
                if r not in self._running:
                    continue
                if r.state is RequestState.DONE:
                    # finished before the failure — nothing was lost;
                    # the next tick's eviction sweep retires it (a
                    # requeue here would re-admit a request with no
                    # decode work left, which can never complete again)
                    continue
                self._running.remove(r)
                self._used_tokens -= r.cost
                if r.slot is not None:
                    self._free_slots.append(r.slot)
                    r.slot = None
                r.state = RequestState.QUEUED
                r.worker = None
                r.prefilled = False
                self._sq.insert(0, r)
        spc.record("serve_requeued", len(back))

    # -- invariants (tests) ------------------------------------------------
    def check_invariants(self) -> None:
        """Raise AssertionError when a batch/budget/slot invariant is
        violated — the serving tests call this every tick."""
        with self._slock:
            assert len(self._running) <= self.max_batch, \
                "batch width exceeded"
            used = sum(r.cost for r in self._running)
            assert used == self._used_tokens, "token accounting drifted"
            assert used <= self.max_batch_tokens, "token budget exceeded"
            slots = [r.slot for r in self._running]
            assert None not in slots, "RUNNING request without a slot"
            assert len(set(slots)) == len(slots), "slot double-assigned"
            assert set(slots).isdisjoint(self._free_slots), \
                "slot both free and assigned"
            assert len(slots) + len(self._free_slots) == self.slots, \
                "slots leaked"
