"""serving/scheduler — the continuous-batching admission scheduler.

One object owns the request lifecycle: QUEUED (submitted, waiting) →
RUNNING (admitted into the in-flight batch, holding a KV slot) → DONE.
Every engine tick the router calls :meth:`ContinuousBatchScheduler.tick`,
which first *evicts* sequences that finished since the last tick (their
KV slots return to the free list immediately — the batch is never
drained) and then *admits* queued requests strictly in arrival order
while three budgets hold: batch width (``max_batch``), reserved token
budget (``max_batch_tokens``, counting ``prompt_len + max_new_tokens``
per admitted request), and free KV slots.

Strict-FIFO admission is the no-starvation guarantee the tests pin: a
request is admitted only when it is the OLDEST queued request, so a
stream of short requests can never overtake a long one indefinitely.

**Multi-tenant fair share** (the fleet layer): with ``tenants={name:
weight}`` configured, each tenant owns its own strict-FIFO queue and
admission runs **weighted round-robin across tenants** — each pass of
the cycle lets tenant ``t`` admit up to ``weight[t]`` requests, so one
tenant's burst can delay another by at most one cycle of the others'
quanta, never starve it.  Both guarantees are *checkable*:
:meth:`check_invariants` asserts per-tenant arrival order AND the
cross-tenant bound (a continuously-backlogged tenant is never passed
over for more than two full cycles of the other tenants' quanta — two,
not one, because the tenant table may grow mid-run) against a bounded
admission log.  A request whose head does not fit the width/token/slot
budgets stops the WHOLE admission round — budget head-of-line blocking
is shared, exactly like the single-queue case, so "passed over" can
only mean "the WRR cycle was mid-rotation", which is what the bound
covers.  Untagged requests ride the default tenant ``""`` and a
scheduler constructed without ``tenants`` degenerates to the original
single-queue FIFO bit-for-bit.

Thread discipline: ``submit`` may be called from a driver thread while
the router thread ticks, so every queue/batch structure is declared
``_guarded_by`` the scheduler lock (otpu-lint's lock-discipline pass
enforces the annotation); :meth:`tick` is tagged ``@hot_path`` — it
runs once per engine tick and stays inside the allocation budget the
hot-path pass checks (no pickle, no string formatting, no list concat).
"""
from __future__ import annotations

import collections
import enum
import itertools
import threading
from typing import Optional

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.runtime import spc, trace
from ompi_tpu.runtime.hotpath import hot_path

_rid_counter = itertools.count()


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"


class ServeRequest:
    """One inference request travelling through the serving engine.

    ``tenant``/``model`` place the request in the fleet (fair-share
    queue and target pool); ``prompt`` optionally carries the actual
    prompt tokens — with it the router can hash prefix blocks and
    route the request to the worker already holding them (``hashes``
    is the lazily computed digest chain, ``hint`` the
    ``(hash, generation)`` the dispatched worker verifies, and
    ``prefill_skipped`` records whether the hit actually saved the
    prefill).  Without ``prompt`` everything behaves exactly as
    before — prefix awareness is strictly additive."""

    __slots__ = ("rid", "prompt_len", "max_new_tokens", "arrival_ns",
                 "state", "tokens", "slot", "worker", "prefilled",
                 "admit_ns", "done_ns", "tenant", "model", "prompt",
                 "hashes", "hint", "prefill_skipped",
                 "dispatch_ns", "decode_ns", "last_res_ns", "slo")

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 rid: Optional[int] = None, tenant: str = "",
                 model: str = "", prompt=None, slo: str = "") -> None:
        if prompt is not None and not prompt_len:
            prompt_len = len(prompt)
        if prompt_len <= 0 or max_new_tokens <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"request needs positive prompt/decode "
                           f"lengths, got ({prompt_len}, {max_new_tokens})")
        self.rid = next(_rid_counter) if rid is None else int(rid)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.arrival_ns = trace.now()
        self.state = RequestState.QUEUED
        self.tokens: list = []           # decoded tokens, router-collected
        self.slot: Optional[int] = None  # KV slot while RUNNING
        self.worker: Optional[int] = None
        self.prefilled = False
        self.admit_ns: Optional[int] = None
        self.done_ns: Optional[int] = None
        self.tenant = str(tenant)
        self.model = str(model)
        self.prompt = tuple(int(t) for t in prompt) \
            if prompt is not None else None
        # SLO class ("interactive"/"batch", frontdoor-assigned; ""
        # means unclassified — never shed, never preempted)
        self.slo = str(slo)
        self.hashes: Optional[tuple] = None   # router-computed digests
        self.hint: Optional[tuple] = None     # (hash, generation)
        self.prefill_skipped = False
        # otpu-req stage stamps, written ONLY while trace.requests_
        # enabled (the zero-overhead identity keeps the record path
        # byte-identical with requests tracing off).  Each lifecycle
        # point stamps its time exactly once and every later consumer
        # reuses the stamp — double-now() reads made the queue-wait and
        # dispatch stages overlap in the decomposition.
        self.dispatch_ns: Optional[int] = None  # first cmd sent
        self.decode_ns: Optional[int] = None    # decode window opened
        self.last_res_ns: Optional[int] = None  # last token chunk in

    @property
    def cost(self) -> int:
        """Reserved token budget: prompt + the full decode allowance
        (the batch must never exceed budget even if every admitted
        sequence runs to its cap)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def __repr__(self) -> str:
        return (f"ServeRequest(rid={self.rid}, {self.state.value}, "
                f"prompt={self.prompt_len}, "
                f"decoded={len(self.tokens)}/{self.max_new_tokens})")


class ContinuousBatchScheduler:
    """Admission control for the continuous batch (see module doc)."""

    _guarded_by = {
        "_sq": "_slock", "_running": "_slock", "_done": "_slock",
        "_free_slots": "_slock", "_tq": "_slock", "_tenants": "_slock",
        "_tenant_names": "_slock", "_admit_log": "_slock",
        "_rr": "_slock", "_rr_left": "_slock",
    }

    def __init__(self, max_batch: int = 8,
                 max_batch_tokens: int = 1 << 14,
                 slots: Optional[int] = None,
                 tenants: Optional[dict] = None) -> None:
        if max_batch <= 0 or max_batch_tokens <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           "scheduler budgets must be positive")
        self.max_batch = int(max_batch)
        self.max_batch_tokens = int(max_batch_tokens)
        self.slots = int(slots) if slots is not None else self.max_batch
        if self.slots < self.max_batch:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"{self.slots} KV slots cannot back a batch "
                           f"of {self.max_batch}")
        self._slock = threading.Lock()
        # per-tenant strict-FIFO queues; "" is the default tenant and
        # its queue IS the legacy _sq attribute (same list object), so
        # single-tenant callers see the original scheduler unchanged
        self._tenants: dict = {"": 1}
        if tenants:
            for name, weight in tenants.items():
                if int(weight) <= 0:
                    raise MpiError(ErrorClass.ERR_ARG,
                                   f"tenant {name!r} needs a positive "
                                   f"weight, got {weight}")
                self._tenants[str(name)] = int(weight)
        self._tq: dict = {name: [] for name in self._tenants}
        self._sq: list = self._tq[""]   # FIFO admission queue (default)
        self._tenant_names = tuple(self._tenants)
        # bounded admission history backing the cross-tenant
        # no-starvation invariant: (tenant, other-backlogged-tenants)
        self._admit_log: collections.deque = collections.deque(maxlen=256)
        # weighted-round-robin rotation state, persistent ACROSS ticks
        # (resetting per tick would let a heavy tenant monopolize a
        # batch narrower than its quantum forever)
        self._rr = 0
        self._rr_left = self._tenants[self._tenant_names[0]]
        self._running: list = []
        self._done: list = []
        self._free_slots = list(range(self.slots - 1, -1, -1))
        self._used_tokens = 0
        # scheduler depth for otpu_top (latest-constructed scheduler
        # wins the slot; the provider runs on the sampler thread only)
        from ompi_tpu.runtime import telemetry

        telemetry.register_source("serving", self.stats)

    def stats(self) -> dict:
        """Queue/batch depth snapshot (the telemetry ``serving`` source
        and the autoscaler's richer sibling of :meth:`depth`)."""
        with self._slock:
            out = {"queued": sum(len(q) for q in self._tq.values()),
                   "running": len(self._running),
                   "done": len(self._done),
                   "used_tokens": self._used_tokens,
                   "free_slots": len(self._free_slots)}
            if len(self._tq) > 1:
                out["tenants"] = {t: len(q) for t, q in self._tq.items()
                                  if q}
            return out

    # -- submission (any thread) -----------------------------------------
    def submit(self, req: ServeRequest) -> ServeRequest:
        if req.cost > self.max_batch_tokens:
            raise MpiError(
                ErrorClass.ERR_ARG,
                f"request {req.rid} reserves {req.cost} tokens, above "
                f"the whole-batch budget {self.max_batch_tokens} — it "
                "could never be admitted")
        spc.record("serve_requests")
        with self._slock:
            q = self._tq.get(req.tenant)
            if q is None:
                # a tenant first seen at submit time joins with weight 1
                # (explicit weights come from the constructor's table)
                self._tenants[req.tenant] = 1
                q = self._tq[req.tenant] = []
                self._tenant_names = tuple(self._tenants)
            q.append(req)
        return req

    def depth(self) -> int:
        """Queued (not yet admitted) request count across every tenant
        — the autoscaling watermark signal."""
        with self._slock:
            return sum(len(q) for q in self._tq.values())

    def tenant_depths(self) -> dict:
        """{tenant: queued count} — the fleet fair-share view."""
        with self._slock:
            return {t: len(q) for t, q in self._tq.items()}

    def running(self) -> list:
        with self._slock:
            return list(self._running)

    def done_count(self) -> int:
        with self._slock:
            return len(self._done)

    def used_tokens(self) -> int:
        with self._slock:
            return self._used_tokens

    # -- engine tick (router thread) -------------------------------------
    @hot_path
    def tick(self) -> tuple:
        """One admission round: (admitted, evicted) lists.

        Eviction first — a sequence that finished last tick frees its
        slot and token reservation for this tick's admissions, which is
        what keeps the batch continuously full instead of draining.
        """
        spc.record("serve_ticks")
        admitted: list = []
        evicted: list = []
        with self._slock:
            keep: list = []
            for r in self._running:
                if r.state is RequestState.DONE:
                    evicted.append(r)
                    self._done.append(r)
                    self._used_tokens -= r.cost
                    if r.slot is not None:
                        self._free_slots.append(r.slot)
                        r.slot = None
                else:
                    keep.append(r)
            self._running = keep
            self._admit_locked(admitted)
        if admitted:
            spc.record("serve_admitted", len(admitted))
        if evicted:
            spc.record("serve_evicted", len(evicted))
        return admitted, evicted

    def _admit_locked(self, admitted: list) -> None:
        """Weighted-round-robin admission (caller holds the scheduler
        lock).  Each cycle pass lets tenant ``t`` admit up to
        ``weight[t]`` oldest requests; a head that does not fit a
        budget ends the WHOLE round (shared head-of-line semantics —
        budget pressure never reorders anybody).  One tenant
        degenerates to the original strict-FIFO loop."""
        names = self._tenant_names
        multi = len(names) > 1
        if self._rr >= len(names):
            self._rr = 0
        while True:
            if not any(self._tq[n] for n in names):
                return
            t = names[self._rr]
            q = self._tq[t]
            if not q or self._rr_left <= 0:
                # empty queue forfeits the rest of the quantum (DRR);
                # either way the NEXT tenant's quantum starts fresh
                self._rr = (self._rr + 1) % len(names)
                self._rr_left = self._tenants[names[self._rr]]
                continue
            head = q[0]
            if len(self._running) >= self.max_batch:
                return
            if self._used_tokens + head.cost > self.max_batch_tokens:
                return
            if not self._free_slots:
                return
            # a budget return above leaves the rotation state in place:
            # the next tick resumes THIS tenant's turn — fairness holds
            # across tick boundaries, not only inside one tick (a
            # narrow batch refilling one slot per tick must still walk
            # the whole cycle)
            q.pop(0)
            head.slot = self._free_slots.pop()
            head.state = RequestState.RUNNING
            head.admit_ns = trace.now()
            self._used_tokens += head.cost
            self._running.append(head)
            admitted.append(head)
            self._rr_left -= 1
            if multi:
                # the no-starvation evidence: who was admitted, and
                # which OTHER tenants were backlogged at that moment
                # (check_invariants replays this)
                others = tuple(n for n in names
                               if n != t and self._tq[n])
                self._admit_log.append((t, others))

    def mark_done(self, req: ServeRequest) -> None:
        """Sequence finished decoding: it leaves the batch at the NEXT
        tick's eviction sweep (state flip only — callable from the
        result-drain path without the lock because state is a single
        attribute store and eviction happens on the tick thread)."""
        req.done_ns = trace.now()
        req.state = RequestState.DONE

    # -- failure recovery -------------------------------------------------
    def requeue(self, reqs) -> None:
        """Serve-through-failure: push RUNNING requests back to the
        head of the queue (arrival order preserved) after their worker
        died.  Decoded tokens survive — decode is deterministic, so a
        replacement worker continues from ``len(tokens)``."""
        back = sorted(reqs, key=lambda r: r.arrival_ns)
        with self._slock:
            for r in reversed(back):
                if r not in self._running:
                    continue
                if r.state is RequestState.DONE:
                    # finished before the failure — nothing was lost;
                    # the next tick's eviction sweep retires it (a
                    # requeue here would re-admit a request with no
                    # decode work left, which can never complete again)
                    continue
                self._running.remove(r)
                self._used_tokens -= r.cost
                if r.slot is not None:
                    self._free_slots.append(r.slot)
                    r.slot = None
                r.state = RequestState.QUEUED
                r.worker = None
                r.prefilled = False
                r.hint = None
                # the replay is a fresh attempt: stale stage stamps
                # from the dead worker's dispatch would fold a bogus
                # pre-failure window into the decomposition
                r.dispatch_ns = None
                r.decode_ns = None
                r.last_res_ns = None
                q = self._tq.get(r.tenant)
                if q is None:
                    self._tenants[r.tenant] = 1
                    q = self._tq[r.tenant] = []
                    self._tenant_names = tuple(self._tenants)
                q.insert(0, r)
        spc.record("serve_requeued", len(back))

    def withdraw(self, slo: str) -> list:
        """Pull every QUEUED request of one SLO class out of the tenant
        queues, arrival-ordered — the front door's preemption path:
        after requeueing a pool's RUNNING batch work, the door also
        withdraws the QUEUED batch work so nothing batch re-admits
        ahead of the interactive backlog (withdrawn requests go back
        BEHIND the door; they are never dropped).  Pulling the whole
        class keeps every tenant queue arrival-ordered when the door
        later re-forwards in its own FIFO order."""
        with self._slock:
            out = []
            for q in self._tq.values():
                mine = [r for r in q if r.slo == slo]
                if mine:
                    out.extend(mine)
                    q[:] = [r for r in q if r.slo != slo]
            out.sort(key=lambda r: r.arrival_ns)
            return out

    # -- invariants (tests) ------------------------------------------------
    def check_invariants(self) -> None:
        """Raise AssertionError when a batch/budget/slot invariant is
        violated — the serving tests call this every tick."""
        with self._slock:
            assert len(self._running) <= self.max_batch, \
                "batch width exceeded"
            used = sum(r.cost for r in self._running)
            assert used == self._used_tokens, "token accounting drifted"
            assert used <= self.max_batch_tokens, "token budget exceeded"
            slots = [r.slot for r in self._running]
            assert None not in slots, "RUNNING request without a slot"
            assert len(set(slots)) == len(slots), "slot double-assigned"
            assert set(slots).isdisjoint(self._free_slots), \
                "slot both free and assigned"
            assert len(slots) + len(self._free_slots) == self.slots, \
                "slots leaked"
            # per-tenant strict FIFO: every queue stays arrival-ordered
            for t, q in self._tq.items():
                arr = [r.arrival_ns for r in q]
                assert arr == sorted(arr), \
                    f"tenant {t!r} queue broke arrival order"
            # cross-tenant no-starvation (the fleet fair-share
            # guarantee): replay the admission log — a tenant that was
            # backlogged at every admission in a run of OTHER tenants'
            # admissions is passed over at most two WRR cycles of the
            # others' quanta (two, not one: the tenant table may have
            # grown mid-run, rotating the cycle under it).  Budget
            # blocking cannot inflate the run — a non-fitting head
            # stops the whole round, so nothing after it is logged.
            total_w = sum(self._tenants.values())
            for t, w in self._tenants.items():
                bound = 2 * max(1, total_w - w)
                run = 0
                for adm, backlogged in self._admit_log:
                    if adm == t or t not in backlogged:
                        run = 0
                        continue
                    run += 1
                    assert run <= bound, (
                        f"tenant {t!r} passed over {run} consecutive "
                        f"admissions while backlogged (bound {bound}) "
                        "— fair-share admission starved it")
