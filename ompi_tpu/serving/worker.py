"""serving/worker — model-shard worker ranks.

A worker owns one shard of the (toy) model and executes the micro-batch
commands its router sends each engine tick over the eager lane — one
coalesced command message per worker per tick, one coalesced result
message back (per-request messages would pay the per-message software
overhead 2508.13397 measures in exactly this small-transfer regime).

Roles:

* ``colocated`` (default) — prefill AND decode on the same rank; the KV
  block of a sequence stays local from prefill to eviction.
* ``prefill`` — runs prefills only and streams each finished sequence's
  KV block to its paired decode rank through a
  :class:`~ompi_tpu.serving.kv_stream.KvSlabSender` epoch per
  micro-batch.
* ``decode`` — receives KV blocks (``Parrived`` per slot), copies them
  into its local cache, and generates tokens.

The "model" is deliberately tiny but *checkable*: ``toy_kv`` and
``toy_token`` are deterministic functions of the request id, so the
decode stage verifies every streamed KV block bit-exactly and the
router verifies every decoded token — a correctness harness for the
transport, not an ML demo.

Failure story: any communication error that ULFM classifies
(revocation after the router saw a death, or a direct peer-failure
report) drops the worker into :meth:`ShardWorker._recover` — shrink to
the survivors (the coord service has already published
``mpi://surviving``), rebind to the shrunken communicator, fall back to
the colocated role (stage pairs may have lost a side), and keep
serving.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ompi_tpu.api.errors import (ErrorClass, MpiError, ProcFailedError,
                                 RevokedError)
from ompi_tpu.api.errhandler import ERRORS_RETURN
from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import spc, trace

#: user-space tags of the serving protocol (below the 2^20 cap)
TAG_CMD = 601
TAG_RES = 602
TAG_KV = 603

_VOCAB = 50021
_KV_MOD = 997

#: simulated model-forward costs (f32 tanh pass sizes).  Autoregressive
#: decode pays one TARGET pass per emitted token; a speculative verify
#: round pays one target pass for the whole window plus one cheap DRAFT
#: pass per proposed token — the gap IS the speculative win the bench
#: A/B rows measure, so both sides must price their passes.
_TARGET_PASS_ELEMS = 1 << 20
_DRAFT_PASS_ELEMS = 1 << 14

_spec_k_var = registry.register(
    "serving", None, "spec_k", vtype=VarType.INT, default=0,
    help="Speculative-decoding window: the draft model proposes this "
         "many tokens per decode step and the target model verifies "
         "them in one batched pass (accepted prefix + one "
         "correction/bonus token emitted per round).  0 (the default) "
         "decodes one target pass per token — speculative off")


def toy_kv(rid: int, elems: int) -> np.ndarray:
    """Deterministic stand-in KV block for request ``rid`` — both stages
    can recompute it, which turns KV streaming into a checkable
    transport (the decode side verifies arrival bit-exactly)."""
    base = (int(rid) * 1009 + np.arange(elems, dtype=np.int64)) % _KV_MOD
    return (base.astype(np.float32) / _KV_MOD)


def toy_token(rid: int, t: int) -> int:
    """Deterministic token ``t`` of request ``rid`` — decode survives a
    worker death because a replacement regenerates the identical
    continuation from ``tokens_done``."""
    return (int(rid) * 1_000_003 + int(t) * 7919) % _VOCAB


def toy_draft_token(rid: int, t: int) -> int:
    """The draft model's proposal for token ``t``: agrees with the
    target on 7 of every 8 positions and is off-by-one on the rest
    (``(rid + t) % 8 == 5``) — a deterministic acceptance pattern, so
    the speculative accept/reject counters are exactly reproducible
    and the tests pin them instead of sampling them."""
    tok = toy_token(rid, t)
    if (int(rid) + int(t)) % 8 == 5:
        return (tok + 1) % _VOCAB
    return tok


class ShardWorker:
    """One worker rank's engine loop (see module doc)."""

    def __init__(self, comm, router: Optional[int] = None,
                 role: str = "colocated", peer=None,
                 slots: int = 8, kv_elems: int = 256,
                 kv_partitions: Optional[int] = None,
                 kv_codec: Optional[str] = None,
                 spec_k: Optional[int] = None) -> None:
        from ompi_tpu import serving as _pkg
        from ompi_tpu.mca.coll import quant as quant_mod
        from ompi_tpu.serving.kv_stream import (KvSlabReceiver,
                                                KvSlabSender)
        from ompi_tpu.serving.prefix_cache import PrefixStore

        comm.set_errhandler(ERRORS_RETURN)   # ULFM: errors raise, not abort
        self.comm = comm
        self.router = _pkg.roles(comm)[0] if router is None else int(router)
        self.role = role
        self.slots, self.kv_elems = int(slots), int(kv_elems)
        # quantized KV slabs (None = the otpu_coll_quant_kv_codec
        # default; "" = raw f32): both sides of every slab pairing in
        # this job resolve the same var, so the pairings agree
        self._kv_codec = quant_mod.kv_codec() if kv_codec is None \
            else str(kv_codec or "")
        # speculative window (None = the otpu_serving_spec_k default;
        # 0 = plain one-pass-per-token decode).  Resolved once: both
        # decode modes of a job agree for its lifetime
        self.spec_k = int(_spec_k_var.value or 0) if spec_k is None \
            else int(spec_k)
        self._kv: dict = {}          # rid -> local KV block (decode state)
        #: rids whose otpu-req flow hops this rank already emitted (a
        #: rid gets many work commands; its hop-0 finish and hop-2
        #: start must fire exactly once).  Trimmed with the KV cache.
        self._req_seen: set = set()
        self._stopped = False
        # prefix store: which block hashes this worker's cache still
        # holds, generation-stamped (the router's routing hints are
        # verified against it — see serving/prefix_cache.py).  The
        # codec stamp makes a codec RECONFIGURATION look like a
        # recovery to every outstanding hint (generation bump).
        self._prefix = PrefixStore()
        self._prefix.set_codec(self._kv_codec)
        self._prefix_hits = 0
        self._preport_installed: list = []
        self._preport_evicted: list = []
        self._preport_prefills = 0
        #: one KV slab sender per DECODE PEER: a prefill pool sized
        #: independently of its decode pool streams to several decode
        #: ranks, each over its own partitioned persistent pairing
        self._senders: dict = {}
        self._receiver = None
        if role == "prefill":
            peers = [int(peer)] if isinstance(peer, int) else \
                [int(p) for p in (peer or ())]
            if not peers:
                raise MpiError(ErrorClass.ERR_ARG,
                               "prefill worker needs >= 1 decode peer")
            for p in peers:
                self._senders[p] = KvSlabSender(comm, p, self.slots,
                                                self.kv_elems, TAG_KV,
                                                codec=self._kv_codec)
        elif role == "decode":
            self._receiver = KvSlabReceiver(comm, int(peer), self.slots,
                                            self.kv_elems, TAG_KV,
                                            partitions=kv_partitions,
                                            codec=self._kv_codec)

    # -- compute ----------------------------------------------------------
    def _prefill(self, rid: int, prompt_len: int) -> np.ndarray:
        # simulated prefill cost scales with the prompt (a tanh pass
        # over prompt_len model rows), result is the checkable KV block.
        # serve_prefills counts exactly these FULL passes — the prefix
        # cache's value shows up as this counter staying below the
        # request count (the acceptance soak asserts the delta)
        spc.record("serve_prefills")
        _ = np.tanh(np.arange(int(prompt_len) * 8,
                              dtype=np.float32)).sum()
        return toy_kv(rid, self.kv_elems)

    def _prefill_or_skip(self, rid: int, prompt_len: int, phashes,
                         hint) -> np.ndarray:
        """Prefill with the prefix cache consulted: a verified hint —
        the hinted block is in THIS store at THIS generation — skips
        the full pass (the cached KV serves the prefix; the toy model
        regenerates the block directly).  Any mismatch, full prefill.
        Either way the prompt's blocks are (re-)installed and the
        caller's pending prefix report picks up what the LRU evicted."""
        hit = bool(hint) and self._prefix.has(hint[0], int(hint[1]))
        if not hit:
            self._preport_prefills += 1
        if hit:
            spc.record("serve_prefix_hits")
            self._prefix_hits += 1
            # only the UNCACHED suffix pays prefill compute: the hinted
            # blocks' KV is already resident (hint[2] counts them)
            from ompi_tpu.serving.prefix_cache import block_size

            cached = int(hint[2]) * block_size() if len(hint) > 2 else 0
            suffix = max(0, int(prompt_len) - cached)
            if suffix:
                _ = np.tanh(np.arange(suffix * 8,
                                      dtype=np.float32)).sum()
            kv = toy_kv(rid, self.kv_elems)
        else:
            if hint:
                # stale hint (evicted entry or a previous store
                # lifetime): a perf miss, NEVER wrong KV
                spc.record("serve_prefix_stale")
            kv = self._prefill(rid, prompt_len)
        if phashes:
            self._preport_installed.extend(phashes)
            self._preport_evicted.extend(self._prefix.add_all(phashes))
        return kv

    def _take_preport(self):
        """Drain the pending prefix report (rides the next reply to
        the router, which folds it into its registry — the same
        idempotent piggyback channel as the KV eviction notices).
        ``prefills``/``hits`` carry the worker's full-pass and
        skipped-pass counts to the router: SPC counters are
        per-process, so the router side is where a fleet-wide
        prefill-delta can actually be read."""
        if not (self._preport_installed or self._preport_evicted
                or self._prefix_hits or self._preport_prefills):
            return None
        rep = {"gen": self._prefix.generation,
               "installed": self._preport_installed,
               "evicted": self._preport_evicted,
               "hits": self._prefix_hits,
               "prefills": self._preport_prefills}
        self._preport_installed = []
        self._preport_evicted = []
        self._prefix_hits = 0
        self._preport_prefills = 0
        return rep

    def _decode(self, rid: int, tokens_done: int, n: int) -> list:
        kv = self._kv.get(rid)
        if kv is None:
            raise MpiError(ErrorClass.ERR_INTERN,
                           f"decode of rid {rid} without its KV block")
        # one fused read of the KV block per chunk keeps the toy model
        # honest about touching its state
        n = int(n)
        _ = float(kv[: max(1, n)].sum())
        if self.spec_k <= 0:
            # plain autoregressive decode: one target forward pass per
            # emitted token (each token conditions on the previous)
            for _i in range(n):
                _ = np.tanh(np.arange(_TARGET_PASS_ELEMS,
                                      dtype=np.float32)).sum()
            return [toy_token(rid, tokens_done + i) for i in range(n)]
        return self._decode_speculative(rid, tokens_done, n)

    def _decode_speculative(self, rid: int, tokens_done: int,
                            n: int) -> list:
        """Speculative decode of one chunk: the draft proposes up to
        ``spec_k`` tokens, the target verifies the whole window in ONE
        batched pass, and the accepted prefix plus one target token
        (the correction at the first mismatch, or the bonus token after
        a fully accepted window) is emitted — so every round makes
        progress and the output is the target model's token stream
        bit-for-bit regardless of what the draft proposed (the router
        re-verifies every token downstream)."""
        out: list = []
        t = int(tokens_done)
        while len(out) < n:
            window = min(self.spec_k, n - len(out))
            proposals = []
            for i in range(window):
                _ = np.tanh(np.arange(_DRAFT_PASS_ELEMS,
                                      dtype=np.float32)).sum()
                proposals.append(toy_draft_token(rid, t + i))
            # one batched target pass verifies all `window` positions
            # (and yields the window+1'th logits for free)
            _ = np.tanh(np.arange(_TARGET_PASS_ELEMS,
                                  dtype=np.float32)).sum()
            accepted = 0
            for i, prop in enumerate(proposals):
                if prop != toy_token(rid, t + i):
                    break
                accepted += 1
            rejected = window - accepted
            if accepted:
                spc.record("serve_spec_accepts", accepted)
            if rejected:
                spc.record("serve_spec_rejects", rejected)
            out.extend(toy_token(rid, t + i) for i in range(accepted))
            t += accepted
            if len(out) < n:
                # the verify pass already computed this position's
                # target token: correction on a mismatch, bonus after
                # a clean window
                out.append(toy_token(rid, t))
                t += 1
        return out

    # -- command handlers --------------------------------------------------
    def _handle(self, msg) -> None:
        kind = msg[0]
        if kind == "work":
            self._on_work(msg[1], msg[2])
        elif kind == "prefill":
            self._on_prefill(msg[1], msg[2], msg[3])
        elif kind == "kv":
            self._on_kv(msg[1], msg[2])
        elif kind == "scale":
            self._on_scale(msg[1], msg[2])
        elif kind == "stop":
            self._stopped = True
        else:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"unknown serving command {kind!r}")

    def _on_work(self, batch, free_rids) -> None:
        """Colocated/decode micro-batch: (rid, prompt_len, tokens_done,
        n, phashes, hint) per entry; results are one coalesced reply
        carrying the pending prefix report."""
        from ompi_tpu.ft import chaos

        if chaos.enabled:
            # serve-through-failure drills: 'kill:site=serve_work,
            # count=k' dies on the (k+1)-th micro-batch, mid-load with
            # results unsent (tests/test_serving.py's victim schedule)
            chaos.kill_point("serve_work")
            # designed-slow-worker drills: 'delay:ms=8,rank=2,
            # site=serve_work' paces every micro-batch on that rank —
            # the tail cohort otpu_analyze --requests must attribute
            chaos.pace("serve_work")
        req_on = trace.requests_enabled
        firsts = set()                 # rids first seen THIS command
        results = []
        for rid, prompt_len, tokens_done, n, phashes, hint in batch:
            if req_on and rid not in self._req_seen:
                self._req_seen.add(rid)
                firsts.add(rid)
            if rid not in self._kv:
                if self.role == "decode":
                    raise MpiError(
                        ErrorClass.ERR_INTERN,
                        f"decode work for rid {rid} before its KV block")
                if rid in firsts:
                    # colocated: this work cmd carried the dispatch
                    # (otpu-req hop 0) AND runs the prefill stage
                    trace.flow_finish("serve_req", (rid, 0))
                    t0 = trace.now()
                self._kv[rid] = self._prefill_or_skip(rid, prompt_len,
                                                      phashes, hint)
                if rid in firsts:
                    trace.span("req_prefill", "serve_req", t0,
                               args={"rid": rid})
                    spc.record("req_stages")
            toks = self._decode(rid, tokens_done, n)
            spc.record("serve_tokens", len(toks))
            if rid in firsts:
                # hop 2 opens at this rid's first token chunk; the
                # router closes it when the request completes
                trace.flow_start("serve_req", (rid, 2))
            results.append((rid, toks))
        for rid in free_rids:          # router-confirmed evictions
            self._kv.pop(rid, None)
            self._req_seen.discard(rid)
        self.comm.send_obj(("res", results, self._take_preport()),
                           self.router, TAG_RES)

    def _on_prefill(self, peer, epoch, batch) -> None:
        """Prefill-stage micro-batch for ONE decode peer's slab:
        compute each block (prefix cache consulted), Pready it the
        moment it is final, aggregate-flush the slab tail."""
        sender = self._senders.get(int(peer))
        if sender is None:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"prefill asked to stream to decode rank "
                           f"{peer} but no slab pairing exists "
                           f"(peers: {sorted(self._senders)})")
        sender.begin_epoch(epoch)
        req_on = trace.requests_enabled
        rids = []
        for rid, slot, prompt_len, phashes, hint in batch:
            if req_on:
                # otpu-req hop 0 closes at command receipt; the prefill
                # stage span covers compute + slab write, and slot_ready
                # opens hop 1 (prefill -> decode, riding the Pready key)
                trace.flow_finish("serve_req", (rid, 0))
                t0 = trace.now()
            sender.write_slot(slot, self._prefill_or_skip(
                rid, prompt_len, phashes, hint))
            sender.slot_ready(slot, rid=rid if req_on else None)
            if req_on:
                trace.span("req_prefill", "serve_req", t0,
                           args={"rid": rid})
                spc.record("req_stages")
            rids.append(rid)
        sender.finish_epoch(wait=True)
        self.comm.send_obj(("prefilled", epoch, rids,
                            self._take_preport()), self.router,
                           TAG_RES)

    def _on_kv(self, epoch, batch) -> None:
        """Decode-stage KV intake: poll Parrived per assigned slot, copy
        the block out (verified against the deterministic model), then
        drain the epoch's tail so the next one may start."""
        from ompi_tpu.runtime.progress import progress

        self._receiver.begin_epoch(epoch)
        req_on = trace.requests_enabled
        t0 = trace.now() if req_on else 0
        pending = list(batch)
        rids = []
        while pending:
            still = []
            for rid, slot in pending:
                if self._receiver.slot_arrived(slot):
                    # read_slot closes otpu-req hop 1 for this rid
                    # (the arrow the KV slab's Pready key launched)
                    block = self._receiver.read_slot(
                        slot, rid=rid if req_on else None)
                    expect = toy_kv(rid, self.kv_elems)
                    if self._kv_codec:
                        # quantized slab: the decoded block must land
                        # within the codec's band of the exact KV —
                        # outside it is transport corruption, not
                        # quantization
                        from ompi_tpu.mca.coll import quant as _q

                        tol = _q.CODEC_BANDS[self._kv_codec] \
                            * max(1e-6, float(np.abs(expect).max()))
                        if not np.allclose(block, expect, atol=tol,
                                           rtol=0.0):
                            raise AssertionError(
                                f"KV stream corrupted rid {rid} slot "
                                f"{slot} (outside the "
                                f"{self._kv_codec} band)")
                    elif not np.array_equal(block, expect):
                        raise AssertionError(
                            f"KV stream corrupted rid {rid} slot {slot}")
                    self._kv[rid] = block
                    if req_on:
                        # KV intake wait for this rid: epoch start ->
                        # its slab partition arrived and verified
                        trace.span("req_kv", "serve_req", t0,
                                   args={"rid": rid})
                        spc.record("req_stages")
                    rids.append(rid)
                else:
                    still.append((rid, slot))
            pending = still
            if pending:
                progress()
        self._receiver.finish_epoch()
        self.comm.send_obj(("kv_ready", epoch, rids), self.router,
                           TAG_RES)

    def _on_scale(self, argv, n) -> None:
        """Autoscale participation: spawn is collective over the comm,
        so every worker joins the router's MPI_Comm_spawn + merge; the
        merged communicator (parents first) replaces ours."""
        inter = self.comm.spawn(list(argv), int(n), root=self.router)
        full = inter.merge(high=False)
        full.set_errhandler(ERRORS_RETURN)
        self.comm = full               # router keeps comm-rank 0 ordering

    # -- engine loop -------------------------------------------------------
    def step(self) -> bool:
        """Handle at most one pending command; False when idle."""
        found, _st = self.comm.iprobe(self.router, TAG_CMD)
        if not found:
            return False
        msg = self.comm.recv_obj(self.router, TAG_CMD)
        self._handle(msg)
        return True

    def serve(self) -> None:
        """Loop until the router says stop.  Revocation (the router saw
        a death) or a direct peer-failure report drops into recovery;
        a dead ROUTER ends the loop — workers cannot serve without
        admission control."""
        idle_s = 0.0005
        while not self._stopped:
            try:
                if not self.step():
                    time.sleep(idle_s)
            except RevokedError:
                self._recover()
            except ProcFailedError:
                from ompi_tpu.ft import state as ft_state

                router_world = self.comm.group.world_rank(self.router)
                if ft_state.is_failed(router_world):
                    return             # no admission control left
                self._recover()

    def _recover(self) -> None:
        """Serve-through-failure, worker side: shrink with the other
        survivors, rebind, fall back to the colocated role (a stage
        pair may have lost its other half), keep serving.  The prefix
        store clears WITH a generation bump: every routing hint minted
        against the pre-shrink store must miss, never alias."""
        for stream in list(self._senders.values()) + [self._receiver]:
            if stream is not None:
                try:
                    stream.free()
                except Exception:
                    pass               # stream rode the dead comm
        self._senders = {}
        self._receiver = None
        self._req_seen.clear()         # replays re-emit their hops
        self._prefix.clear()
        self._preport_installed = []
        self._preport_evicted = []
        self._prefix_hits = 0
        self._preport_prefills = 0
        new = self.comm.shrink()
        new.set_errhandler(ERRORS_RETURN)
        self.comm = new
        from ompi_tpu import serving as _pkg

        self.router = _pkg.roles(new)[0]
        self.role = "colocated"


def worker_main() -> int:
    """Entry point of an AUTOSCALED worker process (``python -m
    ompi_tpu.serving.worker``): meet the parents through
    ``MPI_Comm_get_parent``, merge into their serving communicator
    (children rank after parents, so the router's rank is unchanged),
    and serve."""
    import ompi_tpu

    ompi_tpu.init()
    parent = ompi_tpu.get_parent()
    if parent is None:
        raise SystemExit("serving worker_main: not a spawned process")
    full = parent.merge(high=True)
    ShardWorker(full, router=0).serve()
    return 0


if __name__ == "__main__":
    raise SystemExit(worker_main())
