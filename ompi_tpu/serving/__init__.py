"""ompi_tpu.serving — continuous-batching inference on top of the runtime.

The serving frontier of ROADMAP open item 3: everything below this
package optimizes the *training* path; this one opens the
heavy-traffic inference scenario using exactly the machinery the
earlier PRs built —

* a **request router** rank feeding model-shard **worker** ranks over an
  ordinary communicator (:mod:`ompi_tpu.serving.router`,
  :mod:`ompi_tpu.serving.worker`);
* **continuous batching**: an admission scheduler merges in-flight
  requests into prefill/decode micro-batches every engine tick and
  evicts finished sequences without draining the batch
  (:mod:`ompi_tpu.serving.scheduler`);
* **KV-cache streaming** between the prefill and decode stages over
  MPI-4 partitioned persistent requests — ``Psend_init``/``Precv_init``
  per stage pair, one ``Pready`` per finished sequence, the bucketed-
  overlap machinery of ``mca/part`` pointed at inference
  (:mod:`ompi_tpu.serving.kv_stream`);
* **autoscaling** via ``dpm.spawn`` when queue depth crosses a
  watermark, new workers joining through the dynamic ``mpi://job/<id>``
  process set;
* **serve-through-failure**: on ``proc_failed`` the comm is revoked,
  survivors shrink (publishing ``mpi://surviving``), the router
  re-shards its worker table and requeues the dead worker's in-flight
  requests — no admitted request is ever dropped;
* **the fleet** (:mod:`ompi_tpu.serving.fleet`,
  :mod:`ompi_tpu.serving.prefix_cache`): multiple models and tenants
  sharing one job — named per-model pools (``mpi://serving/pool/
  <model>`` psets, ``tpurun --pool``), fair-share weighted-round-robin
  admission across tenants, prefix-cache-aware routing (hash prompt
  prefixes at KV-block granularity, route to the worker already
  holding them, verified generations so stale entries are perf misses
  only), and autoscaling driven by the live telemetry plane (per-pool
  p99 SLO / stale ranks / depth) instead of a queue-depth watermark.

Why the eager/partitioned lanes and not naive per-request sends:
"Optimizing Allreduce with Multiple Processes per GPU" (arxiv
2508.13397) shows per-message software overhead dominating small
transfers — the regime of per-request decode traffic — so decode
commands ride one coalesced micro-batch message per worker per tick and
KV blocks ride the aggregated partitioned slab.

Role placement: ``tpurun --router-ranks/--worker-ranks`` publish the
``mpi://serving/router`` / ``mpi://serving/workers`` psets; without
them the lowest comm rank routes and the rest serve shards
(:func:`roles`).
"""
from __future__ import annotations

#: role process-set names served by the coordination service (published
#: by ``tpurun --router-ranks`` / ``--worker-ranks``)
PSET_ROUTER = "mpi://serving/router"
PSET_WORKERS = "mpi://serving/workers"


def roles(comm) -> tuple[int, list]:
    """(router comm-rank, [worker comm-ranks]) for ``comm``.

    Resolution order: the ``mpi://serving/router`` / ``.../workers``
    psets when the coordination service advertises them (world ranks are
    mapped into ``comm``; members outside the comm are ignored), else
    the default split — lowest rank routes, everyone else serves.
    """
    router, workers = None, None
    client = getattr(comm.rte, "client", None)
    if client is not None:
        try:
            r_entry = client.pset_get(PSET_ROUTER)
            w_entry = client.pset_get(PSET_WORKERS)
        except Exception:
            r_entry = w_entry = None
        in_comm = {w: i for i, w in enumerate(comm.group.world_ranks)}
        if r_entry is not None:
            rr = [in_comm[int(m)] for m in r_entry["members"]
                  if int(m) in in_comm]
            router = rr[0] if rr else None
        if w_entry is not None:
            workers = sorted(in_comm[int(m)] for m in w_entry["members"]
                             if int(m) in in_comm)
    if router is None:
        router = 0
    if not workers:
        workers = [r for r in range(comm.size) if r != router]
    return router, [w for w in workers if w != router]


from ompi_tpu.serving.scheduler import (ContinuousBatchScheduler,  # noqa: E402
                                        ServeRequest)
from ompi_tpu.serving.kv_stream import (KvSlabReceiver,  # noqa: E402
                                        KvSlabSender)
from ompi_tpu.serving.prefix_cache import (PrefixRegistry,  # noqa: E402
                                           PrefixStore, block_hashes)
from ompi_tpu.serving.frontdoor import (Decision, FrontDoor,  # noqa: E402
                                        SLO_BATCH, SLO_INTERACTIVE,
                                        TokenBucket)
from ompi_tpu.serving.router import Router  # noqa: E402
from ompi_tpu.serving.worker import (ShardWorker,  # noqa: E402
                                     toy_draft_token, toy_token,
                                     worker_main)
from ompi_tpu.serving.fleet import (FleetAutoscaler,  # noqa: E402
                                    FleetController, PoolSpec,
                                    PSET_POOL_PREFIX,
                                    pool_specs_from_psets)
from ompi_tpu.serving.driver import (MixedPoissonDriver,  # noqa: E402
                                     PoissonDriver)

__all__ = [
    "PSET_ROUTER", "PSET_WORKERS", "PSET_POOL_PREFIX", "roles",
    "ServeRequest", "ContinuousBatchScheduler",
    "KvSlabSender", "KvSlabReceiver",
    "PrefixRegistry", "PrefixStore", "block_hashes",
    "Router", "ShardWorker", "worker_main",
    "toy_token", "toy_draft_token",
    "FrontDoor", "TokenBucket", "Decision",
    "SLO_INTERACTIVE", "SLO_BATCH",
    "FleetController", "FleetAutoscaler", "PoolSpec",
    "pool_specs_from_psets",
    "PoissonDriver", "MixedPoissonDriver",
]
