"""Serving front door: SLO-tiered admission, rate limiting, and
overload shedding ahead of the continuous-batching routers.

The scheduler (``serving/scheduler.py``) admits fairly among tenants but
admits *everything* — under sustained overload its queues grow without
bound and every class's latency collapses together.  The front door sits
between the driver and the routers and turns overload into policy:

* every request declares an SLO class (``interactive`` or ``batch``) and
  waits in a bounded per-(pool, class) FIFO behind the door;
* per-tenant token buckets rate-limit admission; a request that finds no
  token (or a full class queue) is **shed** with a deterministic
  retry-after hint instead of queued forever — the driver re-arrives it;
* the door forwards into the scheduler only while the scheduler is
  shallow (``otpu_serving_fd_backlog``), interactive first, so the
  in-engine queue stays short and interactive latency stays bounded;
* when a pool's interactive p99 (rolling window of door-observed
  completions) breaches ``otpu_serving_slo_p99_ms``, the door
  **preempts**: RUNNING batch requests are requeued (never dropped),
  QUEUED batch work is withdrawn back behind the door, and batch
  forwarding is held for ``otpu_serving_fd_hold_ticks`` pump cycles.

Shed -> preempt -> scale-up is one escalation ladder: the breach signal
here is the same ``otpu_serving_slo_p99_ms`` the SLO accountant
(``runtime/telemetry.py``) and the fleet autoscaler
(``serving/fleet.py``) read, and every decision is trace-instant'ed and
SPC-counted (``serve_shed`` / ``serve_preempt``).

The module follows the telemetry/profile module-bool discipline: with no
``FrontDoor`` constructed, ``enabled`` is ``False``, ``_active`` is
``None``, no queue objects exist, no threads run (the door never owns a
thread at all — ``pump()`` rides the fleet tick), and the hot-path hook
in ``router._finish`` is one module-attribute check.  ``test_perf_guard``
pins that identity.

NOTE import discipline: ``router.py`` imports this module, so this
module must never import ``router`` — only scheduler / telemetry / spc /
trace / var.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import spc, telemetry, trace
from ompi_tpu.serving.scheduler import RequestState, ServeRequest

#: the two admission classes.  "" on a ServeRequest means unclassified
#: (submitted around the door) — such requests are never shed and never
#: preempted.
SLO_INTERACTIVE = "interactive"
SLO_BATCH = "batch"
SLO_CLASSES = (SLO_INTERACTIVE, SLO_BATCH)

#: a breach verdict needs at least this many interactive completions in
#: the rolling window — a p99 over three samples is noise, not a signal
_MIN_WINDOW = 16

_queue_cap_var = registry.register(
    "serving", None, "fd_queue_cap", vtype=VarType.INT, default=64,
    help="Front door: bounded depth of each per-(pool, SLO-class) "
         "admission queue.  A request arriving at a full queue is shed "
         "with a retry-after instead of admitted")
_rate_var = registry.register(
    "serving", None, "fd_rate_rps", vtype=VarType.FLOAT, default=0.0,
    help="Front door: per-tenant token-bucket refill rate "
         "(requests/second).  0 (the default) disables rate limiting — "
         "only queue bounds shed")
_burst_var = registry.register(
    "serving", None, "fd_burst", vtype=VarType.FLOAT, default=8.0,
    help="Front door: token-bucket capacity — how many requests a "
         "tenant may burst above its sustained fd_rate_rps")
_retry_s_var = registry.register(
    "serving", None, "fd_retry_s", vtype=VarType.FLOAT, default=0.05,
    help="Front door: retry-after hint (seconds) attached to queue-full "
         "sheds.  Rate-limit sheds compute their own hint from the "
         "bucket deficit")
_backlog_var = registry.register(
    "serving", None, "fd_backlog", vtype=VarType.INT, default=8,
    help="Front door: forward door-held requests into a pool's "
         "scheduler only while its queued depth is below this "
         "watermark — the in-engine queue stays shallow and the door "
         "keeps class ordering under its own control")
_hold_ticks_var = registry.register(
    "serving", None, "fd_hold_ticks", vtype=VarType.INT, default=50,
    help="Front door: after preempting a pool's batch work on an "
         "interactive-p99 breach, hold batch forwarding for this many "
         "pump cycles so the preemption can actually drain the "
         "interactive backlog before batch re-enters")
_window_var = registry.register(
    "serving", None, "fd_p99_window", vtype=VarType.INT, default=64,
    help="Front door: rolling window (completions) of per-pool "
         "interactive latencies the breach detector computes its p99 "
         "over")

#: module-bool discipline (telemetry/profile pattern): `enabled` is the
#: one-attribute hot-path gate in router._finish; `_active` is the armed
#: door instance.  Both stay inert until a FrontDoor is constructed.
enabled = False
_active: Optional["FrontDoor"] = None


def observe(pool: str, slo: str, dur_ms: float) -> None:
    """Hot-path completion hook (router._finish): feed one finished
    request's latency to the armed door's breach detector.  No-op
    unless a door is armed."""
    fd = _active
    if fd is not None:
        fd.observe(pool, slo, dur_ms)


def disarm(fd: Optional["FrontDoor"] = None) -> None:
    """Disarm the module hooks.  With an instance given, only disarms
    if that instance is the armed one (a closed old door must not
    disarm its replacement)."""
    global enabled, _active
    if fd is None or _active is fd:
        _active = None
        enabled = False


def _arm(fd: "FrontDoor") -> None:
    global enabled, _active
    _active = fd
    enabled = True


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/second refill toward
    a ``burst`` cap; one token per admission.  The clock is injectable
    so tests (and the Poisson driver's virtual time) get bit-exact
    refill math, and a failed take returns the exact deficit wait —
    ``(1 - tokens) / rate`` seconds — which becomes the retry-after
    hint the driver honors."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float,
                 now: float = 0.0) -> None:
        if rate <= 0.0:
            raise MpiError(ErrorClass.ERR_ARG,
                           "token bucket needs a positive rate")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._last = float(now)

    def _refill(self, now: float) -> None:
        dt = float(now) - self._last
        if dt > 0.0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
        self._last = float(now)

    def try_take(self, now: float) -> float:
        """Take one token at time ``now``.  Returns 0.0 on success, or
        the exact wait (seconds) until one token will be available."""
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


class Decision:
    """Outcome of one door submission: either ``request`` (admitted —
    the door now owns it until it forwards into the scheduler) or a
    shed with a ``retry_after_s`` hint and the shed ``reason``
    (``"rate"`` or ``"queue"``)."""

    __slots__ = ("request", "retry_after_s", "reason")

    def __init__(self, request: Optional[ServeRequest],
                 retry_after_s: float, reason: str) -> None:
        self.request = request
        self.retry_after_s = float(retry_after_s)
        self.reason = reason

    @property
    def admitted(self) -> bool:
        return self.request is not None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if self.admitted:
            return f"Decision(admitted rid={self.request.rid})"
        return (f"Decision(shed reason={self.reason} "
                f"retry_after={self.retry_after_s:.4f}s)")


class FrontDoor:
    """The admission plane over a fleet's routers.

    Construction arms the module hooks (``enabled`` / ``observe``);
    ``close()`` disarms them.  All mutable state is guarded by one
    lock; ``pump()`` is called from the fleet tick (rank 0's control
    loop) — the door never starts a thread.
    """

    _guarded_by = {
        "_q": "_lock", "_buckets": "_lock", "_tenant_class": "_lock",
        "_lat": "_lock", "_hold": "_lock", "_shed_by": "_lock",
        "_shed_total": "_lock", "_preempt_total": "_lock",
        "_forwarded": "_lock", "_admitted_total": "_lock",
        "_last_retry_s": "_lock", "_breaches": "_lock",
    }

    def __init__(self, routers: Dict[str, object], *,
                 queue_cap: Optional[int] = None,
                 rate_rps: Optional[float] = None,
                 burst: Optional[float] = None,
                 retry_s: Optional[float] = None,
                 backlog: Optional[int] = None,
                 hold_ticks: Optional[int] = None,
                 window: Optional[int] = None,
                 rates: Optional[Dict[str, Tuple[float, float]]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not routers:
            raise MpiError(ErrorClass.ERR_ARG,
                           "front door needs at least one pool router")
        self.routers = dict(routers)
        # config resolves once at construction (var or explicit kwarg)
        self.queue_cap = int(_queue_cap_var.value
                             if queue_cap is None else queue_cap)
        self.rate_rps = float(_rate_var.value
                              if rate_rps is None else rate_rps)
        self.burst = float(_burst_var.value if burst is None else burst)
        self.retry_s = float(_retry_s_var.value
                             if retry_s is None else retry_s)
        self.backlog = int(_backlog_var.value
                           if backlog is None else backlog)
        self.hold_ticks = int(_hold_ticks_var.value
                              if hold_ticks is None else hold_ticks)
        window = int(_window_var.value if window is None else window)
        self._clock = clock
        self._lock = threading.Lock()
        self._q: Dict[Tuple[str, str], collections.deque] = {
            (pool, cls): collections.deque()
            for pool in self.routers for cls in SLO_CLASSES}
        #: per-tenant (rate, burst) overrides; tenants not listed use
        #: the fd_rate_rps/fd_burst defaults
        self._rates = dict(rates or {})
        self._buckets: Dict[str, TokenBucket] = {}
        #: SLO tier is a tenant property: the first class a tenant
        #: submits with sticks, so each scheduler tenant queue stays
        #: arrival-ordered even though the door forwards interactive
        #: ahead of batch
        self._tenant_class: Dict[str, str] = {}
        self._lat = {pool: collections.deque(maxlen=max(window,
                                                        _MIN_WINDOW))
                     for pool in self.routers}
        self._hold = {pool: 0 for pool in self.routers}
        self._shed_by: Dict[str, int] = {}
        self._shed_total = 0
        self._preempt_total = 0
        self._forwarded = 0
        self._admitted_total = 0
        self._breaches = 0
        self._last_retry_s = 0.0
        self._slo_var = None
        telemetry.register_source("frontdoor", self.stats)
        _arm(self)

    # -- admission ---------------------------------------------------------

    def submit(self, tenant: str, model: str = "", prompt_len: int = 0,
               max_new_tokens: int = 8, slo: str = SLO_INTERACTIVE,
               prompt=None, rid: Optional[int] = None) -> Decision:
        """Ask the door for admission.  Returns a ``Decision``: either
        an admitted ``ServeRequest`` (door-held until forwarded — its
        ``arrival_ns`` stamps NOW, so door wait counts toward latency)
        or a shed with a deterministic retry-after."""
        cls = str(slo or SLO_INTERACTIVE)
        if cls not in SLO_CLASSES:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"unknown SLO class {cls!r} (want one of "
                           f"{SLO_CLASSES})")
        pool = str(model)
        if pool not in self.routers:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"unknown pool {pool!r} (have "
                           f"{sorted(self.routers)})")
        tenant = str(tenant)
        now = self._clock()
        with self._lock:
            bound = self._tenant_class.setdefault(tenant, cls)
            if bound != cls:
                raise MpiError(
                    ErrorClass.ERR_ARG,
                    f"tenant {tenant!r} is bound to SLO class "
                    f"{bound!r}; per-tenant FIFO order in the "
                    f"scheduler requires one class per tenant")
            bucket = self._bucket_locked(tenant, now)
            if bucket is not None:
                wait = bucket.try_take(now)
                if wait > 0.0:
                    return self._shed_locked(tenant, pool, cls, wait,
                                             "rate")
            q = self._q[(pool, cls)]
            if len(q) >= self.queue_cap:
                return self._shed_locked(tenant, pool, cls,
                                         self.retry_s, "queue")
            req = ServeRequest(prompt_len, max_new_tokens, rid=rid,
                               tenant=tenant, model=pool, prompt=prompt,
                               slo=cls)
            q.append(req)
            self._admitted_total += 1
        return Decision(req, 0.0, "admitted")

    def _bucket_locked(self, tenant: str,
                       now: float) -> Optional[TokenBucket]:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            rate, burst = self._rates.get(tenant,
                                          (self.rate_rps, self.burst))
            if rate <= 0.0:
                return None
            bucket = TokenBucket(rate, burst, now=now)
            self._buckets[tenant] = bucket
        return bucket

    def _shed_locked(self, tenant: str, pool: str, cls: str,
                     retry_after_s: float, reason: str) -> Decision:
        retry_after_s = max(1e-6, float(retry_after_s))
        key = f"{tenant}/{cls}"
        self._shed_by[key] = self._shed_by.get(key, 0) + 1
        self._shed_total += 1
        self._last_retry_s = retry_after_s
        spc.record("serve_shed")
        trace.instant("frontdoor_shed", "serving", {
            "tenant": tenant, "pool": pool, "slo": cls,
            "reason": reason,
            "retry_after_ms": round(retry_after_s * 1e3, 3)})
        return Decision(None, retry_after_s, reason)

    # -- pump (rides the fleet tick; rank 0 only, no threads) --------------

    def pump(self) -> None:
        """One admission cycle per pool: age the batch hold, check the
        breach ladder, forward door-held work while the scheduler is
        shallow (interactive first)."""
        for pool, router in self.routers.items():
            with self._lock:
                if self._hold[pool] > 0:
                    self._hold[pool] -= 1
            self._check_breach(pool, router)
            self._forward(pool, router)

    def _target_ms(self) -> float:
        if self._slo_var is None:
            self._slo_var = registry.lookup("otpu_serving_slo_p99_ms")
        return float(self._slo_var.value or 0.0) if self._slo_var \
            else 0.0

    def _check_breach(self, pool: str, router) -> None:
        target = self._target_ms()
        if target <= 0.0:
            return
        with self._lock:
            if self._hold[pool] > 0:
                # a recent preemption is still absorbing — don't stack
                return
            lat = self._lat[pool]
            n = len(lat)
            if n < _MIN_WINDOW:
                return
            snd = sorted(lat)
            p99 = snd[min(n - 1, int(0.99 * n))]
            if p99 <= target:
                return
        self._preempt(pool, router, p99, target)

    def _preempt(self, pool: str, router, p99: float,
                 target: float) -> None:
        """Interactive p99 breached: requeue the pool's RUNNING batch
        work (never dropped — the scheduler keeps its decoded tokens),
        withdraw its QUEUED batch work back behind the door, and hold
        batch forwarding so the freed slots drain interactive."""
        sched = router.sched
        victims = [r for r in sched.running() if r.slo == SLO_BATCH]
        if victims:
            sched.requeue(victims)
        withdrawn = sched.withdraw(SLO_BATCH)
        with self._lock:
            self._hold[pool] = self.hold_ticks
            self._breaches += 1
            if withdrawn:
                # withdrawn work is older than anything door-held —
                # re-insert at the FRONT in reverse arrival order so
                # the door queue stays arrival-sorted
                q = self._q[(pool, SLO_BATCH)]
                for r in sorted(withdrawn, key=lambda r: r.arrival_ns,
                                reverse=True):
                    q.appendleft(r)
            if victims:
                self._preempt_total += len(victims)
            # the breach window served its purpose — reset it so the
            # next verdict is computed from post-preemption completions
            self._lat[pool].clear()
        if victims:
            spc.record("serve_preempt", len(victims))
        trace.instant("frontdoor_preempt", "serving", {
            "pool": pool, "p99_ms": round(p99, 3),
            "target_ms": round(target, 3),
            "preempted": len(victims), "withdrawn": len(withdrawn),
            "hold_ticks": self.hold_ticks})

    def _forward(self, pool: str, router) -> None:
        sched = router.sched
        while sched.depth() < self.backlog:
            req = None
            with self._lock:
                hold = self._hold[pool] > 0
                for cls in SLO_CLASSES:
                    if cls == SLO_BATCH and hold:
                        continue
                    q = self._q[(pool, cls)]
                    if q:
                        req = q.popleft()
                        break
            if req is None:
                return
            sched.submit(req)
            with self._lock:
                self._forwarded += 1

    # -- breach-detector feed (router._finish via module observe()) --------

    def observe(self, pool: str, slo: str, dur_ms: float) -> None:
        if slo != SLO_INTERACTIVE:
            return
        dq = self._lat.get(pool)
        if dq is None:
            return
        with self._lock:
            dq.append(float(dur_ms))

    # -- introspection -----------------------------------------------------

    def depth(self) -> int:
        """Requests currently held behind the door (all pools/classes).
        The driver's drain condition: fleet idle AND door empty AND no
        pending retries."""
        with self._lock:
            return sum(len(q) for q in self._q.values())

    def stats(self) -> dict:
        """Telemetry source for the ``frontdoor`` schema key."""
        with self._lock:
            queued = {f"{pool or '-'}/{cls}": len(q)
                      for (pool, cls), q in self._q.items() if q}
            holds = {p or "-": h for p, h in self._hold.items() if h}
            return {
                "queue_cap": self.queue_cap,
                "queued": queued,
                "admitted": self._admitted_total,
                "forwarded": self._forwarded,
                "shed": self._shed_total,
                "shed_by": dict(self._shed_by),
                "preempts": self._preempt_total,
                "breaches": self._breaches,
                "holds": holds,
                "last_retry_ms": round(self._last_retry_s * 1e3, 3),
                "buckets": {t: round(b.tokens, 3)
                            for t, b in sorted(self._buckets.items())},
            }

    def check_invariants(self) -> None:
        """Soak-time assertions: bounded queues, arrival order, class
        purity of every door queue."""
        with self._lock:
            for (pool, cls), q in self._q.items():
                assert len(q) <= self.queue_cap, \
                    f"door queue {pool}/{cls} over cap: {len(q)}"
                arr = [r.arrival_ns for r in q]
                assert arr == sorted(arr), \
                    f"door queue {pool}/{cls} not arrival-ordered"
                for r in q:
                    assert r.slo == cls, \
                        f"class mix in door queue {pool}/{cls}"
                    assert r.state is RequestState.QUEUED, \
                        f"non-QUEUED request behind the door: {r.rid}"

    def close(self) -> None:
        """Disarm the module hooks.  Door-held requests stay owned by
        whoever drains the fleet (shutdown abandons them like the
        scheduler abandons its queue)."""
        disarm(self)
