"""serving/driver — the synthetic heavy-traffic drivers.

:class:`PoissonDriver`: Poisson arrivals (seeded exponential
inter-arrival gaps) with mixed prompt/decode lengths, fed into a
:class:`~ompi_tpu.serving.router.Router` in wall-clock time; the
report reads p50/p99 request latency out of the otpu-trace
``serve_request`` log2 histogram (the percentile estimator of
``runtime/trace.py``) and computes tokens/sec from the completed set —
the serving benchmark surface ``bench.py --serving`` publishes,
qualitatively different from the OSU-style sweeps (open-loop offered
load against a queueing system instead of a closed request/reply
ping-pong).

:class:`MixedPoissonDriver`: the FLEET version — several tenants, each
with its own seeded arrival process, request rate, prompt/decode
length mix, target model, and (optionally) a pool of shared prompt
prefixes (the traffic shape that makes prefix-cache routing pay).
Per-tenant latency percentiles come from per-tenant otpu-trace
histogram FAMILIES (``serve_tenant_<name>``), each ``hist_reset`` at
run start, so two tenants' percentile populations never merge — the
per-tenant p99 is a real per-tenant number, not a blended one.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ompi_tpu.base.var import registry
from ompi_tpu.runtime import trace
from ompi_tpu.serving.frontdoor import SLO_INTERACTIVE
from ompi_tpu.serving.router import POOL_HIST_PREFIX, TENANT_HIST_PREFIX


class PoissonDriver:
    """Open-loop traffic: ``n_requests`` arrivals at ``rate_rps`` with
    prompt/decode lengths drawn uniformly from the given ranges."""

    def __init__(self, rate_rps: float = 200.0, n_requests: int = 64,
                 prompt_lens: tuple = (8, 64),
                 decode_lens: tuple = (4, 24), seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        self.n_requests = int(n_requests)
        gaps = rng.exponential(1.0 / float(rate_rps), self.n_requests)
        self.arrivals_s = np.cumsum(gaps)       # offsets from run start
        self.prompts = rng.integers(prompt_lens[0], prompt_lens[1] + 1,
                                    self.n_requests)
        self.decodes = rng.integers(decode_lens[0], decode_lens[1] + 1,
                                    self.n_requests)
        self._next = 0

    def due(self, elapsed_s: float) -> list:
        """(prompt_len, decode_len) pairs whose arrival time has come."""
        out = []
        while (self._next < self.n_requests
               and self.arrivals_s[self._next] <= elapsed_s):
            out.append((int(self.prompts[self._next]),
                        int(self.decodes[self._next])))
            self._next += 1
        return out

    @property
    def exhausted(self) -> bool:
        return self._next >= self.n_requests

    def run(self, router, max_wall_s: float = 120.0,
            tick_sleep_s: float = 0.0) -> dict:
        """Drive the router under this arrival process and report.

        Tracing is force-enabled for the run (the latency histogram IS
        the measurement instrument) and restored afterwards.
        """
        was_enabled = trace.enabled
        if not was_enabled:
            registry.set("otpu_trace_enable", True)
        # fresh percentile population: an earlier run in this process
        # must not bleed into this run's p50/p99
        trace.hist_reset("serve_request")
        t0 = time.perf_counter()
        try:
            while True:
                elapsed = time.perf_counter() - t0
                if elapsed > max_wall_s:
                    raise TimeoutError(
                        f"serving driver exceeded {max_wall_s}s with "
                        f"{len(router.completed())}/{self.n_requests} "
                        "requests complete")
                for prompt_len, decode_len in self.due(elapsed):
                    router.submit(prompt_len, decode_len)
                router.tick()
                if (self.exhausted and not router.sched.depth()
                        and not router.sched.running()):
                    break
                if tick_sleep_s:
                    time.sleep(tick_sleep_s)
            elapsed = time.perf_counter() - t0
            return self.report(router, elapsed)
        finally:
            if not was_enabled:
                registry.set("otpu_trace_enable", False)

    def report(self, router, elapsed_s: float) -> dict:
        done = router.completed()
        tokens = sum(len(r.tokens) for r in done)
        lat_ms = sorted((r.done_ns - r.arrival_ns) / 1e6 for r in done
                        if r.done_ns is not None)
        exact_p99 = _exact_p99(lat_ms)
        return {
            "requests": len(done),
            "elapsed_s": round(elapsed_s, 3),
            "tokens": int(tokens),
            "tokens_per_s": round(tokens / elapsed_s, 1),
            "req_per_s": round(len(done) / elapsed_s, 1),
            # the contract numbers: percentiles interpolated from the
            # otpu-trace log2 latency histogram
            "p50_ms": round(
                trace.hist_percentile("serve_request", 0.50) / 1000.0, 3),
            "p99_ms": round(
                trace.hist_percentile("serve_request", 0.99) / 1000.0, 3),
            # cross-check: exact p99 over the driver's own sample list
            # (the histogram estimate must sit within a log2 bin of it)
            "p99_exact_ms": round(exact_p99, 3),
            "requeued": router.lost_and_requeued,
        }


def _exact_p99(lat_ms: list) -> float:
    if not lat_ms:
        return 0.0
    return lat_ms[min(len(lat_ms) - 1, int(0.99 * len(lat_ms)))]


class MixedPoissonDriver:
    """Multi-tenant open-loop traffic against a
    :class:`~ompi_tpu.serving.fleet.FleetController` (or a single
    Router — anything with ``submit``/``tick``/``completed``).

    ``tenants`` maps a tenant name to its workload::

        {"ten_a": {"model": "m_a", "rate_rps": 300.0, "n_requests": 32,
                   "prompt_lens": (8, 64), "decode_lens": (4, 24),
                   "prefixes": 4, "prefix_len": 32},
         ...}

    Every tenant gets its OWN deterministic rng stream (seeded
    ``[seed, tenant index]``), so adding a tenant never perturbs
    another tenant's arrivals.  ``prefixes``/``prefix_len`` draw each
    prompt as one of ``prefixes`` shared token templates plus a random
    suffix — the shared-system-prompt shape that exercises
    prefix-cache routing; 0 (the default) submits length-only requests
    exactly like :class:`PoissonDriver`."""

    def __init__(self, tenants: dict, seed: int = 0) -> None:
        if not tenants:
            raise ValueError("mixed driver needs at least one tenant")
        self.tenants = {}
        events = []
        for idx, (name, cfg) in enumerate(sorted(tenants.items())):
            cfg = dict(cfg)
            model = cfg.get("model", "")
            rate = float(cfg.get("rate_rps", 200.0))
            n = int(cfg.get("n_requests", 32))
            plens = cfg.get("prompt_lens", (8, 64))
            dlens = cfg.get("decode_lens", (4, 24))
            n_prefix = int(cfg.get("prefixes", 0))
            prefix_len = int(cfg.get("prefix_len", 0))
            rng = np.random.default_rng([int(seed), idx])
            templates = [tuple(int(t) for t in
                               rng.integers(0, 50000, prefix_len))
                         for _ in range(n_prefix)] \
                if n_prefix and prefix_len else []
            arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
            for i in range(n):
                decode = int(rng.integers(dlens[0], dlens[1] + 1))
                if templates:
                    tmpl = templates[int(rng.integers(len(templates)))]
                    suffix = tuple(int(t) for t in rng.integers(
                        0, 50000, int(rng.integers(plens[0],
                                                   plens[1] + 1))))
                    prompt = tmpl + suffix
                    events.append((float(arrivals[i]), name, model,
                                   len(prompt), decode, prompt))
                else:
                    plen = int(rng.integers(plens[0], plens[1] + 1))
                    events.append((float(arrivals[i]), name, model,
                                   plen, decode, None))
            self.tenants[name] = {"model": model, "n_requests": n,
                                  "slo": str(cfg.get("slo", ""))}
        events.sort(key=lambda e: e[0])
        self.events = events
        self.n_requests = len(events)
        self._next = 0
        # shed/retry accounting per tenant — filled by run() when the
        # fleet has a front door armed, zero otherwise
        self._shed: dict = {}
        self._retried: dict = {}

    def due(self, elapsed_s: float) -> list:
        """(tenant, model, prompt_len, decode_len, prompt-tokens)
        tuples whose arrival time has come, across every tenant."""
        out = []
        while (self._next < self.n_requests
               and self.events[self._next][0] <= elapsed_s):
            out.append(self.events[self._next][1:])
            self._next += 1
        return out

    @property
    def exhausted(self) -> bool:
        return self._next >= self.n_requests

    def _submit(self, fleet, tenant, model, plen, dlen,
                prompt) -> Optional[float]:
        """Submit one arrival.  Returns ``None`` when admitted, or the
        front door's retry-after hint (seconds) when shed — the run
        loop re-arrives the request after exactly that delay."""
        cls = self.tenants[tenant].get("slo", "")
        fd = getattr(fleet, "frontdoor", None)
        if fd is not None:
            used = cls or SLO_INTERACTIVE
            self.tenants[tenant]["slo_used"] = used
            dec = fd.submit(tenant, model, prompt_len=plen,
                            max_new_tokens=dlen, slo=used,
                            prompt=prompt)
            if not dec.admitted:
                return dec.retry_after_s
        elif hasattr(fleet, "routers"):
            fleet.submit(tenant, model, prompt_len=plen,
                         max_new_tokens=dlen, prompt=prompt, slo=cls)
        else:                          # a bare Router works too
            fleet.submit(plen, dlen, tenant=tenant, prompt=prompt,
                         slo=cls)
        return None

    @staticmethod
    def _idle(fleet) -> bool:
        """Nothing queued or running — fleet and bare Router alike
        (the Router keeps those on its scheduler)."""
        sched = fleet if hasattr(fleet, "depth") else fleet.sched
        return not sched.depth() and not sched.running()

    def run(self, fleet, max_wall_s: float = 120.0,
            tick_sleep_s: float = 0.0,
            check_invariants: bool = False) -> dict:
        """Drive the fleet under the merged arrival processes and
        report per tenant.  Tracing is force-enabled for the run (the
        histogram families ARE the measurement instrument) and every
        per-tenant/per-pool family is reset first — percentile
        populations from an earlier run in this process never merge
        into this one's."""
        was_enabled = trace.enabled
        if not was_enabled:
            registry.set("otpu_trace_enable", True)
        trace.hist_reset("serve_request")
        models = set()
        for name, info in self.tenants.items():
            trace.hist_reset(TENANT_HIST_PREFIX + name)
            models.add(info["model"])
        for model in models:
            trace.hist_reset(POOL_HIST_PREFIX + model)
        prefills0, hits0 = self._prefix_counts(fleet)
        self._shed = {}
        self._retried = {}
        #: shed arrivals waiting out their retry-after hint:
        #: (due_s, tenant, model, plen, dlen, prompt)
        pending: list = []
        fd = getattr(fleet, "frontdoor", None)
        t0 = time.perf_counter()
        try:
            while True:
                elapsed = time.perf_counter() - t0
                if elapsed > max_wall_s:
                    raise TimeoutError(
                        f"mixed driver exceeded {max_wall_s}s with "
                        f"{len(fleet.completed())}/{self.n_requests} "
                        "requests complete")
                arrivals = list(self.due(elapsed))
                if pending:
                    # honor retry-after: a shed request re-arrives only
                    # once its hinted delay has fully elapsed
                    due_now = [e for e in pending if e[0] <= elapsed]
                    if due_now:
                        pending = [e for e in pending
                                   if e[0] > elapsed]
                        for e in due_now:
                            self._retried[e[1]] = \
                                self._retried.get(e[1], 0) + 1
                        arrivals.extend(e[1:] for e in due_now)
                for tenant, model, plen, dlen, prompt in arrivals:
                    retry = self._submit(fleet, tenant, model, plen,
                                         dlen, prompt)
                    if retry is not None:
                        self._shed[tenant] = \
                            self._shed.get(tenant, 0) + 1
                        pending.append((elapsed + retry, tenant, model,
                                        plen, dlen, prompt))
                fleet.tick()
                if check_invariants and hasattr(fleet, "routers"):
                    for router in fleet.routers.values():
                        router.sched.check_invariants()
                    if fd is not None:
                        fd.check_invariants()
                if (self.exhausted and not pending
                        and (fd is None or not fd.depth())
                        and self._idle(fleet)):
                    break
                if tick_sleep_s:
                    time.sleep(tick_sleep_s)
            elapsed = time.perf_counter() - t0
            return self.report(fleet, elapsed, prefills0, hits0)
        finally:
            if not was_enabled:
                registry.set("otpu_trace_enable", False)

    @staticmethod
    def _prefix_counts(fleet) -> tuple:
        """(full prefills, verified hits) as the ROUTER side counted
        them from worker reports — works across processes, where SPC
        counters (per process, worker-side) cannot."""
        routers = fleet.routers.values() if hasattr(fleet, "routers") \
            else (fleet,)
        return (sum(r.prefill_count for r in routers),
                sum(r.prefix_hit_count for r in routers))

    def report(self, fleet, elapsed_s: float, prefills0: int = 0,
               hits0: int = 0) -> dict:
        done = fleet.completed()
        tokens = sum(len(r.tokens) for r in done)
        per_tenant = {}
        for name in self.tenants:
            mine = [r for r in done if r.tenant == name]
            lat_ms = sorted((r.done_ns - r.arrival_ns) / 1e6
                            for r in mine if r.done_ns is not None)
            fam = TENANT_HIST_PREFIX + name
            t_tokens = sum(len(r.tokens) for r in mine)
            per_tenant[name] = {
                "requests": len(mine),
                "tokens": t_tokens,
                "tokens_per_s": round(t_tokens / elapsed_s, 1),
                # per-tenant percentiles from the tenant's OWN
                # histogram family — populations never merge
                "p50_ms": round(
                    trace.hist_percentile(fam, 0.50) / 1000.0, 3),
                "p99_ms": round(
                    trace.hist_percentile(fam, 0.99) / 1000.0, 3),
                "p99_exact_ms": round(_exact_p99(lat_ms), 3),
                # front-door accounting (0/0 without a door): every
                # shed eventually re-arrives, so shed <= retried at
                # drain time and completed == n_requests
                "shed": self._shed.get(name, 0),
                "retried": self._retried.get(name, 0),
            }
        # per-SLO-class rollup: latency populations from the done
        # requests' own class stamps, shed/retried attributed through
        # each tenant's effective submit class
        by_cls: dict = {}
        for r in done:
            by_cls.setdefault(r.slo or "unclassified", []).append(r)
        slo_classes = {}
        for cls, reqs in sorted(by_cls.items()):
            lat = sorted((r.done_ns - r.arrival_ns) / 1e6 for r in reqs
                         if r.done_ns is not None)
            slo_classes[cls] = {
                "requests": len(reqs),
                "tokens": sum(len(r.tokens) for r in reqs),
                "p50_ms": round(lat[len(lat) // 2], 3) if lat else 0.0,
                "p99_exact_ms": round(_exact_p99(lat), 3),
                "shed": 0, "retried": 0,
            }
        for name, info in self.tenants.items():
            cls = info.get("slo_used") or info.get("slo") \
                or "unclassified"
            if cls in slo_classes:
                slo_classes[cls]["shed"] += self._shed.get(name, 0)
                slo_classes[cls]["retried"] += \
                    self._retried.get(name, 0)
        prefills_now, hits_now = self._prefix_counts(fleet)
        prefills = prefills_now - prefills0
        hits = hits_now - hits0
        return {
            "requests": len(done),
            "elapsed_s": round(elapsed_s, 3),
            "tokens": int(tokens),
            "tokens_per_s": round(tokens / elapsed_s, 1),
            "req_per_s": round(len(done) / elapsed_s, 1),
            "tenants": per_tenant,
            "slo_classes": slo_classes,
            "shed": sum(self._shed.values()),
            "retried": sum(self._retried.values()),
            # the prefix-cache evidence: full prefill passes actually
            # computed vs worker-verified hits that skipped them
            "prefills": int(prefills),
            "prefix_hits": int(hits),
            "prefix_hit_rate": round(hits / (prefills + hits), 4)
            if (prefills + hits) else 0.0,
            "requeued": fleet.lost_and_requeued,
        }
