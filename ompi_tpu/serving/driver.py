"""serving/driver — the synthetic heavy-traffic driver.

Poisson arrivals (seeded exponential inter-arrival gaps) with mixed
prompt/decode lengths, fed into a :class:`~ompi_tpu.serving.router.
Router` in wall-clock time; the report reads p50/p99 request latency
out of the otpu-trace ``serve_request`` log2 histogram (the percentile
estimator of ``runtime/trace.py``) and computes tokens/sec from the
completed set — the serving benchmark surface ``bench.py --serving``
publishes, qualitatively different from the OSU-style sweeps (open-loop
offered load against a queueing system instead of a closed
request/reply ping-pong).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ompi_tpu.base.var import registry
from ompi_tpu.runtime import trace


class PoissonDriver:
    """Open-loop traffic: ``n_requests`` arrivals at ``rate_rps`` with
    prompt/decode lengths drawn uniformly from the given ranges."""

    def __init__(self, rate_rps: float = 200.0, n_requests: int = 64,
                 prompt_lens: tuple = (8, 64),
                 decode_lens: tuple = (4, 24), seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        self.n_requests = int(n_requests)
        gaps = rng.exponential(1.0 / float(rate_rps), self.n_requests)
        self.arrivals_s = np.cumsum(gaps)       # offsets from run start
        self.prompts = rng.integers(prompt_lens[0], prompt_lens[1] + 1,
                                    self.n_requests)
        self.decodes = rng.integers(decode_lens[0], decode_lens[1] + 1,
                                    self.n_requests)
        self._next = 0

    def due(self, elapsed_s: float) -> list:
        """(prompt_len, decode_len) pairs whose arrival time has come."""
        out = []
        while (self._next < self.n_requests
               and self.arrivals_s[self._next] <= elapsed_s):
            out.append((int(self.prompts[self._next]),
                        int(self.decodes[self._next])))
            self._next += 1
        return out

    @property
    def exhausted(self) -> bool:
        return self._next >= self.n_requests

    def run(self, router, max_wall_s: float = 120.0,
            tick_sleep_s: float = 0.0) -> dict:
        """Drive the router under this arrival process and report.

        Tracing is force-enabled for the run (the latency histogram IS
        the measurement instrument) and restored afterwards.
        """
        was_enabled = trace.enabled
        if not was_enabled:
            registry.set("otpu_trace_enable", True)
        # fresh percentile population: an earlier run in this process
        # must not bleed into this run's p50/p99
        trace.hist_reset("serve_request")
        t0 = time.perf_counter()
        try:
            while True:
                elapsed = time.perf_counter() - t0
                if elapsed > max_wall_s:
                    raise TimeoutError(
                        f"serving driver exceeded {max_wall_s}s with "
                        f"{len(router.completed())}/{self.n_requests} "
                        "requests complete")
                for prompt_len, decode_len in self.due(elapsed):
                    router.submit(prompt_len, decode_len)
                router.tick()
                if (self.exhausted and not router.sched.depth()
                        and not router.sched.running()):
                    break
                if tick_sleep_s:
                    time.sleep(tick_sleep_s)
            elapsed = time.perf_counter() - t0
            return self.report(router, elapsed)
        finally:
            if not was_enabled:
                registry.set("otpu_trace_enable", False)

    def report(self, router, elapsed_s: float) -> dict:
        done = router.completed()
        tokens = sum(len(r.tokens) for r in done)
        lat_ms = sorted((r.done_ns - r.arrival_ns) / 1e6 for r in done
                        if r.done_ns is not None)
        exact_p99 = lat_ms[min(len(lat_ms) - 1,
                               int(0.99 * len(lat_ms)))] if lat_ms else 0.0
        return {
            "requests": len(done),
            "elapsed_s": round(elapsed_s, 3),
            "tokens": int(tokens),
            "tokens_per_s": round(tokens / elapsed_s, 1),
            "req_per_s": round(len(done) / elapsed_s, 1),
            # the contract numbers: percentiles interpolated from the
            # otpu-trace log2 latency histogram
            "p50_ms": round(
                trace.hist_percentile("serve_request", 0.50) / 1000.0, 3),
            "p99_ms": round(
                trace.hist_percentile("serve_request", 0.99) / 1000.0, 3),
            # cross-check: exact p99 over the driver's own sample list
            # (the histogram estimate must sit within a log2 bin of it)
            "p99_exact_ms": round(exact_p99, 3),
            "requeued": router.lost_and_requeued,
        }
