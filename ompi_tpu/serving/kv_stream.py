"""serving/kv_stream — KV-cache slabs streamed prefill → decode over
MPI-4 partitioned persistent requests.

One stage pair (a prefill worker and its decode peer) shares a fixed
slab of ``slots`` KV blocks.  The pair binds the slab ONCE —
``Psend_init`` on the prefill side, ``Precv_init`` on the decode side —
and then runs one partitioned *epoch* per prefill micro-batch:

* the sender starts the epoch, writes each sequence's KV block into its
  assigned slot and releases it with ``Pready(slot)`` the moment that
  sequence's prefill finishes — transfer of finished sequences overlaps
  the prefill compute of the rest (the bucketed-gradient-overlap
  pattern of ``mca/part`` pointed at inference);
* slots not used by this micro-batch are flushed in one aggregated tail
  (``Pready_range`` + ``otpu_part_persist_min_partitions`` coalescing),
  which is what completes the epoch — MPI-4 partitioned semantics make
  the whole slab the message, so the slab should be sized to the batch;
* the receiver polls ``Parrived`` per slot (exact even when its
  partition count differs from the sender's — the byte-framed wire
  protocol counts arrival against RECEIVER partitions) and copies each
  block out before the next epoch overwrites the slab.

Epoch numbering is explicit and checked: the router stamps every
prefill micro-batch with the epoch index both sides must be on, so a
desync (a stage skipping a round) is a loud error, not silent
corruption — ``mca/part``'s epoch-stamped wire protocol underneath
already keeps a restarted sender's bytes out of the previous epoch.

**Quantized slabs** (``otpu_coll_quant_kv_codec``): with a codec, each
slot holds the coll/quant block-scale ENCODING of its KV block (int8 +
per-block f32 scales: ~3.9x smaller; bf16: 2x) over the SAME
partitioned persistent pairing — the slab is just bytes to ``mca/part``
— so a worker's fixed slab budget holds 2-4x more concurrent
sequences.  Both sides of a pairing must agree on the codec (they are
built from the same MCA var/config); the fleet's stale-hint guarantee
survives a codec change because the worker's PrefixStore bumps its
generation on ``set_codec`` — a hint minted against the old encoding
can only ever be a perf miss, never wrong KV.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.mca.coll import quant as quant_mod
from ompi_tpu.runtime import spc, trace


class _KvSlabBase:
    """Shared geometry of one stage pair's slab."""

    def __init__(self, slots: int, elems_per_slot: int,
                 codec: Optional[str] = None) -> None:
        if slots <= 0 or elems_per_slot <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           "KV slab needs positive slots/elems")
        self.slots = int(slots)
        self.elems_per_slot = int(elems_per_slot)
        # codec None = the MCA var's job-wide default; "" = raw f32
        self.codec = quant_mod.kv_codec() if codec is None \
            else str(codec or "")
        if self.codec:
            if self.codec not in quant_mod.CODECS:
                raise MpiError(
                    ErrorClass.ERR_ARG,
                    f"unknown KV slab codec {self.codec!r} (known: "
                    f"{', '.join(quant_mod.CODECS)})")
            self._block = quant_mod.block_elems()
            self.slot_nbytes = quant_mod.encoded_nbytes(
                self.elems_per_slot, self.codec, self._block)
            self.slab = np.zeros((self.slots, self.slot_nbytes),
                                 np.uint8)
        else:
            self._block = 0
            self.slot_nbytes = 4 * self.elems_per_slot
            self.slab = np.zeros((self.slots, self.elems_per_slot),
                                 np.float32)
        self.epoch = -1

    @property
    def capacity_multiplier(self) -> float:
        """How many more sequences a fixed byte budget holds under the
        codec (1.0 for raw slabs) — the users-per-chip multiplier the
        bench row pins."""
        return (4.0 * self.elems_per_slot) / self.slot_nbytes

    def _check_slot(self, slot: int) -> int:
        if not 0 <= int(slot) < self.slots:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"KV slot {slot} out of [0, {self.slots})")
        return int(slot)

    def _check_epoch(self, epoch: int) -> None:
        if int(epoch) != self.epoch:
            raise MpiError(
                ErrorClass.ERR_REQUEST,
                f"KV stream desync: asked for epoch {epoch} while the "
                f"slab is on epoch {self.epoch} — a stage skipped or "
                "repeated a prefill round")


class KvSlabSender(_KvSlabBase):
    """Prefill side of one stage pair."""

    def __init__(self, comm, peer: int, slots: int, elems_per_slot: int,
                 tag: int, codec: Optional[str] = None) -> None:
        super().__init__(slots, elems_per_slot, codec)
        self.req = comm.psend_init(self.slab, self.slots, dest=peer,
                                   tag=tag)
        self._readied: set = set()

    def begin_epoch(self, epoch: int) -> None:
        """Start partitioned epoch ``epoch`` (must be the successor of
        the previous one — both sides count rounds)."""
        if int(epoch) != self.epoch + 1:
            raise MpiError(
                ErrorClass.ERR_REQUEST,
                f"KV sender asked to begin epoch {epoch} after "
                f"{self.epoch} — epochs are consecutive")
        self.req.start()
        self.epoch = int(epoch)
        self._readied.clear()
        spc.record("serve_kv_epochs")

    def write_slot(self, slot: int, kv: np.ndarray) -> None:
        """Land one finished sequence's KV block in its slot (pad/trim
        to the slab row — a toy stand-in for paged KV layout).  With a
        codec armed the slot holds the block-scale ENCODING."""
        s = self._check_slot(slot)
        row = np.asarray(kv, np.float32).reshape(-1)
        n = min(row.size, self.elems_per_slot)
        if self.codec:
            full = np.zeros(self.elems_per_slot, np.float32)
            full[:n] = row[:n]
            self.slab[s, :] = quant_mod.encode_f32(full, self.codec,
                                                   self._block)
            return
        self.slab[s, :n] = row[:n]
        self.slab[s, n:] = 0.0

    def slot_ready(self, slot: int, rid: Optional[int] = None) -> None:
        """``Pready`` for one finished sequence — its block starts
        travelling while later sequences are still prefilling.  With a
        ``rid`` (otpu-req armed) the Pready doubles as the producing
        half of the request's hop-1 flow edge: the per-sequence
        partition key the slab already carries IS the causal link
        prefill -> decode, so the arrow costs one ring slot, no wire
        bytes."""
        s = self._check_slot(slot)
        self.req.pready(s)
        self._readied.add(s)
        if rid is not None:
            trace.flow_start("serve_req", (rid, 1))

    def finish_epoch(self, wait: bool = True) -> None:
        """Flush the unused remainder of the slab (one aggregated tail
        run — ``Pready_list``; the final ready force-flushes contiguous
        runs as single wire messages) to complete the epoch; ``wait``
        blocks until every block is on the wire."""
        self.req.pready_list([s for s in range(self.slots)
                              if s not in self._readied])
        self._readied.update(range(self.slots))
        if wait:
            self.req.wait()

    def free(self) -> None:
        self.req.free()


class KvSlabReceiver(_KvSlabBase):
    """Decode side of one stage pair.

    ``partitions`` may exceed the sender's slot count (any multiple of
    ``slots``): arrival is then tracked at sub-slot granularity and
    :meth:`slot_arrived` maps a slot onto its RUN of receiver
    partitions — the mismatched-partition-count exactness of
    ``mca/part``'s byte-framed protocol, which the serving tests pin.
    """

    def __init__(self, comm, peer: int, slots: int, elems_per_slot: int,
                 tag: int, partitions: Optional[int] = None,
                 codec: Optional[str] = None) -> None:
        super().__init__(slots, elems_per_slot, codec)
        self.partitions = int(partitions) if partitions else self.slots
        if self.partitions % self.slots:
            raise MpiError(
                ErrorClass.ERR_ARG,
                f"{self.partitions} receiver partitions do not tile "
                f"{self.slots} KV slots")
        self._parts_per_slot = self.partitions // self.slots
        self.req = comm.precv_init(self.slab, self.partitions,
                                   source=peer, tag=tag)

    def begin_epoch(self, epoch: int) -> None:
        if int(epoch) != self.epoch + 1:
            raise MpiError(
                ErrorClass.ERR_REQUEST,
                f"KV receiver asked to begin epoch {epoch} after "
                f"{self.epoch} — epochs are consecutive")
        self.req.start()
        self.epoch = int(epoch)

    def slot_arrived(self, slot: int) -> bool:
        """Has this sequence's whole block landed (all of the slot's
        receiver partitions, exact under mismatched counts)?"""
        s = self._check_slot(slot)
        lo = s * self._parts_per_slot
        return self.req.parrived_range(lo, lo + self._parts_per_slot - 1)

    def read_slot(self, slot: int,
                  rid: Optional[int] = None) -> np.ndarray:
        """COPY one arrived block out — the next epoch reuses the slab,
        so decode state must not alias it.  With a codec armed the
        block is dequantized here (the decode owns its memory).  A
        ``rid`` closes the request's hop-1 flow edge (the consuming
        half of the arrow :meth:`KvSlabSender.slot_ready` launched)."""
        s = self._check_slot(slot)
        if not self.slot_arrived(s):
            raise MpiError(ErrorClass.ERR_REQUEST,
                           f"KV slot {s} read before it arrived "
                           f"(epoch {self.epoch})")
        if rid is not None:
            trace.flow_finish("serve_req", (rid, 1))
        if self.codec:
            return quant_mod.decode_f32(self.slab[s], self.codec,
                                        self.elems_per_slot,
                                        self._block)
        return self.slab[s].copy()

    def finish_epoch(self) -> None:
        """Block until the whole slab (the epoch's tail flush included)
        has landed — after this the sender may begin the next epoch."""
        self.req.wait()

    def free(self) -> None:
        self.req.free()
