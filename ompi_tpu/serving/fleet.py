"""serving/fleet — the multi-tenant serving platform control plane.

PR 8 built one router + one worker pool for one model.  A
million-user frontier is a *fleet*: several models and tenants sharing
the TPU workers of one job.  This module is the control plane that
composes the machinery the earlier PRs built into that story:

* **named per-model pools** — the fleet partitions the worker ranks
  into pools (one :class:`~ompi_tpu.serving.router.Router` each, all
  on the SHARED communicator), published as ``mpi://serving/pool/
  <model>`` process sets (``tpurun --pool model:ranks`` pre-publishes
  them; :func:`pool_specs_from_psets` resolves placement the way
  ``roles()`` resolves the router).  A pool's prefill and decode
  stages are sized independently (``prefill=``/``decode=`` of
  :class:`PoolSpec` — a prefill rank streams KV slabs to every decode
  rank mapped onto it);
* **fair-share admission** — every request carries a tenant; each
  pool's scheduler runs strict FIFO within a tenant and weighted
  round-robin across tenants (the checkable no-starvation guarantee of
  ``scheduler.py``), so one tenant's burst cannot starve another;
* **prefix-cache-aware routing** — each pool owns a
  :class:`~ompi_tpu.serving.prefix_cache.PrefixRegistry`; requests
  whose prompt shares a registered prefix route to the worker already
  holding those KV blocks and skip the prefill (worker-verified
  generation — stale entries are perf misses, never correctness bugs);
* **telemetry-driven autoscaling** — :class:`FleetAutoscaler` replaces
  the queue-depth watermark with a policy loop over
  ``runtime/telemetry.py`` samples: per-pool scheduler depth, the
  per-pool interval p99 out of the sample's histogram deltas (the SLO
  signal), and stale-rank flags (a worker whose sample seq stopped
  advancing).  Scale-up enlists a parked reserve rank when one exists
  and otherwise spawns a fresh worker via ``dpm.spawn`` (verified
  against the dynamic ``mpi://job/<id>`` pset, merged parents-first);
  scale-down drains an idle worker, removes it from the pool pset and
  parks it in the reserve — the rank stays in the communicator
  (collectives like the next spawn still include it) but holds no pool
  work, modelling released capacity.  Cooldown and the max-workers cap
  are **per pool**: model A absorbing its scale-up must not block a
  needed spawn for model B;
* **one recovery** — pool routers run ``manage_recovery=False``: a
  worker death anywhere revokes the shared comm ONCE, the fleet
  shrinks it once, recomputes every pool's table from surviving world
  ranks, invalidates the prefix registries, and requeues in-flight
  requests — zero admitted requests dropped, fleet-wide.

Everything the fleet decides publishes through the telemetry ``fleet``
SCHEMA key (pool tables, prefix hit/miss, autoscale decisions) so
``otpu_top`` and ``otpu_analyze`` see the fleet live, and every scale
decision lands in the otpu-trace ring as a ``fleet_scale`` instant
naming its driving signal.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Optional

from ompi_tpu.api.errhandler import ERRORS_RETURN
from ompi_tpu.api.errors import (ErrorClass, MpiError, ProcFailedError,
                                 RevokedError)
from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import spc, trace
from ompi_tpu.serving.prefix_cache import PrefixRegistry
from ompi_tpu.serving.router import (POOL_HIST_PREFIX, Router)
from ompi_tpu.serving.scheduler import ContinuousBatchScheduler
from ompi_tpu.serving.worker import TAG_CMD

#: pool process sets: ``mpi://serving/pool/<model>`` (tpurun --pool)
PSET_POOL_PREFIX = "mpi://serving/pool/"

_cooldown_var = registry.register(
    "serving", None, "scale_cooldown", vtype=VarType.INT, default=8,
    help="Autoscale cooldown in policy evaluations, tracked PER POOL: "
         "after a pool scales, that pool sits out this many policy "
         "steps so the change can absorb — other pools' decisions are "
         "never blocked by it")
_patience_var = registry.register(
    "serving", None, "scale_patience", vtype=VarType.INT, default=3,
    help="Consecutive policy evaluations a pool's queue depth must "
         "exceed the high watermark before a depth-driven scale-up")
_slo_var = registry.register(
    "serving", None, "slo_p99_ms", vtype=VarType.FLOAT, default=0.0,
    help="Per-pool p99 request-latency SLO in milliseconds, read from "
         "the live telemetry sample's per-pool histogram delta; an "
         "interval p99 above it triggers a telemetry-driven scale-up. "
         "0 (the default) disables the SLO signal")
_idle_var = registry.register(
    "serving", None, "idle_patience", vtype=VarType.INT, default=50,
    help="Consecutive policy evaluations a pool must be completely "
         "idle (no queue, no running requests) before one worker is "
         "drained and parked in the reserve")
_poll_var = registry.register(
    "serving", None, "poll_ticks", vtype=VarType.INT, default=25,
    help="Engine ticks between autoscaler policy evaluations (each "
         "evaluation polls the telemetry samples once)")


class PoolSpec:
    """Static description of one per-model pool.

    ``workers`` are communicator ranks; ``prefill``/``decode`` split
    them into independently sized stage pools (omit both for colocated
    serving).  Scheduler budgets are per pool — two models share the
    job but never a batch."""

    def __init__(self, name: str, workers, prefill=None, decode=None,
                 max_batch: int = 8, max_batch_tokens: int = 1 << 14,
                 slots: Optional[int] = None, decode_chunk: int = 4,
                 kv_elems: int = 256, experts: int = 0) -> None:
        self.name = str(name)
        self.workers = [int(w) for w in workers]
        if not self.workers:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"pool {name!r} needs at least one worker")
        self.prefill = [int(w) for w in prefill] if prefill else None
        self.decode = [int(w) for w in decode] if decode else None
        if (self.prefill is None) != (self.decode is None):
            raise MpiError(ErrorClass.ERR_ARG,
                           f"pool {name!r}: prefill and decode pools "
                           "must be given together")
        self.max_batch = int(max_batch)
        self.max_batch_tokens = int(max_batch_tokens)
        self.slots = slots
        self.decode_chunk = int(decode_chunk)
        self.kv_elems = int(kv_elems)
        #: > 0: an expert-sharded MoE decode pool — each decode worker
        #: homes a contiguous expert range (parallel/moe sharding) and
        #: the router prefers a request's expert home on prefix miss
        self.experts = int(experts)


def pool_specs_from_psets(comm) -> list:
    """Resolve :class:`PoolSpec` tables from the published
    ``mpi://serving/pool/<model>`` process sets (``tpurun --pool``),
    world ranks mapped into ``comm`` — the pset-driven placement path,
    mirroring :func:`ompi_tpu.serving.roles`."""
    client = getattr(comm.rte, "client", None)
    if client is None:
        return []
    try:
        names = [r["name"] for r in client.pset_list()
                 if str(r["name"]).startswith(PSET_POOL_PREFIX)]
    except Exception:
        return []
    in_comm = {w: i for i, w in enumerate(comm.group.world_ranks)}
    specs = []
    for pname in sorted(names):
        entry = client.pset_get(pname)
        members = sorted(in_comm[int(m)] for m in entry["members"]
                         if int(m) in in_comm)
        if members:
            specs.append(PoolSpec(pname[len(PSET_POOL_PREFIX):],
                                  members))
    return specs


class FleetController:
    """The fleet control plane (see module doc): per-model pools over
    one shared communicator, fair-share tenant admission, prefix-aware
    routing, one recovery, and the telemetry autoscaler.

    Pool/reserve tables are mutated on the engine-tick thread and
    snapshotted by the telemetry sampler thread through :meth:`stats`
    — the mutable tables are declared ``_guarded_by`` the fleet lock
    (sends never happen under it)."""

    _guarded_by = {"_pool_world": "_lock", "_reserve": "_lock",
                   "_decision_log": "_lock"}

    def __init__(self, comm, pools: Optional[list] = None,
                 tenants: Optional[dict] = None,
                 spawn_argv: Optional[list] = None,
                 autoscale: Optional[dict] = None,
                 frontdoor: Optional[dict] = None,
                 publish_psets: bool = True) -> None:
        comm.set_errhandler(ERRORS_RETURN)
        self.comm = comm
        if pools is None:
            pools = pool_specs_from_psets(comm)
        if not pools:
            raise MpiError(ErrorClass.ERR_ARG,
                           "fleet needs at least one pool (explicit "
                           "PoolSpec list, or tpurun --pool psets)")
        seen: set = set()
        for spec in pools:
            overlap = seen & set(spec.workers)
            if overlap:
                raise MpiError(ErrorClass.ERR_ARG,
                               f"pool {spec.name!r} shares workers "
                               f"{sorted(overlap)} with another pool")
            seen |= set(spec.workers)
        self.tenants = dict(tenants) if tenants else None
        self.spawn_argv = list(spawn_argv) if spawn_argv else None
        self._lock = threading.Lock()
        self._specs = {s.name: s for s in pools}
        self.routers: dict = {}
        #: pool membership in WORLD ranks — the stable identity across
        #: shrinks and merges (comm ranks are recomputed from it)
        self._pool_world: dict = {}
        self._reserve: list = []       # parked world ranks (capacity)
        self._decision_log: collections.deque = collections.deque(
            maxlen=64)
        self._lost_and_requeued = 0
        for spec in pools:
            reg = PrefixRegistry()
            sched = ContinuousBatchScheduler(
                max_batch=spec.max_batch,
                max_batch_tokens=spec.max_batch_tokens,
                slots=spec.slots, tenants=self.tenants)
            self.routers[spec.name] = Router(
                comm, scheduler=sched, workers=spec.workers,
                prefill_ranks=spec.prefill, decode_ranks=spec.decode,
                prefix_registry=reg, pool=spec.name,
                experts=spec.experts,
                manage_recovery=False, decode_chunk=spec.decode_chunk,
                kv_elems=spec.kv_elems)
            with self._lock:
                self._pool_world[spec.name] = [
                    int(comm.group.world_rank(w)) for w in spec.workers]
        self.me = next(iter(self.routers.values())).me
        self._publish = bool(publish_psets)
        self._publish_pool_psets()
        self.autoscaler = FleetAutoscaler(self, **(autoscale or {}))
        #: the admission plane is strictly opt-in (a kwargs dict, {} for
        #: defaults): with frontdoor=None nothing here runs, no queue
        #: objects exist, and frontdoor.enabled stays False — the
        #: disabled-is-identity pin in test_perf_guard
        self.frontdoor = None
        if frontdoor is not None:
            from ompi_tpu.serving.frontdoor import FrontDoor

            self.frontdoor = FrontDoor(self.routers, **frontdoor)
        from ompi_tpu.runtime import telemetry

        telemetry.register_source("fleet", self.stats)

    # -- placement ---------------------------------------------------------
    def _publish_pool_psets(self) -> None:
        """(Re-)advertise every pool's world-rank membership as its
        ``mpi://serving/pool/<model>`` pset — the leave-pset half of
        retirement and the join half of a scale-up both land here."""
        if not self._publish:
            return
        client = getattr(self.comm.rte, "client", None)
        if client is None:
            return
        with self._lock:
            snapshot = {n: list(m) for n, m in self._pool_world.items()}
        for name, members in snapshot.items():
            try:
                client.pset_publish(PSET_POOL_PREFIX + name, members,
                                    source="user")
            except Exception:
                return                 # coord gone: psets are advisory

    def _comm_rank_of(self, world_rank: int) -> Optional[int]:
        try:
            return self.comm.group.world_ranks.index(int(world_rank))
        except ValueError:
            return None

    def pool_workers(self) -> dict:
        """{pool: [comm ranks]} snapshot (tests, stats)."""
        return {name: list(r.workers) for name, r in self.routers.items()}

    # -- public API --------------------------------------------------------
    def submit(self, tenant: str, model: str, prompt_len: int = 0,
               max_new_tokens: int = 8, prompt=None, rid=None,
               slo: str = ""):
        """Admit one request for ``tenant`` against ``model``'s pool
        (fair-share queued; prompt tokens, when given, feed the
        prefix-cache router).  This path bypasses the front door even
        when one is armed — callers who want admission control submit
        via ``fleet.frontdoor.submit`` and honor its Decision."""
        router = self.routers.get(str(model))
        if router is None:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"no serving pool for model {model!r} "
                           f"(pools: {sorted(self.routers)})")
        return router.submit(prompt_len or 0, max_new_tokens,
                             rid=rid, tenant=tenant, prompt=prompt,
                             slo=slo)

    def completed(self) -> list:
        out = []
        for router in self.routers.values():
            out.extend(router.completed())
        return out

    @property
    def lost_and_requeued(self) -> int:
        return self._lost_and_requeued + sum(
            r.lost_and_requeued for r in self.routers.values())

    def depth(self) -> int:
        return sum(r.sched.depth() for r in self.routers.values())

    def running(self) -> list:
        out = []
        for router in self.routers.values():
            out.extend(router.sched.running())
        return out

    def tick(self) -> None:
        """One fleet engine tick: every pool router ticks, then the
        autoscaler evaluates.  Any ULFM error anywhere routes through
        the ONE shared recovery."""
        try:
            if self.frontdoor is not None:
                # admission first: forwards land before this tick's
                # admit round, and the breach ladder sees last tick's
                # completions
                self.frontdoor.pump()
            for router in self.routers.values():
                router.tick()
            self.autoscaler.step()
        except (RevokedError, ProcFailedError):
            self._recover()

    def serve_until_drained(self, max_ticks: int = 100000,
                            check_invariants: bool = False) -> list:
        ticks = 0
        while True:
            busy = any(r.sched.depth() or r.sched.running()
                       for r in self.routers.values())
            if self.frontdoor is not None and self.frontdoor.depth():
                busy = True        # door-held work still needs forwarding
            if not busy:
                break
            self.tick()
            if check_invariants:
                for router in self.routers.values():
                    router.sched.check_invariants()
                if self.frontdoor is not None:
                    self.frontdoor.check_invariants()
            ticks += 1
            if ticks >= max_ticks:
                raise MpiError(ErrorClass.ERR_INTERN,
                               f"fleet did not drain in {max_ticks} "
                               "ticks (a request starved)")
        return self.completed()

    def shutdown(self) -> None:
        """Stop every worker this fleet can reach — pool members AND
        parked reserve ranks (they idle on the same serve loop)."""
        if self.frontdoor is not None:
            self.frontdoor.close()
        with self._lock:
            reserve = list(self._reserve)
        targets = set()
        for router in self.routers.values():
            targets.update(router.workers)
        for wr in reserve:
            cr = self._comm_rank_of(wr)
            if cr is not None:
                targets.add(cr)
        for w in sorted(targets):
            try:
                self.comm.send_obj(("stop",), w, TAG_CMD)
            except MpiError:
                pass

    # -- recovery (ONE shrink for the whole fleet) -------------------------
    def _recover(self) -> None:
        """Fleet-wide serve-through-failure: revoke + shrink the shared
        comm exactly once, recompute every pool (and the reserve) from
        the surviving world ranks, rebind every router (which
        invalidates its prefix registry and requeues its in-flight
        requests), re-publish the pool psets."""
        try:
            self.comm.revoke()
        except MpiError:
            pass
        new = self.comm.shrink()
        new.set_errhandler(ERRORS_RETURN)
        self.comm = new
        surviving = {int(w): i for i, w in
                     enumerate(new.group.world_ranks)}
        with self._lock:
            for name in self._pool_world:
                self._pool_world[name] = [
                    wr for wr in self._pool_world[name]
                    if wr in surviving]
            self._reserve = [wr for wr in self._reserve
                             if wr in surviving]
            tables = {name: [surviving[wr] for wr in members]
                      for name, members in self._pool_world.items()}
        for name, router in self.routers.items():
            if not tables[name]:
                raise MpiError(
                    ErrorClass.ERR_PROC_FAILED,
                    f"pool {name!r} lost its last worker — the fleet "
                    "cannot serve this model (scale it up first)")
            router.rebind(new, tables[name])
        self.me = next(iter(self.routers.values())).me
        self._publish_pool_psets()

    # -- capacity changes (autoscaler actions) -----------------------------
    def enlist(self, pool: str) -> Optional[int]:
        """Scale-up from the parked reserve: move one reserve rank into
        ``pool``'s table (cheap — no spawn, the rank is already in the
        communicator idling on its serve loop)."""
        with self._lock:
            while self._reserve:
                wr = self._reserve.pop(0)
                cr = self._comm_rank_of(wr)
                if cr is None:
                    continue           # died while parked
                self._pool_world[pool].append(wr)
                break
            else:
                return None
        router = self.routers[pool]
        router.workers = sorted(set(router.workers) | {cr})
        spc.record("serve_enlists")
        self._publish_pool_psets()
        return cr

    def retire(self, pool: str) -> Optional[int]:
        """Scale-down: drain → leave pset → park.  Picks a pool worker
        with nothing running (drained by construction — the policy only
        retires from an idle pool), removes it from the pool table and
        pset, invalidates its prefix-registry entries, and parks its
        rank in the reserve.  The rank stays in the communicator —
        collectives (the next spawn) still include it — but holds no
        pool work: released capacity, re-enlistable for free.

        Stage pools retire STAGE-AWARE: colocated extras go first,
        then the larger of the two stage pools, and the last prefill
        or last decode rank is never taken — removing either would
        wedge the pool with live workers still in it."""
        router = self.routers[pool]
        busy = {r.worker for r in router.sched.running()}
        candidates = [w for w in router.workers if w not in busy]
        if router.stages:
            pre, dec, extra = router._stage_split()
            keep = set()                       # never-take set
            if len(pre) <= 1:
                keep.update(pre)
            if len(dec) <= 1:
                keep.update(dec)
            larger = dec if len(dec) >= len(pre) else pre
            # preference order: colocated extras, then the larger
            # stage pool's newest rank, then anything else legal
            candidates = (
                [w for w in extra if w in candidates]
                + [w for w in reversed(larger)
                   if w in candidates and w not in keep]
                + [w for w in candidates
                   if w not in extra and w not in larger
                   and w not in keep])
            if not candidates:
                return None
            victim = candidates[0]
        else:
            if not candidates or len(router.workers) <= 1:
                return None
            victim = candidates[-1]    # newest-joined rank leaves first
        router.workers = [w for w in router.workers if w != victim]
        if router.registry is not None:
            router.registry.invalidate_worker(victim)
        wr = int(self.comm.group.world_rank(victim))
        with self._lock:
            self._pool_world[pool] = [w for w in self._pool_world[pool]
                                      if w != wr]
            self._reserve.append(wr)
        spc.record("serve_scaledowns")
        self._publish_pool_psets()
        return victim

    def spawn_into(self, pool: str, n: int = 1) -> list:
        """Scale-up by process spawn: every live rank in the shared
        comm participates in ``MPI_Comm_spawn`` (told via a ``scale``
        command this tick), the children are verified against the
        dynamic ``mpi://job/<id>`` pset, merged parents-first (every
        existing rank keeps its rank), and the fresh ranks join
        ``pool``'s table and pset."""
        if self.spawn_argv is None:
            return []
        argv = self.spawn_argv
        targets = set()
        for router in self.routers.values():
            targets.update(router.workers)
        with self._lock:
            for wr in self._reserve:
                cr = self._comm_rank_of(wr)
                if cr is not None:
                    targets.add(cr)
        for w in sorted(targets):
            self.comm.send_obj(("scale", argv, n), w, TAG_CMD)
        inter = self.comm.spawn(argv, n, root=self.me)
        client = getattr(self.comm.rte, "client", None)
        job = getattr(inter, "spawn_job", None)
        if client is not None and job is not None:
            entry = client.pset_get(f"mpi://job/{job}")
            members = sorted(int(m) for m in entry["members"])
            if members != sorted(inter.remote_group.world_ranks):
                raise MpiError(
                    ErrorClass.ERR_SPAWN,
                    f"mpi://job/{job} pset {members} does not match "
                    "the spawned intercomm")
        full = inter.merge(high=False)
        full.set_errhandler(ERRORS_RETURN)
        self.comm = full
        for router in self.routers.values():
            router.comm = full         # ranks preserved: tables stand
        new_ranks = list(range(full.size - n, full.size))
        router = self.routers[pool]
        router.workers = sorted(set(router.workers) | set(new_ranks))
        with self._lock:
            self._pool_world[pool].extend(
                int(full.group.world_rank(r)) for r in new_ranks)
        spc.record("serve_scaleups")
        self._publish_pool_psets()
        return new_ranks

    # -- observability -----------------------------------------------------
    def note_decision(self, decision: dict) -> None:
        with self._lock:
            self._decision_log.append(decision)

    def stats(self) -> Optional[dict]:
        """The telemetry ``fleet`` source: pool tables + queue depths,
        prefix-registry hit/miss, reserve size, recent autoscale
        decisions.  Called on the sampler thread — everything it reads
        is either under the fleet lock or a locked snapshot of its
        own."""
        pools = {}
        for name, router in self.routers.items():
            st = router.sched.stats()
            entry = {"workers": len(router.workers),
                     "queued": st["queued"],
                     "running": st["running"],
                     "prefills": router.prefill_count,
                     "prefix_hits": router.prefix_hit_count}
            if "tenants" in st:
                entry["tenants"] = st["tenants"]
            if router.registry is not None:
                entry["prefix"] = router.registry.stats()
            if router.experts:
                # expert placement snapshot: {expert: home worker} —
                # recomputed from the live table, so a shrink shows
                # the re-shard here immediately
                entry["experts"] = {str(e): w for e, w in
                                    router.expert_table().items()}
            pools[name] = entry
        # otpu-req SLO plane: fold each pool's worst-tenant burn rate
        # into its entry (the controller rank runs every router, so
        # its SLO accountant holds every pool's rolling window)
        from ompi_tpu.runtime import telemetry

        slo = telemetry.slo_snapshot()
        if slo:
            for name, tenants in (slo.get("pools") or {}).items():
                entry = pools.get(name)
                if entry is not None and tenants:
                    entry["slo_burn"] = max(
                        float(t.get("burn", 0.0))
                        for t in tenants.values())
        with self._lock:
            reserve = len(self._reserve)
            decisions = list(self._decision_log)[-8:]
        return {"pools": pools, "reserve": reserve,
                "decisions": decisions,
                "autoscale": self.autoscaler.stats()}


class FleetAutoscaler:
    """The telemetry-driven scaling policy (see module doc).

    Every ``poll_ticks`` engine ticks the policy polls one round of
    telemetry samples — from the coordination-service KV when the job
    has one (each rank's sampler publishes there; the same data
    ``otpu_top`` renders), else from an in-process sampler snapshot —
    and evaluates each pool against three signals, most urgent first:

    1. **p99 SLO** (telemetry): the pool's interval p99 out of the
       router rank sample's ``serve_pool_<model>`` histogram delta
       exceeds ``slo_p99_ms``;
    2. **stale rank** (telemetry): a pool worker's sample seq stopped
       advancing — wedged or dying; capacity is added ahead of the
       failure detector's verdict;
    3. **queue depth** (the legacy watermark, now per pool): depth
       above ``depth_high`` for ``patience`` consecutive evaluations.

    Cooldown and the max-workers cap are tracked PER POOL — one pool
    absorbing its scale-up never blocks another pool's needed spawn.
    Scale-down: a pool completely idle for ``idle_patience``
    evaluations drains one worker into the shared reserve."""

    def __init__(self, fleet: FleetController,
                 depth_high: Optional[int] = None,
                 patience: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 cooldown: Optional[int] = None,
                 max_workers=None,
                 min_workers: int = 1,
                 idle_patience: Optional[int] = None,
                 poll_ticks: Optional[int] = None,
                 watch_stale: bool = True) -> None:
        self.fleet = fleet
        self.depth_high = depth_high
        self.patience = int(patience if patience is not None
                            else _patience_var.value or 3)
        self.slo_p99_ms = float(slo_p99_ms if slo_p99_ms is not None
                                else _slo_var.value or 0.0)
        self.cooldown = int(cooldown if cooldown is not None
                            else _cooldown_var.value or 8)
        #: per-pool cap: int applies to every pool, dict per pool
        self._max_workers = max_workers
        self.min_workers = int(min_workers)
        self.idle_patience = int(idle_patience if idle_patience
                                 is not None else _idle_var.value or 50)
        self.poll_ticks = max(1, int(poll_ticks if poll_ticks
                                     is not None
                                     else _poll_var.value or 25))
        self.watch_stale = bool(watch_stale)
        self._tick = 0
        self._cooling: dict = {}       # pool -> evaluations left
        self._over: dict = {}          # pool -> consecutive deep polls
        self._idle: dict = {}          # pool -> consecutive idle polls
        self._ups = 0
        self._downs = 0
        self._last_signal: Optional[str] = None
        self._local_sampler = None
        self._seq_seen: dict = {}      # world rank -> (seq, monotonic)

    def max_workers_of(self, pool: str) -> Optional[int]:
        if isinstance(self._max_workers, dict):
            return self._max_workers.get(pool)
        return self._max_workers

    def stats(self) -> dict:
        return {"ups": self._ups, "downs": self._downs,
                "last_signal": self._last_signal,
                "cooling": {p: c for p, c in self._cooling.items()
                            if c > 0}}

    # -- telemetry input ---------------------------------------------------
    def _poll_samples(self) -> dict:
        """{world rank: latest telemetry sample}.  Inside a job the
        coord KV has every rank's published sample (the otpu_top
        surface); without a coord service an in-process sampler
        snapshot stands in — same schema, local ranks only."""
        from ompi_tpu.runtime import telemetry

        client = getattr(self.fleet.comm.rte, "client", None)
        if client is not None:
            import json

            out = {}
            for wr in self.fleet.comm.group.world_ranks:
                try:
                    raw = client.get(int(wr), telemetry._KV_KEY,
                                     wait=False)
                except Exception:
                    return {}
                if raw:
                    try:
                        out[int(wr)] = json.loads(raw)
                    except (TypeError, ValueError):
                        pass
            return out
        if self._local_sampler is None:
            rank = int(getattr(self.fleet.comm.rte, "my_world_rank", 0)
                       or 0)
            self._local_sampler = telemetry.Sampler(rank, 1)
        sample = self._local_sampler._sample_once()
        return {sample["rank"]: sample}

    def _stale_ranks(self, samples: dict) -> set:
        """World ranks whose sample seq stopped advancing for longer
        than 3 of their own sampling intervals — wedged, dying, or
        their sampler lost the coord (the otpu_top staleness rule)."""
        if not self.watch_stale:
            return set()
        now = time.monotonic()
        stale: set = set()
        for wr, sample in samples.items():
            seq = int(sample.get("seq", 0))
            iv_s = max(0.05,
                       float(sample.get("interval_ms") or 0) / 1e3)
            last = self._seq_seen.get(wr)
            if last is None or last[0] != seq:
                self._seq_seen[wr] = (seq, now)
                continue
            if now - last[1] > 3 * iv_s:
                stale.add(wr)
        return stale

    def _pool_p99_ms(self, name: str, samples: dict) -> float:
        """The pool's interval p99 (ms) from the ROUTER rank's sample
        histogram delta — the per-coll p99 signal of the live plane."""
        me_world = None
        try:
            me_world = int(self.fleet.comm.group.world_rank(
                self.fleet.me))
        except Exception:
            pass
        sample = samples.get(me_world)
        if sample is None and samples:
            sample = next(iter(samples.values()))
        if not sample:
            return 0.0
        cell = (sample.get("hist") or {}).get(POOL_HIST_PREFIX + name)
        if not cell:
            return 0.0
        return float(cell.get("p99_us", 0.0)) / 1000.0

    # -- the policy loop ---------------------------------------------------
    def step(self) -> None:
        """Called once per fleet tick; evaluates every ``poll_ticks``."""
        self._tick += 1
        if self._tick % self.poll_ticks:
            return
        samples = self._poll_samples()
        stale = self._stale_ranks(samples)
        for name, router in self.fleet.routers.items():
            self._evaluate(name, router, samples, stale)

    def _evaluate(self, name: str, router, samples: dict,
                  stale: set) -> None:
        cooling = self._cooling.get(name, 0)
        if cooling > 0:
            # PER-POOL cooldown: only THIS pool sits the round out
            self._cooling[name] = cooling - 1
            return
        st = router.sched.stats()
        depth, running = st["queued"], st["running"]

        # ---- scale up (signals most-urgent first) ----
        signal, value = None, 0.0
        p99 = self._pool_p99_ms(name, samples)
        if self.slo_p99_ms > 0 and p99 > self.slo_p99_ms:
            signal, value = "p99", p99
        if signal is None and stale:
            pool_world = {int(self.fleet.comm.group.world_rank(w))
                          for w in router.workers}
            wedged = stale & pool_world
            if wedged:
                signal, value = "stale_rank", float(len(wedged))
        if signal is None and self.depth_high is not None:
            if depth > self.depth_high:
                self._over[name] = self._over.get(name, 0) + 1
                if self._over[name] >= self.patience:
                    signal, value = "depth", float(depth)
            else:
                self._over[name] = 0
        if signal is not None:
            self._over[name] = 0
            self._idle[name] = 0
            cap = self.max_workers_of(name)
            if cap is not None and len(router.workers) >= cap:
                return                 # per-pool cap: full, stay put
            self._scale_up(name, signal, value)
            return

        # ---- scale down (drain an idle pool into the reserve) ----
        if depth == 0 and running == 0:
            self._idle[name] = self._idle.get(name, 0) + 1
            if (self._idle[name] >= self.idle_patience
                    and len(router.workers) > self.min_workers):
                self._idle[name] = 0
                victim = self.fleet.retire(name)
                if victim is not None:
                    self._downs += 1
                    self._note(name, "down", "idle", float(victim))
                    self._cooling[name] = self.cooldown
        else:
            self._idle[name] = 0

    def _scale_up(self, name: str, signal: str, value: float) -> None:
        added = self.fleet.enlist(name)
        how = "enlist"
        if added is None:
            spawned = self.fleet.spawn_into(name, 1)
            if not spawned:
                return                 # no reserve, no spawn path
            added = spawned[0]
            how = "spawn"
        self._ups += 1
        self._cooling[name] = self.cooldown
        self._note(name, "up", signal, value, how=how, rank=added)

    def _note(self, pool: str, direction: str, signal: str,
              value: float, **extra) -> None:
        """Record one decision everywhere the acceptance looks: the
        otpu-trace ring (a ``fleet_scale`` instant naming the driving
        signal), the fleet's bounded decision log (telemetry sample),
        and the autoscaler's own counters."""
        self._last_signal = signal
        decision = {"pool": pool, "dir": direction, "signal": signal,
                    "value": round(float(value), 3)}
        decision.update(extra)
        trace.instant("fleet_scale", "serving", dict(decision))
        self.fleet.note_decision(decision)
