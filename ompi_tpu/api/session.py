"""MPI-4 Sessions (``ompi/mpi/c/session_*.c`` + ``ompi/instance``).

A Session is an application-visible handle on the runtime instance: it
can be opened WITHOUT ``MPI_Init``, enumerates the process sets the
runtime advertises, and seeds the sessions-model communicator
construction chain::

    s = Session.init()
    g = s.group_from_pset("mpi://WORLD")     # MPI_Group_from_session_pset
    comm = Comm.create_from_group(g, "app")  # MPI_Comm_create_from_group

Each open session holds one reference on the underlying instance
(:mod:`ompi_tpu.instance`), so any number of sessions and the world
model share a single RTE/coord boot, and the runtime only tears down
when the last of them is gone.  Per MPI-4, a session's communicators
remain independent objects: finalizing the session that created a
communicator does not invalidate the communicator (the instance — kept
alive by nothing once all refs drop — is what actually owns the RTE).
"""
from __future__ import annotations

import threading
from typing import Optional

from ompi_tpu.api.errhandler import ERRORS_ARE_FATAL, Errhandler
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.info import Info


class Session:
    """``MPI_Session``: init/finalize, errhandler + info, pset queries."""

    _count = 0
    _count_lock = threading.Lock()

    def __init__(self, instance, info: Optional[Info],
                 errhandler: Optional[Errhandler]) -> None:
        self._instance = instance
        self._finalized = False
        self.info = (info or Info()).dup()
        self.errhandler = errhandler or ERRORS_ARE_FATAL
        with Session._count_lock:
            Session._count += 1
            self.name = f"session#{Session._count}"

    # -- lifecycle -------------------------------------------------------
    @classmethod
    def init(cls, info: Optional[Info] = None,
             errhandler: Optional[Errhandler] = None,
             argv: Optional[list] = None) -> "Session":
        """``MPI_Session_init``: open a session, booting the runtime
        instance if this is the first reference (no MPI_Init needed —
        sessions ARE the boot path; world init is just the implicit
        default session)."""
        from ompi_tpu import instance as inst_mod

        return cls(inst_mod.acquire(argv=argv), info, errhandler)

    def finalize(self) -> None:
        """``MPI_Session_finalize``: drop this session's instance
        reference (the last reference — session or world — finalizes
        the runtime)."""
        self._check()
        self._finalized = True
        from ompi_tpu import instance as inst_mod

        inst_mod.release()

    @property
    def finalized(self) -> bool:
        return self._finalized

    def _check(self) -> None:
        if self._finalized:
            self._err(MpiError(ErrorClass.ERR_SESSION,
                               f"{self.name} was finalized"))

    def _err(self, error: MpiError) -> None:
        self.errhandler.invoke(self, error)
        raise error  # ERRORS_RETURN already raised; fatal aborts

    # -- errhandler / info ----------------------------------------------
    def set_errhandler(self, eh: Errhandler) -> None:
        self.errhandler = eh

    def get_errhandler(self) -> Errhandler:
        return self.errhandler

    def call_errhandler(self, errorcode) -> None:
        """``MPI_Session_call_errhandler``."""
        try:
            cls = ErrorClass(int(errorcode))
        except ValueError:
            cls = ErrorClass.ERR_OTHER
        self._err(MpiError(cls, f"user-raised code {int(errorcode)}"))

    def get_info(self) -> Info:
        """``MPI_Session_get_info``: the session's hints (always
        includes the provided thread level, like the reference)."""
        self._check()
        out = self.info.dup()
        if "thread_level" not in out:
            out.set("thread_level", "MPI_THREAD_MULTIPLE")
        return out

    # -- process sets ----------------------------------------------------
    def get_num_psets(self, info: Optional[Info] = None) -> int:
        """``MPI_Session_get_num_psets``."""
        self._check()
        return len(self._instance.pset_names())

    def get_nth_pset(self, n: int, info: Optional[Info] = None) -> str:
        """``MPI_Session_get_nth_pset``."""
        self._check()
        names = self._instance.pset_names()
        if not 0 <= int(n) < len(names):
            self._err(MpiError(ErrorClass.ERR_ARG,
                               f"pset index {n} out of range "
                               f"[0, {len(names)})"))
        return names[int(n)]

    def psets(self) -> list:
        """All pset names (convenience superset of the nth iteration)."""
        self._check()
        return self._instance.pset_names()

    def get_pset_info(self, name: str) -> Info:
        """``MPI_Session_get_pset_info``: at least ``mpi_size``."""
        self._check()
        try:
            return self._instance.pset_info(name)
        except MpiError as exc:
            self._err(exc)

    def group_from_pset(self, name: str):
        """``MPI_Group_from_session_pset``: the ordered group of world
        ranks behind a named pset."""
        self._check()
        from ompi_tpu.api.group import Group

        try:
            return Group(self._instance.pset_members(name))
        except MpiError as exc:
            self._err(exc)

    def __repr__(self) -> str:
        state = "finalized" if self._finalized else "active"
        return f"Session({self.name}, {state})"
