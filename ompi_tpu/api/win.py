"""RMA windows — the MPI one-sided API surface.

Re-design of ``/root/reference/ompi/win/win.c`` + the ``osc`` framework
dispatch (``ompi/mca/osc/osc.h`` module vtable): a ``Win`` owns an exposure
region (a 1-D numpy array; ``disp_unit`` = dtype itemsize), an internal
duplicate of the creating communicator isolating its RMA traffic (the
reference allocates a window CID the same way), and the osc module chosen
at creation (``win_select``).  Public ops mirror MPI-3 RMA: put/get/
accumulate/get_accumulate/fetch_and_op/compare_and_swap, with fence,
passive-target lock/unlock/lock_all/flush, and PSCW generalized active
target sync.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.attributes import AttributeHost
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.group import Group
from ompi_tpu.runtime import trace


#: otpu-verify contract — the RMA epoch automaton, machine-read by the
#: ``mpi-typestate`` static pass (loaded from the AST; keep every value
#: a literal).  lock/lock_all open a passive-target epoch that must close
#: with unlock/unlock_all; flush only orders operations inside one; PSCW
#: pairs start/complete on the origin and post/wait on the target.
_TYPESTATE = {
    "create": ["Win.create", "Win.allocate", "Win.allocate_shared",
               "Win.create_dynamic"],
    "passive_open": ["lock", "lock_all"],
    "passive_close": ["unlock", "unlock_all"],
    "pscw": {"start": "complete", "post": "wait"},
    "in_passive": ["flush", "flush_all"],
}


class Win(AttributeHost):
    LOCK_EXCLUSIVE = "exclusive"
    LOCK_SHARED = "shared"

    def __init__(self, comm, local: np.ndarray, name: str = "") -> None:
        self.comm = comm            # internal dup — RMA traffic isolation
        self.local = local          # my exposure region
        self.name = name or f"win#{comm.cid}"
        self.module = None          # selected osc module
        self.freed = False
        # a byte-addressed window (symmetric heap): offsets are bytes and
        # typed RMA ops reinterpret target bytes as the origin dtype
        self.byte_addressed = False

    # -- creation (collective) ------------------------------------------
    @classmethod
    def create(cls, comm, size: Optional[int] = None, base=None,
               dtype=np.float64, name: str = "",
               device: bool = False) -> "Win":
        """``MPI_Win_create`` / ``MPI_Win_allocate``.

        ``base``: expose an existing 1-D array; or ``size``: allocate a
        zero-filled region of ``size`` elements of ``dtype``.
        ``device=True`` in a device world allocates the window in HBM
        (osc/device: a sharded ``jax.Array`` exposure region per rank).
        """
        if base is None:
            if size is None:
                raise MpiError(ErrorClass.ERR_WIN,
                               "Win.create needs size= or base=")
            base = np.zeros(size, dtype=dtype)
        else:
            base = np.ascontiguousarray(base)
            if base.ndim != 1:
                raise MpiError(ErrorClass.ERR_WIN,
                               "window base must be 1-D")
        win = cls(comm.dup(), base, name=name)
        win.dtype = base.dtype     # survives device windows (local=None)
        win.device = device
        from ompi_tpu.mca.osc import win_select

        win_select(win)
        win.comm.barrier()  # all exposure agents live before first access
        return win

    @classmethod
    def create_dynamic(cls, comm, name: str = "") -> "Win":
        """``MPI_Win_create_dynamic``: a window with NO exposure region
        at creation; memory is attached later with :meth:`attach`.  The
        reference addresses attached regions by absolute address; here
        :meth:`attach` returns a region handle the application shares
        with peers (the same out-of-band step real MPI apps do with
        ``MPI_Get_address``)."""
        import itertools

        if comm.rte is not None and comm.rte.is_device_world:
            raise MpiError(
                ErrorClass.ERR_WIN,
                "dynamic windows need the multi-process model (attach "
                "semantics are per-process memory; run under tpurun)")
        win = cls(comm.dup(), np.zeros(0, np.uint8), name=name)
        win.dtype = np.dtype(np.uint8)
        win.device = False
        win.dynamic = True
        win.regions = {}
        win._region_ids = itertools.count(1)
        from ompi_tpu.mca.osc import win_select

        win_select(win)
        win.comm.barrier()
        return win

    def attach_region(self, arr) -> int:
        """``MPI_Win_attach`` (local): expose ``arr`` through this
        dynamic window; returns the region handle peers target."""
        self._check()
        if not getattr(self, "dynamic", False):
            raise MpiError(ErrorClass.ERR_WIN,
                           "attach needs a dynamic window")
        if not isinstance(arr, np.ndarray) or \
                not arr.flags["C_CONTIGUOUS"]:
            # a silent ascontiguousarray COPY would expose hidden memory:
            # peers' puts must land in the caller's own array
            raise MpiError(ErrorClass.ERR_WIN,
                           "attach needs a C-contiguous ndarray (remote "
                           "writes target the caller's memory)")
        handle = next(self._region_ids)
        self.regions[handle] = arr
        return handle

    def detach_region(self, handle: int) -> None:
        """``MPI_Win_detach``."""
        self._check()
        if getattr(self, "regions", None) is None \
                or handle not in self.regions:
            raise MpiError(ErrorClass.ERR_WIN,
                           f"no attached region {handle}")
        del self.regions[handle]

    @classmethod
    def allocate(cls, comm, size: int, dtype=np.float64,
                 name: str = "") -> tuple["Win", np.ndarray]:
        """``MPI_Win_allocate``: framework-allocated exposure region;
        returns (win, local buffer)."""
        win = cls.create(comm, size=size, dtype=dtype, name=name)
        return win, win.local

    @classmethod
    def allocate_shared(cls, comm, size: int, dtype=np.float64,
                        name: str = "") -> tuple["Win", np.ndarray]:
        """``MPI_Win_allocate_shared``: same-node windows are genuinely
        shared-memory mapped here (osc/rdma's segments), so allocate IS
        allocate_shared; ``shared_query`` gives the direct view."""
        return cls.allocate(comm, size, dtype, name)

    def shared_query(self, target: int) -> np.ndarray:
        """``MPI_Win_shared_query``: a direct load/store view of
        ``target``'s window (same-node, shm-mapped osc modules only)."""
        self._check()
        seg = getattr(self.module, "_seg", None)
        if seg is None:
            raise MpiError(
                ErrorClass.ERR_RMA_CONFLICT,
                f"window {self.name}'s osc module has no shared segments "
                f"(active-message path); use put/get")
        view = seg(self, target).typed()
        # trim the >=1-byte allocation pad (zero-size windows) off the
        # mapped segment.  shared_query assumes the symmetric allocation
        # allocate_shared performs (same size every rank), so my element
        # count is the peer's too
        nelem = self.local.size if self.local is not None else len(view)
        return view[:nelem]

    # -- accessors -------------------------------------------------------
    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def rank(self) -> int:
        return self.comm.rank

    def _check(self) -> None:
        if self.freed:
            raise MpiError(ErrorClass.ERR_WIN, "window was freed")

    def _mon(self, op: str, nbytes: int) -> None:
        # osc/monitoring interposition (common_monitoring.h's osc slot)
        from ompi_tpu.runtime import monitoring

        if monitoring.enabled():
            monitoring.record_osc(op, nbytes)

    def _epoch(self, name: str, fn, *a):
        """Run one epoch-synchronization call under an osc trace span
        (fence / lock / unlock / PSCW / flush — the waits where RMA skew
        and straggler targets become visible)."""
        if not trace.enabled:
            return fn(*a)
        t0 = trace.now()
        try:
            return fn(*a)
        finally:
            trace.span(name, "osc", t0, args={"win": self.name})

    # -- RMA ops ---------------------------------------------------------
    def put(self, arr, target: int, offset: int = 0,
            region: Optional[int] = None) -> None:
        self._check()
        arr = np.ascontiguousarray(arr)
        self._mon("put", arr.nbytes)
        if region is not None:
            self._region_op("put_region", arr, target, offset, region)
            return
        self.module.put(self, arr, target, offset)

    def get(self, count: int, target: int, offset: int = 0,
            region: Optional[int] = None) -> np.ndarray:
        self._check()
        if region is not None:
            # region dtype lives at the target: count real bytes after
            out = self._region_op("get_region", count, target, offset,
                                  region)
            self._mon("get", out.nbytes)
            return out
        self._mon("get", count * self.dtype.itemsize)
        return self.module.get(self, count, target, offset)

    def _region_op(self, name: str, payload, target: int, offset: int,
                   region: int):
        fn = getattr(self.module, name, None)
        if fn is None:
            raise MpiError(
                ErrorClass.ERR_WIN,
                f"{self.name}'s osc module has no dynamic-region RMA")
        return fn(self, payload, target, offset, region)

    def accumulate(self, arr, target: int, offset: int = 0,
                   op: op_mod.Op = op_mod.SUM) -> None:
        self._check()
        arr = np.ascontiguousarray(arr)
        self._mon("accumulate", arr.nbytes)
        self.module.accumulate(self, arr, target, offset, op)

    def get_accumulate(self, arr, target: int, offset: int = 0,
                       op: op_mod.Op = op_mod.SUM) -> np.ndarray:
        """Atomically fetch the old contents and apply ``arr (op) target``."""
        self._check()
        arr = np.ascontiguousarray(arr)
        self._mon("get_accumulate", arr.nbytes)
        return self.module.get_accumulate(self, arr, target, offset, op)

    def fetch_and_op(self, value, target: int, offset: int = 0,
                     op: op_mod.Op = op_mod.SUM):
        self._check()
        out = self.module.get_accumulate(
            self, np.asarray([value], dtype=self.dtype), target,
            offset, op)
        return out[0]

    def compare_and_swap(self, value, compare, target: int, offset: int = 0):
        self._check()
        self._mon("compare_and_swap", np.asarray(value).nbytes)
        return self.module.compare_and_swap(self, value, compare, target,
                                            offset)

    # -- request-based RMA (MPI_Rput/Rget/Raccumulate/Rget_accumulate) ---
    # The osc modules complete operations on return (mapped windows:
    # direct load/store; active message: request/reply inside the call),
    # so the returned request is born complete — flush is still what
    # orders remote visibility, exactly as MPI allows.
    def rput(self, arr, target: int, offset: int = 0):
        from ompi_tpu.api.request import CompletedRequest

        self.put(arr, target, offset)
        return CompletedRequest()

    def rget(self, count: int, target: int, offset: int = 0):
        from ompi_tpu.api.request import CompletedRequest

        req = CompletedRequest()
        req.result = self.get(count, target, offset)
        return req

    def raccumulate(self, arr, target: int, offset: int = 0,
                    op: op_mod.Op = op_mod.SUM):
        from ompi_tpu.api.request import CompletedRequest

        self.accumulate(arr, target, offset, op)
        return CompletedRequest()

    def rget_accumulate(self, arr, target: int, offset: int = 0,
                        op: op_mod.Op = op_mod.SUM):
        from ompi_tpu.api.request import CompletedRequest

        req = CompletedRequest()
        req.result = self.get_accumulate(arr, target, offset, op)
        return req

    # -- synchronization -------------------------------------------------
    def fence(self) -> None:
        """``MPI_Win_fence``: close + open an active-target epoch."""
        self._check()
        self._epoch("win_fence", self.module.fence, self)

    def lock(self, target: int, lock_type: str = LOCK_EXCLUSIVE) -> None:
        self._check()
        self._epoch("win_lock", self.module.lock, self, target, lock_type)

    def unlock(self, target: int) -> None:
        self._check()
        self._epoch("win_unlock", self.module.unlock, self, target)

    def lock_all(self) -> None:
        self._check()

        def _all():
            for t in range(self.size):
                self.module.lock(self, t, self.LOCK_SHARED)

        self._epoch("win_lock_all", _all)

    def unlock_all(self) -> None:
        self._check()

        def _all():
            for t in range(self.size):
                self.module.unlock(self, t)

        self._epoch("win_unlock_all", _all)

    def flush(self, target: int) -> None:
        """Complete all outstanding ops this process issued to ``target``."""
        self._check()
        self._epoch("win_flush", self.module.flush, self, target)

    def flush_all(self) -> None:
        self._check()

        def _all():
            for t in range(self.size):
                self.module.flush(self, t)

        self._epoch("win_flush_all", _all)

    def flush_local(self, target: int) -> None:
        # origin-local completion; our put/accumulate pack eagerly, so
        # origin buffers are reusable as soon as the call returns
        self._check()

    def sync(self) -> None:
        self._check()

    # PSCW generalized active-target (MPI_Win_post/start/complete/wait)
    def post(self, group: Group) -> None:
        self._check()
        self._epoch("win_post", self.module.post, self, group)

    def start(self, group: Group) -> None:
        self._check()
        self._epoch("win_start", self.module.start, self, group)

    def complete(self) -> None:
        self._check()
        self._epoch("win_complete", self.module.complete, self)

    def wait(self) -> None:
        self._check()
        self._epoch("win_wait", self.module.wait, self)

    def test(self) -> bool:
        """``MPI_Win_test``: nonblocking ``wait`` — True iff the exposure
        epoch completed (all access-group members called complete)."""
        self._check()
        fn = getattr(self.module, "pscw_test", None)
        if fn is None:
            raise MpiError(ErrorClass.ERR_RMA_SYNC,
                           f"{self.name}'s osc module has no "
                           "nonblocking PSCW test")
        return bool(fn(self))

    # -- lifecycle -------------------------------------------------------
    def free(self) -> None:
        if self.freed:
            return
        self.comm.barrier()
        self.module.detach(self)
        self._attrs_delete_all()
        self.comm.free()  # release the internal dup (CID, match state)
        self.freed = True

    def __repr__(self) -> str:
        n = self.local.size if self.local is not None else "device"
        return f"Win({self.name}, rank={self.rank}/{self.size}, len={n})"
