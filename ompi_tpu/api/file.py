"""File — the MPI-IO surface object (``MPI_File``).

Re-design of ``/root/reference/ompi/file/file.c`` + the ``MPI_File_*``
bindings (``ompi/mpi/c/file_*.c``): a File is opened collectively on a
communicator, carries an access mode, a file view (disp, etype, filetype),
an individual file pointer, and a *shared* file pointer, and dispatches
every I/O operation to the io module selected for it (``mca/io/base``).

Buffers are numpy arrays (count/type inferred) or ``(array, count,
Datatype)`` triples; non-contiguous memory layouts go through the datatype
convertor's pack/unpack, and non-contiguous *file* layouts through the
view's filetype — the same duality the reference's convertor + file-view
machinery provides.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import CompletedRequest, Request
from ompi_tpu.datatype import BYTE, Datatype
from ompi_tpu.datatype.convertor import Convertor

# amode flags (MPI_MODE_*)
MODE_RDONLY = 0x01
MODE_WRONLY = 0x02
MODE_RDWR = 0x04
MODE_CREATE = 0x08
MODE_EXCL = 0x10
MODE_DELETE_ON_CLOSE = 0x20
MODE_APPEND = 0x40
MODE_UNIQUE_OPEN = 0x80
MODE_SEQUENTIAL = 0x100

SEEK_SET, SEEK_CUR, SEEK_END = 0, 1, 2

_MODE_CHARS = {"r": MODE_RDONLY, "w": MODE_WRONLY, "+": MODE_RDWR,
               "c": MODE_CREATE, "x": MODE_EXCL, "a": MODE_APPEND,
               "d": MODE_DELETE_ON_CLOSE}


def _parse_amode(amode) -> int:
    if isinstance(amode, int):
        return amode
    flags = 0
    for ch in amode:
        if ch not in _MODE_CHARS:
            raise MpiError(ErrorClass.ERR_AMODE, f"bad amode char {ch!r}")
        flags |= _MODE_CHARS[ch]
    return flags


def _buffer_to_bytes(buf) -> tuple[bytes, Any]:
    """Pack a user buffer to its data-stream bytes (+ keepalive array)."""
    if isinstance(buf, tuple):
        arr, count, dt = buf
        arr = np.asarray(arr)
        if dt.is_contiguous and arr.flags.c_contiguous:
            data = arr.tobytes()[:count * dt.size]
        else:
            conv = Convertor(dt, count).prepare(arr)
            data = conv.pack()
        return data, arr
    arr = np.ascontiguousarray(buf)
    return arr.tobytes(), arr


def _stream_nbytes(buf) -> int:
    """Data-stream byte size of a buffer spec without packing it."""
    if isinstance(buf, tuple):
        _, count, dt = buf
        return count * dt.size
    return np.asarray(buf).nbytes


# -- data representations (MPI_Register_datarep, io ompio datareps) -----
#
# "native" = bytes as-is; "external32" = canonical big-endian per etype
# item; user reps registered here convert the whole byte stream between
# file and memory representation (read_fn: file->memory bytes,
# write_fn: memory->file bytes), the MPI_Register_datarep contract with
# the dtype-conversion collapsed to the byte stream.
_datareps: dict = {}


def register_datarep(name: str, read_fn, write_fn,
                     extent_fn=None) -> None:
    """``MPI_Register_datarep``."""
    if name in ("native", "external32") or name in _datareps:
        raise MpiError(ErrorClass.ERR_ARG,
                       f"datarep {name!r} already defined")
    _datareps[name] = (read_fn, write_fn, extent_fn)


def _bytes_to_buffer(data: bytes, buf) -> int:
    """Unpack stream bytes into the user buffer; returns element count."""
    if isinstance(buf, tuple):
        arr, count, dt = buf
        arr = np.asarray(arr)
        conv = Convertor(dt, count).prepare(arr)
        return conv.unpack(data) // max(1, dt.size) if dt.size else 0
    arr = np.asarray(buf)
    if not arr.flags.c_contiguous:
        raise MpiError(ErrorClass.ERR_BUFFER,
                       "read into non-contiguous memory requires an "
                       "(array, count, Datatype) buffer triple")
    flat = arr.reshape(-1).view(np.uint8)
    n = min(len(data), flat.nbytes)
    flat[:n] = np.frombuffer(data, np.uint8, count=n)
    return n // max(1, arr.dtype.itemsize)


class File:
    """An open MPI file.  Create with ``File.open(comm, name, amode)``."""

    def __init__(self, comm, filename: str, amode: int, fd: int) -> None:
        self.comm = comm
        self.filename = filename
        self.amode = amode
        self.fd = fd
        self.closed = False
        self.atomicity = False
        self.io_module = None      # set by file_select
        # default view: displacement 0, byte stream
        self.disp = 0
        self.etype: Datatype = BYTE
        self.filetype: Datatype = BYTE
        self._fp = 0               # individual pointer, etype units
        self._sfp_key = f"__sfp__:{os.path.abspath(filename)}"
        self._split = None         # active split collective (kind, end)

    # -- open / close -----------------------------------------------------
    @classmethod
    def open(cls, comm, filename: str, amode="rc",
             info=None) -> "File":
        """Collective open (``MPI_File_open``).

        ``amode`` is an int of MODE_* flags or a string: r/w/+ access,
        c(reate), x(excl), a(ppend), d(elete-on-close).
        """
        flags = _parse_amode(amode)
        access = bool(flags & (MODE_RDONLY | MODE_WRONLY | MODE_RDWR))
        if not access:
            flags |= MODE_RDWR
        osflags = os.O_RDONLY
        if flags & MODE_RDWR or (flags & MODE_RDONLY and flags & MODE_WRONLY):
            osflags = os.O_RDWR
        elif flags & MODE_WRONLY:
            osflags = os.O_WRONLY
        if flags & MODE_CREATE:
            osflags |= os.O_CREAT
        if flags & MODE_APPEND:
            osflags |= os.O_APPEND
        rank = comm.rank if comm is not None else 0
        # rank 0 creates (EXCL races resolved there), others open after
        if comm is not None and comm.size > 1:
            err = ""
            if rank == 0:
                try:
                    excl = osflags | (os.O_EXCL if flags & MODE_EXCL else 0)
                    fd = os.open(filename, excl, 0o644)
                except OSError as exc:
                    err, fd = str(exc), -1
                comm.bcast(np.array([fd >= 0], np.int8), root=0)
                if fd < 0:
                    raise MpiError(ErrorClass.ERR_IO,
                                   f"cannot open {filename!r}: {err}")
            else:
                ok = comm.bcast(np.zeros(1, np.int8), root=0)
                if not int(ok[0]):
                    raise MpiError(ErrorClass.ERR_IO,
                                   f"cannot open {filename!r} (root failed)")
                fd = os.open(filename, osflags & ~os.O_CREAT
                             if not flags & MODE_CREATE else osflags, 0o644)
        else:
            excl = osflags | (os.O_EXCL if flags & MODE_EXCL else 0)
            try:
                fd = os.open(filename, excl, 0o644)
            except OSError as exc:
                raise MpiError(ErrorClass.ERR_IO,
                               f"cannot open {filename!r}: {exc}")
        f = cls(comm, filename, flags, fd)
        from ompi_tpu.mca.io.base import file_select

        file_select(f)
        # per-open shared-pointer counter: a fresh key per collective open
        # (so reopened or concurrently-opened handles of the same path
        # don't share or inherit a stale counter), starting at 0
        client = f._sfp_client()
        if comm is not None and comm.size > 1:
            seq = np.zeros(1, np.int64)
            if rank == 0 and client is not None:
                seq[0] = client.fetch_add(-1, "__sfp_open_seq__", 1)
            seq = comm.bcast(seq, root=0)
            f._sfp_key += f":open{int(seq[0])}"
            if rank == 0:
                f._shared_reset(0)
            comm.barrier()   # reset visible before anyone's first I/O
        else:
            f._shared_reset(0)
        return f

    @staticmethod
    def delete(filename: str) -> None:
        try:
            os.unlink(filename)
        except FileNotFoundError as exc:
            raise MpiError(ErrorClass.ERR_FILE, str(exc))

    def close(self) -> None:
        if self.closed:
            return
        if self.comm is not None and self.comm.size > 1:
            self.comm.barrier()
        os.close(self.fd)
        if self.amode & MODE_DELETE_ON_CLOSE:
            if self.comm is None or self.comm.rank == 0:
                try:
                    os.unlink(self.filename)
                except FileNotFoundError:
                    pass
        self.closed = True

    def _check(self) -> None:
        if self.closed:
            raise MpiError(ErrorClass.ERR_FILE, "file is closed")

    # -- datarep conversion (applied at the stream boundary) -------------
    def _convert(self, data, direction: str):
        rep = getattr(self, "datarep", "native")
        if rep == "native":
            return data
        if rep == "external32":
            # segment-wise byteswap of the packed stream — derived
            # etypes swap each field at its own itemsize (the convertor
            # owns that walk; reuse it rather than re-deriving)
            from ompi_tpu.datatype.convertor import (Convertor,
                                                     ConvertorFlags)

            size = max(1, self.etype.size)
            if len(data) % size:
                raise MpiError(ErrorClass.ERR_ARG,
                               f"external32 stream of {len(data)} bytes "
                               f"not a multiple of etype size {size}")
            arr = np.frombuffer(data, np.uint8).copy()
            cv = Convertor(self.etype, len(data) // size,
                           flags=ConvertorFlags.EXTERNAL32)
            cv._swap_external32(arr, 0)
            return arr.tobytes()
        read_fn, write_fn, _ = _datareps[rep]
        fn = read_fn if direction == "read" else write_fn
        out = fn(bytes(data), self.etype)
        if len(out) != len(data):
            # the read-sizing and file-pointer math assume the file and
            # memory representations have equal extents
            raise MpiError(ErrorClass.ERR_ARG,
                           f"datarep {rep!r} changed the stream size "
                           f"({len(data)} -> {len(out)}); only "
                           "size-preserving representations are "
                           "supported")
        return out

    def _to_stream(self, buf):
        data, keep = _buffer_to_bytes(buf)
        return self._convert(data, "write"), keep

    def _from_stream(self, data, buf) -> int:
        return _bytes_to_buffer(self._convert(data, "read"), buf)

    # -- view -------------------------------------------------------------
    def set_view(self, disp: int = 0, etype: Optional[Datatype] = None,
                 filetype: Optional[Datatype] = None,
                 datarep: str = "native", info=None) -> None:
        self._check()
        self.disp = disp
        self.etype = etype or BYTE
        self.filetype = filetype or self.etype
        if self.filetype.size % max(1, self.etype.size):
            raise MpiError(ErrorClass.ERR_ARG,
                           "filetype size must be a multiple of etype size")
        if datarep not in ("native", "external32") \
                and datarep not in _datareps:
            raise MpiError(ErrorClass.ERR_UNSUPPORTED_DATAREP
                           if hasattr(ErrorClass, "ERR_UNSUPPORTED_DATAREP")
                           else ErrorClass.ERR_ARG,
                           f"unsupported datarep {datarep!r}")
        self.datarep = datarep
        self._fp = 0
        if self.comm is None or self.comm.rank == 0:
            self._shared_reset(0)
        if self.comm is not None and self.comm.size > 1:
            # set_view is collective: nobody may issue shared-pointer I/O
            # until the reset has happened (rank 0 resets before its
            # barrier arrival releases the others)
            self.comm.barrier()

    def get_view(self) -> tuple:
        return self.disp, self.etype, self.filetype

    def get_byte_offset(self, offset: int) -> int:
        """``MPI_File_get_byte_offset``: absolute file byte of a
        view-relative offset (etype units), walking the filetype
        tiling (``ompi/mpi/c/file_get_byte_offset.c``)."""
        self._check()
        from ompi_tpu.mca.io.ompio import view_extents

        start = offset * max(1, self.etype.size)
        for off, _ln in view_extents(self.disp, self.filetype, start, 1):
            return off
        # zero-size etype / empty view: the displacement itself
        return self.disp + start

    def get_type_extent(self, datatype: Datatype) -> int:
        """``MPI_File_get_type_extent``: datatype extent in this file's
        data representation (external32 is size-packed; native keeps
        the memory extent)."""
        self._check()
        rep = getattr(self, "datarep", "native")
        if rep != "native":
            if rep in _datareps and _datareps[rep][2] is not None:
                return int(_datareps[rep][2](datatype))  # extent_fn
            return datatype.size       # external32: size-packed stream
        return datatype.extent

    # -- explicit-offset I/O ---------------------------------------------
    def write_at(self, offset: int, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        return self.io_module.write_at(self, offset, data)

    def read_at(self, offset: int, buf) -> int:
        self._check()
        data = self.io_module.read_at(self, offset, _stream_nbytes(buf))
        return self._from_stream(data, buf)

    def write_at_all(self, offset: int, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        return self.io_module.write_at_all(self, offset, data)

    def read_at_all(self, offset: int, buf) -> int:
        self._check()
        data = self.io_module.read_at_all(self, offset, _stream_nbytes(buf))
        return self._from_stream(data, buf)

    # nonblocking variants (MPI_File_iwrite_at & friends): the I/O path is
    # synchronous POSIX, so requests complete eagerly — same shape the
    # device collectives use (the XLA stream / page cache is the engine)
    def iwrite_at(self, offset: int, buf) -> Request:
        r = CompletedRequest()
        r.result = self.write_at(offset, buf)
        return r

    def iread_at(self, offset: int, buf) -> Request:
        r = CompletedRequest()
        r.result = self.read_at(offset, buf)
        return r

    def iwrite_at_all(self, offset: int, buf) -> Request:
        """``MPI_File_iwrite_at_all`` (nonblocking collective; eager)."""
        r = CompletedRequest()
        r.result = self.write_at_all(offset, buf)
        return r

    def iread_at_all(self, offset: int, buf) -> Request:
        r = CompletedRequest()
        r.result = self.read_at_all(offset, buf)
        return r

    # -- individual-pointer I/O ------------------------------------------
    def _advance(self, buf, n_elems_bytes: int) -> None:
        self._fp += n_elems_bytes // max(1, self.etype.size)

    def write(self, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        n = self.io_module.write_at(self, self._fp, data)
        self._advance(buf, len(data))
        return n

    def read(self, buf) -> int:
        self._check()
        data = self.io_module.read_at(self, self._fp, _stream_nbytes(buf))
        self._advance(buf, len(data))
        return self._from_stream(data, buf)

    def write_all(self, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        n = self.io_module.write_at_all(self, self._fp, data)
        self._advance(buf, len(data))
        return n

    def iwrite(self, buf) -> Request:
        """``MPI_File_iwrite`` (individual pointer, eager completion —
        the pointer advances before return, per MPI nonblocking rules)."""
        r = CompletedRequest()
        r.result = self.write(buf)
        return r

    def iread(self, buf) -> Request:
        r = CompletedRequest()
        r.result = self.read(buf)
        return r

    def iwrite_all(self, buf) -> Request:
        """``MPI_File_iwrite_all`` (nonblocking collective; eager)."""
        r = CompletedRequest()
        r.result = self.write_all(buf)
        return r

    def iread_all(self, buf) -> Request:
        r = CompletedRequest()
        r.result = self.read_all(buf)
        return r

    def read_all(self, buf) -> int:
        self._check()
        data = self.io_module.read_at_all(self, self._fp, _stream_nbytes(buf))
        self._advance(buf, len(data))
        return self._from_stream(data, buf)

    # -- split collectives (MPI_File_read_all_begin/end family) ----------
    # The reference carries these as begin/end halves over its two-phase
    # collective engine (``ompi/mpi/c/file_read_all_begin.c`` ->
    # ``mca_common_ompio_file_read_all_begin``).  Here the collective
    # engine is synchronous, so *begin* runs the collective and parks
    # the delivery while *end* hands it to the caller — the standard's
    # contract is what matters and is enforced: one outstanding split
    # collective per handle, matching end call, same buffer at end.

    def _assert_no_split(self) -> None:
        """Must run BEFORE a begin's I/O: a rejected begin must not
        have touched the file or advanced any pointer."""
        if self._split is not None:
            raise RuntimeError(
                f"split collective {self._split[0]}_begin already "
                "active: MPI allows one outstanding split collective "
                "per file handle")

    def _split_begin(self, kind: str, buf, finish) -> None:
        self._assert_no_split()
        self._split = (kind, buf, finish)

    def _split_end(self, kind: str, buf):
        self._check()
        if self._split is None:
            raise RuntimeError(f"{kind}_end without {kind}_begin")
        active, begin_buf, finish = self._split
        if active != kind:
            raise RuntimeError(
                f"{kind}_end does not match active split collective "
                f"{active}_begin")
        if begin_buf is not buf:
            raise RuntimeError(
                f"{kind}_end must receive the same buffer passed to "
                f"{kind}_begin")
        self._split = None
        return finish()

    def read_all_begin(self, buf) -> None:
        self._check()
        self._assert_no_split()
        data = self.io_module.read_at_all(self, self._fp,
                                          _stream_nbytes(buf))
        self._advance(buf, len(data))
        self._split_begin("read_all", buf,
                          lambda: self._from_stream(data, buf))

    def read_all_end(self, buf) -> int:
        return self._split_end("read_all", buf)

    def write_all_begin(self, buf) -> None:
        self._check()
        self._assert_no_split()
        data, _ = self._to_stream(buf)
        n = self.io_module.write_at_all(self, self._fp, data)
        self._advance(buf, len(data))
        self._split_begin("write_all", buf, lambda: n)

    def write_all_end(self, buf) -> int:
        return self._split_end("write_all", buf)

    def read_at_all_begin(self, offset: int, buf) -> None:
        self._check()
        self._assert_no_split()
        data = self.io_module.read_at_all(self, offset,
                                          _stream_nbytes(buf))
        self._split_begin("read_at_all", buf,
                          lambda: self._from_stream(data, buf))

    def read_at_all_end(self, buf) -> int:
        return self._split_end("read_at_all", buf)

    def write_at_all_begin(self, offset: int, buf) -> None:
        self._check()
        self._assert_no_split()
        data, _ = self._to_stream(buf)
        n = self.io_module.write_at_all(self, offset, data)
        self._split_begin("write_at_all", buf, lambda: n)

    def write_at_all_end(self, buf) -> int:
        return self._split_end("write_at_all", buf)

    def seek(self, offset: int, whence: int = SEEK_SET) -> None:
        self._check()
        if whence == SEEK_SET:
            self._fp = offset
        elif whence == SEEK_CUR:
            self._fp += offset
        elif whence == SEEK_END:
            size_et = self.get_size() // max(1, self.etype.size)
            self._fp = size_et + offset
        else:
            raise MpiError(ErrorClass.ERR_ARG, f"bad whence {whence}")
        if self._fp < 0:
            raise MpiError(ErrorClass.ERR_ARG, "negative file position")

    def get_position(self) -> int:
        return self._fp

    # -- shared-pointer I/O (sharedfp framework) -------------------------
    def _sfp_client(self):
        rte = self.comm.rte if self.comm is not None else None
        return getattr(rte, "client", None)

    def _shared_fetch_add(self, delta: int) -> int:
        client = self._sfp_client()
        if client is not None:
            return client.fetch_add(-1, self._sfp_key, delta)
        # single-process models: plain local counter
        cur = getattr(self, "_local_sfp", 0)
        self._local_sfp = cur + delta
        return cur

    def _shared_reset(self, value: int = 0) -> None:
        """Set the shared pointer (one atomic put; MPI requires the shared
        pointer to be 0 at open and reset by set_view)."""
        client = self._sfp_client()
        if client is not None:
            client.put(-1, self._sfp_key, value)
        else:
            self._local_sfp = value

    def write_shared(self, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        n_et = -(-len(data) // max(1, self.etype.size))
        pos = self._shared_fetch_add(n_et)
        return self.io_module.write_at(self, pos, data)

    def read_shared(self, buf) -> int:
        self._check()
        nbytes = _stream_nbytes(buf)
        n_et = -(-nbytes // max(1, self.etype.size))
        pos = self._shared_fetch_add(n_et)
        data = self.io_module.read_at(self, pos, nbytes)
        return self._from_stream(data, buf)

    def iwrite_shared(self, buf) -> Request:
        """``MPI_File_iwrite_shared`` (eager; the shared-pointer
        fetch-add is the ordering point, same as the blocking form)."""
        r = CompletedRequest()
        r.result = self.write_shared(buf)
        return r

    def iread_shared(self, buf) -> Request:
        r = CompletedRequest()
        r.result = self.read_shared(buf)
        return r

    def get_position_shared(self) -> int:
        """``MPI_File_get_position_shared``: shared pointer in etypes."""
        self._check()
        return self._shared_fetch_add(0)

    # -- ordered shared-pointer collectives (MPI_File_read_ordered) ------
    def _ordered_pos(self, nbytes: int) -> int:
        """Collective rank-ordered carve-out of the shared pointer:
        every rank learns everyone's element count, rank 0 advances the
        shared counter once by the total, and each rank's region starts
        at the old value plus the counts of the ranks before it — the
        reference's sharedfp ordered algorithm
        (``ompio/sharedfp/base``) on the coord-backed counter."""
        n_et = -(-nbytes // max(1, self.etype.size))
        if self.comm is None or self.comm.size == 1:
            return self._shared_fetch_add(n_et)
        counts = np.asarray(self.comm.allgather(
            np.array([n_et], np.int64))).reshape(-1)
        rank = self.comm.rank
        base = np.zeros(1, np.int64)
        if rank == 0:
            base[0] = self._shared_fetch_add(int(counts.sum()))
        base = np.asarray(self.comm.bcast(base, root=0)).reshape(-1)
        return int(base[0]) + int(counts[:rank].sum())

    def read_ordered(self, buf) -> int:
        self._check()
        nbytes = _stream_nbytes(buf)
        pos = self._ordered_pos(nbytes)
        data = self.io_module.read_at(self, pos, nbytes)
        return self._from_stream(data, buf)

    def write_ordered(self, buf) -> int:
        self._check()
        data, _ = self._to_stream(buf)
        pos = self._ordered_pos(len(data))
        return self.io_module.write_at(self, pos, data)

    def read_ordered_begin(self, buf) -> None:
        self._check()
        self._assert_no_split()
        nbytes = _stream_nbytes(buf)
        pos = self._ordered_pos(nbytes)
        data = self.io_module.read_at(self, pos, nbytes)
        self._split_begin("read_ordered", buf,
                          lambda: self._from_stream(data, buf))

    def read_ordered_end(self, buf) -> int:
        return self._split_end("read_ordered", buf)

    def write_ordered_begin(self, buf) -> None:
        self._check()
        self._assert_no_split()
        data, _ = self._to_stream(buf)
        pos = self._ordered_pos(len(data))
        n = self.io_module.write_at(self, pos, data)
        self._split_begin("write_ordered", buf, lambda: n)

    def write_ordered_end(self, buf) -> int:
        return self._split_end("write_ordered", buf)

    def seek_shared(self, offset: int, whence: int = SEEK_SET) -> None:
        """Collective in MPI; here any rank may reset the shared counter."""
        self._shared_reset(offset)

    # -- fs passthrough ---------------------------------------------------
    def get_size(self) -> int:
        self._check()
        return self.io_module.get_size(self)

    def set_size(self, size: int) -> None:
        self._check()
        self.io_module.set_size(self, size)

    def preallocate(self, size: int) -> None:
        self._check()
        self.io_module.preallocate(self, size)

    def sync(self) -> None:
        self._check()
        self.io_module.sync(self)

    def get_amode(self) -> int:
        return self.amode

    def get_group(self):
        return self.comm.group if self.comm is not None else None

    def set_atomicity(self, flag: bool) -> None:
        self.atomicity = bool(flag)

    def get_atomicity(self) -> bool:
        return self.atomicity

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        return f"File({self.filename!r}, fd={self.fd})"
