"""Error handlers (``ompi/errhandler/errhandler.c``): ERRORS_ARE_FATAL,
ERRORS_RETURN (raise to Python), ERRORS_ABORT, user handlers; FT escalation
hooks in the ULFM layer call through here."""
from __future__ import annotations

import sys
from typing import Callable, Optional

from ompi_tpu.api.errors import ErrorClass, MpiError


class Errhandler:
    def __init__(self, name: str, fn: Optional[Callable] = None):
        self.name = name
        self._fn = fn

    def invoke(self, obj, error: MpiError) -> None:
        if self._fn is not None:
            self._fn(obj, error.error_class)
            return
        if self.name == "ERRORS_RETURN":
            raise error
        # ERRORS_ARE_FATAL / ERRORS_ABORT
        print(f"[ompi_tpu] fatal error on {obj!r}: {error}", file=sys.stderr)
        from ompi_tpu.runtime import init as rt

        rt.abort(obj, int(error.error_class))


ERRORS_ARE_FATAL = Errhandler("ERRORS_ARE_FATAL")
ERRORS_RETURN = Errhandler("ERRORS_RETURN")
ERRORS_ABORT = Errhandler("ERRORS_ABORT")


def create(fn: Callable) -> Errhandler:
    return Errhandler(f"user_{id(fn):x}", fn)
