"""MPI_T tool information interface (``ompi/mpi/tool``, MPI-3 §14.3).

The reference exposes the MCA var/pvar registry programmatically so
performance tools can enumerate, read, and (for control variables) write
tunables at runtime without linking private headers.  Same product here,
over ``ompi_tpu.base.var.registry``:

- control variables (cvars)  ≅ MPI_T_cvar_get_num / get_info /
  read / write  (``mca_base_var`` registry rows)
- performance variables (pvars) ≅ MPI_T_pvar_get_num / get_info +
  session/handle start-stop-read (``mca_base_pvar``)

Sessions exist for the reference's reason: a tool's handles must be
independent of another tool's (start/stop state is per-handle, not
per-variable).  Verbosity levels and binding objects are carried but the
Python surface keeps them advisory.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.var import Pvar, Var, registry

_init_count = 0
_lock = threading.Lock()


def init_thread() -> None:
    """``MPI_T_init_thread``: refcounted, independent of MPI init."""
    global _init_count
    with _lock:
        _init_count += 1


def finalize() -> None:
    """``MPI_T_finalize``."""
    global _init_count
    with _lock:
        if _init_count == 0:
            raise MpiError(ErrorClass.ERR_OTHER, "MPI_T not initialized")
        _init_count -= 1


def _check_init() -> None:
    if _init_count == 0:
        raise MpiError(ErrorClass.ERR_OTHER,
                       "MPI_T interface not initialized")


# -- control variables ---------------------------------------------------

def cvar_get_num() -> int:
    _check_init()
    return len(registry.all_vars())


def cvar_get_info(index: int) -> Var:
    """Returns the Var object itself — name/value/type/source are its
    attributes (the C API's out-params)."""
    _check_init()
    out = registry.all_vars()
    if not 0 <= index < len(out):
        raise MpiError(ErrorClass.ERR_ARG, f"no cvar at index {index}")
    return out[index]


def cvar_get_index(name: str) -> int:
    _check_init()
    for i, v in enumerate(registry.all_vars()):
        if v.name == name:
            return i
    raise MpiError(ErrorClass.ERR_ARG, f"no cvar named {name!r}")


def cvar_read(index: int) -> Any:
    return cvar_get_info(index).value


def cvar_write(index: int, value: Any) -> None:
    """``MPI_T_cvar_write``: runtime set, recorded with source=tool.

    Raises MpiError when the variable cannot be written (constant scope,
    or read-only after runtime init) — mirroring MPI_T_ERR_CVAR_SET_NEVER
    / _SET_NOT_NOW."""
    from ompi_tpu.base.var import VarSource

    var = cvar_get_info(index)
    try:
        applied = var._set(value, VarSource.API, "MPI_T")
    except RuntimeError as exc:
        raise MpiError(ErrorClass.ERR_ARG,
                       f"cvar {var.name} not settable now: {exc}")
    if not applied:
        raise MpiError(ErrorClass.ERR_ARG,
                       f"cvar {var.name} can never be set (constant scope)")


# -- performance variables ----------------------------------------------

def pvar_get_num() -> int:
    _check_init()
    return len(registry.all_pvars())


def pvar_get_info(index: int) -> Pvar:
    _check_init()
    out = registry.all_pvars()
    if not 0 <= index < len(out):
        raise MpiError(ErrorClass.ERR_ARG, f"no pvar at index {index}")
    return out[index]


def pvar_get_index(name: str) -> int:
    _check_init()
    for i, p in enumerate(registry.all_pvars()):
        if p.name == name:
            return i
    raise MpiError(ErrorClass.ERR_ARG, f"no pvar named {name!r}")


class PvarSession:
    """``MPI_T_pvar_session``: an isolated set of pvar handles."""

    def __init__(self) -> None:
        _check_init()
        self._handles: dict[int, "PvarHandle"] = {}
        self._ids = itertools.count(1)

    def handle_alloc(self, index: int, obj: Any = None) -> "PvarHandle":
        h = PvarHandle(pvar_get_info(index), next(self._ids), obj)
        self._handles[h.hid] = h
        return h

    def handle_free(self, handle: "PvarHandle") -> None:
        self._handles.pop(handle.hid, None)


class PvarHandle:
    """A started/stopped view of one pvar; ``read`` reports the delta
    since ``start`` for counters (the MPI_T session semantic that lets
    two tools watch one counter without fighting over resets)."""

    def __init__(self, pvar: Pvar, hid: int, obj: Any = None) -> None:
        self.pvar = pvar
        self.hid = hid
        self.bound_obj = obj
        self.started = False
        self._frozen_valid = False   # has start() or stop() set _frozen state?
        self._base = 0.0
        self._frozen = 0.0

    def _delta_class(self) -> bool:
        """Counters/timers report deltas against the start value; level,
        size, and watermark classes are absolute (MPI-3 §14.3.7)."""
        from ompi_tpu.base.var import PvarClass

        return self.pvar.pclass in (PvarClass.COUNTER, PvarClass.TIMER)

    def start(self) -> None:
        self._base = self.pvar.read() if self._delta_class() else 0.0
        self.started = True
        self._frozen_valid = True

    def stop(self) -> None:
        """Freeze the handle: reads after stop report the value observed
        at stop time (MPI-3 §14.3 stopped-handle semantics)."""
        self._frozen = self.pvar.read() - self._base
        self.started = False
        self._frozen_valid = True

    def read(self) -> float:
        if not self.started:
            # a never-started, never-stopped handle on an absolute class
            # (LEVEL/SIZE/WATERMARK) reports the live value — MPI-3
            # continuous-variable semantics; only delta classes freeze at 0
            # before a start, and an explicit stop() freezes every class
            if not self._frozen_valid and not self._delta_class():
                return self.pvar.read()
            return self._frozen
        return self.pvar.read() - self._base

    def reset(self) -> None:
        self._base = self.pvar.read() if self._delta_class() else 0.0
        self._frozen = 0.0


def pvar_session_create() -> PvarSession:
    return PvarSession()


def pvar_session_free(session: PvarSession) -> None:
    session._handles.clear()


# -- categories (MPI_T_category_*): frameworks are the natural grouping --

def category_get_num() -> int:
    _check_init()
    from ompi_tpu.base import mca

    return len(mca.all_frameworks())


def category_get_info(index: int):
    """(name, description, cvar names in the category)."""
    _check_init()
    from ompi_tpu.base import mca

    fws = mca.all_frameworks()
    if not 0 <= index < len(fws):
        raise MpiError(ErrorClass.ERR_ARG, f"no category at index {index}")
    fw = fws[index]
    vars_in = [v.name for v in registry.all_vars()
               if v.group.split("/")[0] == fw.name]
    return fw.name, fw.description, vars_in
