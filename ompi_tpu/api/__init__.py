"""MPI-semantics API layer (``/root/reference/ompi/`` core objects +
``ompi/mpi/c`` bindings collapsed into Pythonic classes)."""
