"""MPI buffered-send machinery (``MPI_Buffer_attach`` / ``MPI_Bsend``).

Reference: ``ompi/mpi/c/buffer_attach.c`` + the bsend allocator
(``ompi/runtime/ompi_mpi_init.c`` pml base bsend).  One buffer per
process; Bsend copies the message out of the user's buffer immediately
(so the user may reuse it on return) and accounts the copy against the
attached capacity until the underlying send completes.  Detach blocks
until every buffered send has drained — the MPI semantic tools rely on.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError

BSEND_OVERHEAD = 64     # accounting slack per message (MPI_BSEND_OVERHEAD)

_lock = threading.Lock()
_capacity = 0
_in_use = 0
_pending: list = []
_attached_obj = None


def attach(buf) -> None:
    """``MPI_Buffer_attach``: int size or a numpy buffer (its nbytes)."""
    global _capacity, _in_use, _attached_obj
    with _lock:
        if _attached_obj is not None:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           "a bsend buffer is already attached")
        nbytes = int(buf) if isinstance(buf, (int, np.integer)) \
            else int(np.asarray(buf).nbytes)
        _attached_obj = buf
        _capacity = nbytes
        _in_use = 0


def detach():
    """``MPI_Buffer_detach``: waits for all buffered sends, returns the
    attached buffer (or its size)."""
    global _capacity, _in_use, _attached_obj
    with _lock:
        if _attached_obj is None:
            raise MpiError(ErrorClass.ERR_BUFFER, "no bsend buffer attached")
        pending = list(_pending)
    for req in pending:
        try:
            req.wait()
        except Exception:
            # a buffered send to a dead peer completes in error; the
            # detach must still succeed (the buffer IS drained — the
            # message just won't arrive), or buffered sends would be
            # bricked for the rest of the process
            pass
    with _lock:
        obj = _attached_obj
        _attached_obj = None
        _capacity = 0
        _in_use = 0
        _pending.clear()
    return obj


def claim(nbytes: int) -> None:
    """Reserve bsend space for one message (raises if it can't fit)."""
    global _in_use
    need = nbytes + BSEND_OVERHEAD
    with _lock:
        if _attached_obj is None:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           "MPI_Bsend without an attached buffer")
        if _in_use + need > _capacity:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"bsend buffer exhausted ({_in_use}+{need} > {_capacity})")
        _in_use += need


def release(nbytes: int) -> None:
    """Undo a claim whose send was never issued (isend raised)."""
    global _in_use
    with _lock:
        _in_use = max(0, _in_use - (nbytes + BSEND_OVERHEAD))


def track(req, nbytes: int) -> None:
    """Release the claim when the underlying send completes."""
    def done(_r, n=nbytes + BSEND_OVERHEAD):
        global _in_use
        with _lock:
            _in_use = max(0, _in_use - n)
            if req in _pending:
                _pending.remove(req)

    with _lock:
        _pending.append(req)
    req.on_complete(done)


def reset_for_testing() -> None:
    global _capacity, _in_use, _attached_obj
    with _lock:
        _capacity = 0
        _in_use = 0
        _attached_obj = None
        _pending.clear()
