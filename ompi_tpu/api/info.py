"""MPI_Info equivalent (``ompi/info/info.c`` — ordered key/value hints with
dup and subscriber semantics collapsed to plain get/set)."""
from __future__ import annotations

from typing import Optional


class Info:
    MAX_KEY = 255
    MAX_VAL = 1024

    def __init__(self, items: Optional[dict] = None):
        self._d: dict[str, str] = dict(items or {})

    def set(self, key: str, value: str) -> None:
        if not 0 < len(key) <= self.MAX_KEY:
            raise ValueError("invalid info key")
        if len(str(value)) > self.MAX_VAL:
            raise ValueError("info value too long")
        self._d[key] = str(value)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._d.get(key, default)

    def delete(self, key: str) -> None:
        if key not in self._d:
            raise KeyError(key)
        del self._d[key]

    def get_nkeys(self) -> int:
        return len(self._d)

    def get_nthkey(self, n: int) -> str:
        return list(self._d)[n]

    def dup(self) -> "Info":
        return Info(self._d)

    def items(self):
        return self._d.items()

    def __contains__(self, key: str) -> bool:
        return key in self._d


INFO_NULL = Info()
