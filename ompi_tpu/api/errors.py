"""MPI error classes and exception types.

Equivalent of the reference error-class table (``ompi/errhandler/``,
``ompi/include/mpi.h.in`` MPI_ERR_* constants) including the ULFM
fault-tolerance error classes (``MPIX_ERR_PROC_FAILED`` /
``MPIX_ERR_REVOKED``, ``ompi/mpiext/ftmpi/``).
"""
from __future__ import annotations

import enum


class ErrorClass(enum.IntEnum):
    SUCCESS = 0
    ERR_BUFFER = 1
    ERR_COUNT = 2
    ERR_TYPE = 3
    ERR_TAG = 4
    ERR_COMM = 5
    ERR_RANK = 6
    ERR_REQUEST = 7
    ERR_ROOT = 8
    ERR_GROUP = 9
    ERR_OP = 10
    ERR_TOPOLOGY = 11
    ERR_DIMS = 12
    ERR_ARG = 13
    ERR_UNKNOWN = 14
    ERR_TRUNCATE = 15
    ERR_OTHER = 16
    ERR_INTERN = 17
    ERR_IN_STATUS = 18
    ERR_PENDING = 19
    ERR_KEYVAL = 20
    ERR_NO_MEM = 21
    ERR_INFO = 22
    ERR_INFO_KEY = 23
    ERR_INFO_VALUE = 24
    ERR_INFO_NOKEY = 25
    ERR_WIN = 26
    ERR_FILE = 27
    ERR_RMA_CONFLICT = 28
    ERR_RMA_SYNC = 29
    ERR_IO = 30
    ERR_NOT_SAME = 31
    ERR_AMODE = 32
    ERR_UNSUPPORTED_OPERATION = 33
    ERR_NO_SPACE = 34
    ERR_NO_SUCH_FILE = 35
    ERR_SPAWN = 36
    ERR_PORT = 37
    ERR_SERVICE = 38
    ERR_NAME = 39
    ERR_SESSION = 40
    # ULFM fault-tolerance classes
    ERR_PROC_FAILED = 75
    ERR_PROC_FAILED_PENDING = 76
    ERR_REVOKED = 77


class MpiError(Exception):
    """Raised by the ERRORS_RETURN-style paths and re-raised to Python."""

    def __init__(self, error_class: ErrorClass, message: str = ""):
        self.error_class = ErrorClass(error_class)
        super().__init__(f"{self.error_class.name}: {message}" if message
                         else self.error_class.name)


class ProcFailedError(MpiError):
    """A peer involved in the operation has failed (ULFM)."""

    def __init__(self, message: str = "", failed_ranks: tuple = ()):
        super().__init__(ErrorClass.ERR_PROC_FAILED, message)
        self.failed_ranks = failed_ranks


class RevokedError(MpiError):
    """The communicator has been revoked (ULFM)."""

    def __init__(self, message: str = ""):
        super().__init__(ErrorClass.ERR_REVOKED, message)


_user_classes: dict[int, str] = {}
_user_codes: dict[int, tuple[int, str]] = {}
_next_user = 100


def add_error_class(msg: str = "") -> int:
    """``MPI_Add_error_class``: allocate a user error class."""
    global _next_user
    cls = _next_user
    _next_user += 1
    _user_classes[cls] = msg or f"user error class {cls}"
    return cls


def add_error_code(error_class: int, msg: str = "") -> int:
    """``MPI_Add_error_code``: a code within a (user) class."""
    global _next_user
    code = _next_user
    _next_user += 1
    _user_codes[code] = (error_class, msg or f"user error code {code}")
    return code


def add_error_string(code: int, string: str) -> None:
    """``MPI_Add_error_string``."""
    if code in _user_classes:
        _user_classes[code] = string
    elif code in _user_codes:
        _user_codes[code] = (_user_codes[code][0], string)
    else:
        raise MpiError(ErrorClass.ERR_ARG, f"unknown error code {code}")


def error_string(error_class) -> str:
    code = int(error_class)
    if code in _user_classes:
        return _user_classes[code]
    if code in _user_codes:
        return _user_codes[code][1]
    return ErrorClass(error_class).name


def error_class_of(code) -> int:
    """``MPI_Error_class``: map a code back to its class."""
    c = int(code)
    if c in _user_codes:
        return _user_codes[c][0]
    return c
