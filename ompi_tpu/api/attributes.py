"""Attribute keyvals on comm/win/datatype objects (``ompi/attribute/``):
keyval create/free with copy & delete callbacks, get/set/delete."""
from __future__ import annotations

from typing import Any, Callable, Optional

from ompi_tpu.base.containers import PointerArray

KEYVAL_INVALID = -1


def _dup_fn(obj, keyval, extra, value):
    return True, value


def _null_copy_fn(obj, keyval, extra, value):
    return False, None


def _null_delete_fn(obj, keyval, value, extra):
    pass


_keyvals = PointerArray(lowest_free=1)


class _Keyval:
    def __init__(self, copy_fn, delete_fn, extra_state):
        self.copy_fn = copy_fn or _null_copy_fn
        self.delete_fn = delete_fn or _null_delete_fn
        self.extra_state = extra_state


def keyval_create(copy_fn: Optional[Callable] = None,
                  delete_fn: Optional[Callable] = None,
                  extra_state: Any = None) -> int:
    return _keyvals.add(_Keyval(copy_fn, delete_fn, extra_state))


def keyval_free(keyval: int) -> None:
    _keyvals.remove(keyval)


DUP_FN = _dup_fn
NULL_COPY_FN = _null_copy_fn
NULL_DELETE_FN = _null_delete_fn


class AttributeHost:
    """Mixin giving an object MPI attribute semantics."""

    def _attrs(self) -> dict:
        if not hasattr(self, "_attributes"):
            self._attributes: dict[int, Any] = {}
        return self._attributes

    def attr_put(self, keyval: int, value: Any) -> None:
        if _keyvals.get(keyval) is None:
            raise KeyError(f"invalid keyval {keyval}")
        self._attrs()[keyval] = value

    def attr_get(self, keyval: int) -> tuple[bool, Any]:
        a = self._attrs()
        if keyval in a:
            return True, a[keyval]
        return False, None

    def attr_delete(self, keyval: int) -> None:
        kv: _Keyval = _keyvals.get(keyval)
        a = self._attrs()
        if keyval in a:
            if kv is not None:
                kv.delete_fn(self, keyval, a[keyval], kv.extra_state)
            del a[keyval]

    def _attrs_copy_to(self, other: "AttributeHost") -> None:
        """Run copy callbacks on dup (``ompi_attr_copy_all``)."""
        for keyval, value in list(self._attrs().items()):
            kv: _Keyval = _keyvals.get(keyval)
            if kv is None:
                continue
            keep, newval = kv.copy_fn(self, keyval, kv.extra_state, value)
            if keep:
                other._attrs()[keyval] = newval

    def _attrs_delete_all(self) -> None:
        for keyval in list(self._attrs()):
            self.attr_delete(keyval)
