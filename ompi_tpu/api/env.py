"""Environment inquiry functions (``ompi/mpi/c/wtime.c``, ``get_version.c``,
``get_processor_name.c``, ``alloc_mem.c`` family)."""
from __future__ import annotations

import os
import socket
import time

import numpy as np

VERSION = (4, 0)              # MPI standard level the API tracks


def wtime() -> float:
    """``MPI_Wtime``: monotonic wall clock in seconds."""
    return time.perf_counter()


def wtick() -> float:
    """``MPI_Wtick``: the clock's resolution."""
    info = time.get_clock_info("perf_counter")
    return info.resolution


def get_processor_name() -> str:
    """``MPI_Get_processor_name``."""
    return socket.gethostname()


def get_version() -> tuple:
    """``MPI_Get_version``: (version, subversion) of the MPI level."""
    return VERSION


def get_library_version() -> str:
    """``MPI_Get_library_version``."""
    import ompi_tpu

    return f"ompi_tpu {ompi_tpu.__version__} (TPU-native, MPI-{VERSION[0]}" \
           f".{VERSION[1]} API surface)"


def alloc_mem(nbytes: int, info=None) -> np.ndarray:
    """``MPI_Alloc_mem``: a byte buffer suitable for RMA/sends.  The
    reference returns registered memory; XLA owns device allocation here,
    so host-side this is an aligned numpy buffer."""
    return np.zeros(int(nbytes), np.uint8)


def free_mem(buf) -> None:
    """``MPI_Free_mem`` (the GC owns it; exists for API parity)."""


_pcontrol_level = 1


def pcontrol(level: int = 1, *args) -> None:
    """``MPI_Pcontrol``: profiling-level hint.  The Python-layer tracers
    (monitoring components, PERUSE subscribers) are toggled by their own
    MCA vars; this records the application's requested level for them to
    consult (``ompi/mpi/c/pcontrol.c`` is likewise a no-op hook)."""
    global _pcontrol_level
    _pcontrol_level = int(level)


def pcontrol_level() -> int:
    return _pcontrol_level


def get_affinity() -> list:
    """``MPIX_Get_affinity`` (mpiext/affinity): the CPU set this process
    is bound to (empty when unbound / unsupported)."""
    try:
        return sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return []


def query_accelerator_support() -> bool:
    """``MPIX_Query_cuda_support`` analog: True when this process's
    initialized runtime is accelerator-backed (device-buffer collectives
    select; the TPU plays the reference's CUDA slot).  Meaningful after
    ``ompi_tpu.init()`` — like the reference macro, it reports the
    support already compiled/configured in, and deliberately does NOT
    initialize a backend as a side effect of a query."""
    from ompi_tpu.runtime import init as rt

    world = rt.get_world_if_initialized()
    if world is None or world.rte is None:
        return False
    if not world.rte.is_device_world:
        return False
    devs = getattr(world.rte, "devices", ())
    return any(getattr(d, "platform", "cpu") != "cpu" for d in devs)
