"""Reduction operators (``ompi/op/op.c`` + ``ompi/mca/op/`` framework).

Named MPI ops with host kernels (numpy — the VPU-analog of the reference's
AVX op component, ``ompi/mca/op/avx/op_avx_functions.c``) and their XLA
lowerings for the device collective path (``coll/xla``): each op carries the
jax reduction it lowers to inside ``shard_map`` (SUM → ``lax.psum``; MIN/MAX
→ ``lax.pmin``/``pmax``; others → all_gather + local fold).  User-defined ops
(``MPI_Op_create``) carry a commute flag that the coll decision ladder
consults (non-commutative ops are excluded from ring/Rabenseifner, reference
``coll_tuned_decision_fixed.c:77-80``).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError


class Op:
    def __init__(
        self,
        name: str,
        fn: Optional[Callable] = None,
        commute: bool = True,
        jax_reduce: Optional[str] = None,
        builtin: bool = False,
    ) -> None:
        self.name = name
        self._fn = fn
        self.commute = commute
        self.jax_reduce = jax_reduce  # "psum" | "pmax" | "pmin" | None
        self.builtin = builtin

    def __call__(self, invec, inoutvec, datatype=None):
        """inoutvec = invec (op) inoutvec — MPI argument order."""
        if self._fn is None:
            raise MpiError(ErrorClass.ERR_OP, f"{self.name} not callable")
        return self._fn(invec, inoutvec, datatype)

    def reduce_arrays(self, a: np.ndarray, b: np.ndarray,
                      datatype=None) -> np.ndarray:
        """Pure reduction of two operand arrays (coll algorithm library use)."""
        out = b.copy()
        self(a, out, datatype)
        return out

    def __repr__(self) -> str:
        return f"Op({self.name}, commute={self.commute})"


#: ufuncs the threads-framework pool can run as parallel native spans
_POOL_UFUNC = {np.add: "sum", np.multiply: "prod",
               np.maximum: "max", np.minimum: "min"}
_POOL_DTYPES = ("float32", "float64", "int32", "int64")
#: big host reductions fan out over the worker pool (op/avx discipline:
#: keep the reduction math at hardware speed — here, all memory
#: channels).  Gain scales with host cores/memory channels; measured
#: neutral (~1.0x) on a 1-core CI container, the win is on real
#: many-core TPU-host VMs
_POOL_REDUCE_MIN = 1 << 20


def _pool_reduce(np_fn, invec, inoutvec) -> bool:
    opname = _POOL_UFUNC.get(np_fn)
    if (opname is None or not isinstance(inoutvec, np.ndarray)
            or inoutvec.nbytes < _POOL_REDUCE_MIN
            or str(inoutvec.dtype) not in _POOL_DTYPES
            or invec.dtype != inoutvec.dtype
            or invec.shape != inoutvec.shape
            or not (invec.flags.c_contiguous
                    and inoutvec.flags.c_contiguous)):
        return False
    from ompi_tpu.mca.threads import base as threads_base

    pool = threads_base.get_pool()
    if not getattr(pool, "parallel_pack", False) or pool.size < 2:
        return False
    # commutative elementwise: acc = acc (op) src == invec (op) inoutvec
    pool.reduce(opname, inoutvec, invec).wait()
    return True


def _elementwise(np_fn):
    if isinstance(np_fn, np.ufunc):
        # write straight into inoutvec: the temp-then-copy form doubles
        # memory traffic, which is THE cost of a host reduction
        def fn(invec, inoutvec, datatype=None):
            if not _pool_reduce(np_fn, invec, inoutvec):
                np_fn(invec, inoutvec, out=inoutvec)
    else:
        def fn(invec, inoutvec, datatype=None):
            inoutvec[...] = np_fn(invec, inoutvec)
    return fn


def _logical(np_fn):
    def fn(invec, inoutvec, datatype=None):
        inoutvec[...] = np_fn(invec.astype(bool), inoutvec.astype(bool)) \
            .astype(inoutvec.dtype)
    return fn


def _loc_op(extremum):
    """MAXLOC/MINLOC on pair-type structured arrays (fields 'v' and 'i')."""
    def fn(invec, inoutvec, datatype=None):
        if invec.dtype.fields is None or "v" not in invec.dtype.fields:
            raise MpiError(ErrorClass.ERR_OP,
                           "MINLOC/MAXLOC need a pair datatype")
        a_v, b_v = invec["v"], inoutvec["v"]
        if extremum == "max":
            take_a = (a_v > b_v) | ((a_v == b_v) & (invec["i"] < inoutvec["i"]))
        else:
            take_a = (a_v < b_v) | ((a_v == b_v) & (invec["i"] < inoutvec["i"]))
        inoutvec["v"] = np.where(take_a, a_v, b_v)
        inoutvec["i"] = np.where(take_a, invec["i"], inoutvec["i"])
    return fn


def _replace(invec, inoutvec, datatype=None):
    inoutvec[...] = invec


def _no_op(invec, inoutvec, datatype=None):
    pass


SUM = Op("SUM", _elementwise(np.add), True, "psum", builtin=True)
PROD = Op("PROD", _elementwise(np.multiply), True, None, builtin=True)
MAX = Op("MAX", _elementwise(np.maximum), True, "pmax", builtin=True)
MIN = Op("MIN", _elementwise(np.minimum), True, "pmin", builtin=True)
LAND = Op("LAND", _logical(np.logical_and), True, None, builtin=True)
LOR = Op("LOR", _logical(np.logical_or), True, None, builtin=True)
LXOR = Op("LXOR", _logical(np.logical_xor), True, None, builtin=True)
BAND = Op("BAND", _elementwise(np.bitwise_and), True, None, builtin=True)
BOR = Op("BOR", _elementwise(np.bitwise_or), True, None, builtin=True)
BXOR = Op("BXOR", _elementwise(np.bitwise_xor), True, None, builtin=True)
MAXLOC = Op("MAXLOC", _loc_op("max"), True, None, builtin=True)
MINLOC = Op("MINLOC", _loc_op("min"), True, None, builtin=True)
REPLACE = Op("REPLACE", _replace, False, None, builtin=True)
NO_OP = Op("NO_OP", _no_op, False, None, builtin=True)

BUILTIN_OPS = {
    op.name: op
    for op in (SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR,
               MAXLOC, MINLOC, REPLACE, NO_OP)
}


def create(fn: Callable, commute: bool) -> Op:
    """``MPI_Op_create``: user function fn(invec, inoutvec, datatype)."""
    return Op(f"user_{id(fn):x}", fn, commute=commute)


def jax_stack_reduce(op: Op, dtype=None):
    """Fused device reduction of a (k, ...) stack along axis 0, if any
    op component provides one (pallas_vpu's ``reduce_stack`` on TPU);
    None otherwise.  Callers fall back to chained :func:`jax_fold`."""
    from ompi_tpu.mca.op import base as op_base

    if op.name not in BUILTIN_OPS:
        return None
    return op_base.select_stack(op.name, dtype)


def jax_fold(op: Op, dtype=None, fusable: bool = False):
    """A jax-traceable two-operand fold for device-side reductions.

    Used by coll/xla for ops without a native collective lowering (tree
    reduction over gathered shards) and by scan/exscan.  The kernel comes
    from the MCA ``op`` framework (``ompi_tpu/mca/op/``): on TPU the
    Pallas VPU component wins (the op/avx analog), elsewhere plain XLA —
    the reference's per-op function-table selection
    (``ompi/mca/op/base/op_base_op_select.c``).
    """
    from ompi_tpu.mca.op import base as op_base

    fn = op_base.select_fold(op.name, dtype, fusable=fusable)
    if fn is None:
        raise MpiError(ErrorClass.ERR_OP,
                       f"op {op.name} has no device lowering")
    return fn
