"""Request lifecycle: test/wait families, completion callbacks, cancellation,
persistent and generalized requests.

Re-design of ``/root/reference/ompi/request/request.h`` (the
``ompi_request_wait_completion`` spin at ``request.h:427`` becomes a progress
-driven wait loop) with the FT-aware completion semantics of ``req_ft.c``
(pending requests complete in error when a peer dies).
"""
from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.status import Status, UNDEFINED


class RequestState(enum.Enum):
    INACTIVE = "inactive"
    ACTIVE = "active"
    COMPLETE = "complete"
    CANCELLED = "cancelled"


#: otpu-verify contract — the request lifecycle automaton, machine-read
#: by the ``mpi-typestate`` static pass (``analysis/passes/typestate.py``
#: loads this dict from the AST; keep every value a literal).  Persistent
#: requests cycle inactive -> start -> active -> wait/test -> inactive and
#: end with free; Pready marks partitions on an ACTIVE partitioned SEND
#: request only; Parrived is observable on the receive side only.
_TYPESTATE = {
    "create_inactive": ["send_init", "recv_init", "psend_init",
                        "precv_init", "pallreduce_init"],
    "create_active": ["isend", "irecv"],
    "send_side": ["send_init", "psend_init", "isend", "pallreduce_init"],
    "partitioned": ["psend_init", "precv_init", "pallreduce_init"],
    "start": ["start"],
    "start_many": ["start_all", "startall"],
    "complete": ["wait", "test", "get_status", "on_complete"],
    "complete_many": ["waitall", "waitany", "waitsome", "testall",
                      "testany", "testsome"],
    "free": ["free"],
    "pready": ["pready", "pready_range", "pready_list"],
    "parrived": ["parrived", "parrived_range"],
}


def _progress() -> int:
    from ompi_tpu.runtime.progress import progress

    return progress()


#: Empty progress polls before yielding the core.  On an oversubscribed
#: host (more ranks than cores — the reference's ``mpi_yield_when_idle``
#: situation) a waiter that keeps spinning hogs its whole scheduler
#: quantum while the peer it waits on is runnable but descheduled: every
#: rendezvous round-trip then costs O(quantum) instead of O(µs).  Yielding
#: after a handful of empty polls costs ~1µs on an idle machine and turns
#: the oversubscribed pingpong from milliseconds into microseconds.
_YIELD_AFTER = 4
_SLEEP_AFTER = 64


def _idle_backoff(spins: int) -> None:
    """Escalating wait: spin -> sched_yield -> block on transport fds
    (the btl doorbell/socket set; wakes in ~10µs on message arrival)."""
    if spins >= _SLEEP_AFTER:
        from ompi_tpu.runtime.progress import idle_wait

        idle_wait(0.001)
    elif spins >= _YIELD_AFTER:
        time.sleep(0)          # bare yield: give the peer the core


class Request:
    """Base request; subclasses drive completion from the progress engine."""

    def __init__(self, persistent: bool = False):
        self.state = RequestState.INACTIVE if persistent else RequestState.ACTIVE
        self.persistent = persistent
        self.status = Status()
        self.error: Optional[MpiError] = None
        self._callbacks: list[Callable[["Request"], None]] = []
        self._lock = threading.Lock()

    # -- completion ------------------------------------------------------
    def on_complete(self, cb: Callable[["Request"], None]) -> None:
        fire = False
        with self._lock:
            if self.state in (RequestState.COMPLETE, RequestState.CANCELLED):
                fire = True
            else:
                self._callbacks.append(cb)
        if fire:
            cb(self)

    def complete(self, error: Optional[MpiError] = None) -> None:
        with self._lock:
            if self.state is RequestState.COMPLETE:
                return
            self.state = RequestState.COMPLETE
            self.error = error
            if error is not None:
                self.status.error = error.error_class
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    @property
    def complete_flag(self) -> bool:
        return self.state in (RequestState.COMPLETE, RequestState.CANCELLED)

    # -- MPI operations --------------------------------------------------
    def test(self) -> tuple[bool, Optional[Status]]:
        if self.persistent and self.state is RequestState.INACTIVE:
            return True, Status()    # MPI-3.1 §3.7.3: inactive → empty status
        if not self.complete_flag:
            _progress()
        if self.complete_flag:
            self._raise_if_error()
            return True, self.status
        return False, None

    def wait(self, timeout: Optional[float] = None) -> Status:
        """Spin in the progress engine until complete (``request.h:427``).

        An inactive persistent request returns immediately with the empty
        status (MPI-3.1 §3.7.3) instead of spinning forever."""
        if self.persistent and self.state is RequestState.INACTIVE:
            return Status()
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while not self.complete_flag:
            made = _progress()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("request wait timed out")
            if made == 0:
                spins += 1
                _idle_backoff(spins)
            else:
                spins = 0
        self._raise_if_error()
        return self.status

    def get_status(self) -> tuple[bool, Optional[Status]]:
        """``MPI_Request_get_status``: like test() but errors surface in
        ``status.error`` rather than raising."""
        try:
            return self.test()
        except MpiError:
            return True, self.status

    def cancel(self) -> None:
        with self._lock:
            if self.state is RequestState.ACTIVE and self._try_cancel():
                self.state = RequestState.CANCELLED
                self.status.set_cancelled(True)

    def _try_cancel(self) -> bool:  # subclass hook
        return False

    def start(self) -> None:
        """Restart a persistent request (``MPI_Start``)."""
        if not self.persistent:
            raise MpiError(ErrorClass.ERR_REQUEST, "not a persistent request")
        if self.state is RequestState.ACTIVE:
            raise MpiError(ErrorClass.ERR_REQUEST, "already active")
        self.state = RequestState.ACTIVE
        self.status = Status()
        self.error = None
        self._start()

    def _start(self) -> None:  # subclass hook
        raise MpiError(ErrorClass.ERR_REQUEST, "not startable")

    def free(self) -> None:
        self.state = RequestState.INACTIVE

    # -- MPI-4 partitioned communication (``mca/part``) ------------------
    # The partitioned request classes (part/persist PsendRequest /
    # PrecvRequest, the coll pcoll request) override the relevant side;
    # on any other request these calls are erroneous and must say so
    # loudly instead of silently accepting.
    def pready(self, partition) -> None:
        raise MpiError(ErrorClass.ERR_REQUEST,
                       "Pready on a non-partitioned request")

    def pready_range(self, partition_low: int, partition_high: int) -> None:
        """``MPI_Pready_range``: inclusive [low, high] like the standard."""
        for p in range(int(partition_low), int(partition_high) + 1):
            self.pready(p)

    def pready_list(self, partitions) -> None:
        """``MPI_Pready_list``."""
        for p in partitions:
            self.pready(p)

    def parrived(self, partition) -> bool:
        raise MpiError(ErrorClass.ERR_REQUEST,
                       "Parrived on a non-partitioned request")

    def parrived_range(self, partition_low: int,
                       partition_high: int) -> bool:
        """Have ALL partitions in the inclusive [low, high] range
        arrived?  (No standard analog — the serving KV-slab receiver
        uses it to test one sequence slot that maps onto a RUN of
        receiver partitions when send/recv partition counts differ.)"""
        return all(self.parrived(p)
                   for p in range(int(partition_low),
                                  int(partition_high) + 1))

    def _raise_if_error(self) -> None:
        if self.error is not None:
            raise self.error


class CompletedRequest(Request):
    """Immediately-complete request (empty ops, trivial sends)."""

    def __init__(self, status: Optional[Status] = None):
        super().__init__()
        if status is not None:
            self.status = status
        self.complete()


class PersistentP2P(Request):
    """``MPI_Send_init``/``MPI_Recv_init``: a reusable communication
    specification.  Each ``start()`` issues a fresh underlying pml
    request; completion (and the received status) is mirrored up.
    Inactive until the first start, like the reference
    (``ompi/request/request.h`` persistent lifecycle).  Start with
    :func:`start_all` (``MPI_Startall``)."""

    def __init__(self, issue) -> None:
        super().__init__(persistent=True)
        self._issue = issue
        self._inner: Optional[Request] = None

    @property
    def result(self):
        """The inner request's payload (persistent collectives return
        their output here, like CompletedRequest.result)."""
        return getattr(self._inner, "result", None)

    def _start(self) -> None:
        try:
            inner = self._issue()
        except MpiError as exc:
            # the issue path ran a whole algorithm (persistent
            # collectives) and failed: complete-in-error so wait() does
            # not spin forever and the request stays restartable, then
            # surface the error like the blocking call would
            self.complete(exc)
            raise
        self._inner = inner

        def mirror(r: Request) -> None:
            self.status = r.status
            self.complete(r.error)

        inner.on_complete(mirror)

    def _try_cancel(self) -> bool:
        if self._inner is None:
            return False
        self._inner.cancel()
        return self._inner.state is RequestState.CANCELLED


class GeneralizedRequest(Request):
    """``MPI_Grequest_start``: user-driven completion with query/free/cancel."""

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None):
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn

    def grequest_complete(self) -> None:
        if self._query_fn is not None:
            self._query_fn(self.status)
        self.complete()

    def _try_cancel(self) -> bool:
        if self._cancel_fn is not None:
            self._cancel_fn(False)
            return True
        return False

    def free(self) -> None:
        if self._free_fn is not None:
            self._free_fn()
        super().free()


# -- wait/test families (``ompi/request/req_wait.c`` / ``req_test.c``) ----

def waitall(requests: Sequence[Request],
            timeout: Optional[float] = None) -> list[Status]:
    errs = []
    stats = []
    for r in requests:
        try:
            stats.append(r.wait(timeout))
        except MpiError as e:
            errs.append(e)
            stats.append(r.status)
    if errs:
        raise MpiError(ErrorClass.ERR_IN_STATUS, f"{len(errs)} request(s) failed")
    return stats


def waitany(requests: Sequence[Request]) -> tuple[int, Status]:
    if not requests or all(r.state is RequestState.INACTIVE for r in requests):
        return UNDEFINED, Status()
    spins = 0
    while True:
        for i, r in enumerate(requests):
            if r.complete_flag:
                r._raise_if_error()
                return i, r.status
        made = _progress()
        spins = spins + 1 if made == 0 else 0
        _idle_backoff(spins)


def waitsome(requests: Sequence[Request]):
    """Returns ``(indices, statuses)``; ``(UNDEFINED, [])`` when the list
    holds no active request (outcount=MPI_UNDEFINED, MPI-3.1 §3.7.5)."""
    idx, _ = waitany(requests)
    if idx == UNDEFINED:
        return UNDEFINED, []
    out, stats = [], []
    for i, r in enumerate(requests):
        if r.complete_flag:
            r._raise_if_error()
            out.append(i)
            stats.append(r.status)
    return out, stats


def _inactive(r: Request) -> bool:
    """Inactive persistent requests don't participate in the wait/test
    families and count as trivially complete (MPI-3.1 §3.7.3/§3.7.5)."""
    return r.persistent and r.state is RequestState.INACTIVE


def testall(requests: Sequence[Request]) -> tuple[bool, Optional[list[Status]]]:
    _progress()
    if all(r.complete_flag or _inactive(r) for r in requests):
        out = []
        for r in requests:
            if _inactive(r):
                out.append(Status())
                continue
            r._raise_if_error()
            out.append(r.status)
        return True, out
    return False, None


def testany(requests: Sequence[Request]) -> tuple[bool, int, Optional[Status]]:
    _progress()
    active = False
    for i, r in enumerate(requests):
        if _inactive(r):
            continue
        active = True
        if r.complete_flag:
            r._raise_if_error()
            return True, i, r.status
    if not active:
        return True, UNDEFINED, Status()
    return False, UNDEFINED, None


def testsome(requests: Sequence[Request]):
    """Returns ``(indices, statuses)``; ``(UNDEFINED, [])`` when the list
    holds no active request (outcount=MPI_UNDEFINED, MPI-3.1 §3.7.5)."""
    _progress()
    if not requests or all(r.state is RequestState.INACTIVE
                           for r in requests):
        return UNDEFINED, []
    out, stats = [], []
    for i, r in enumerate(requests):
        if r.complete_flag:
            r._raise_if_error()
            out.append(i)
            stats.append(r.status)
    return out, stats


def start_all(requests: Iterable[Request]) -> None:
    """``MPI_Startall``."""
    for r in requests:
        r.start()


startall = start_all   # MPI spelling
